"""hvdrun elastic mode: --host-discovery-script switches the CLI into
ElasticDriver supervision (ref: horovodrun's elastic launch flags [V],
SURVEY.md §2.5 CLI row). Live multi-process test in the style of
tests/test_runner.py / test_elastic.py."""

import os
import sys

import pytest

from horovod_tpu.runner.launch import parse_args, run_commandline


def _clean_env(monkeypatch):
    for var in list(os.environ):
        if var.startswith("HOROVOD_"):
            monkeypatch.delenv(var, raising=False)


def test_elastic_flags_parse():
    args = parse_args(
        [
            "-np", "2", "--host-discovery-script", "/tmp/d.sh",
            "--min-np", "1", "--max-np", "4", "--reset-limit", "3",
            "--", "python", "train.py",
        ]
    )
    assert args.host_discovery_script == "/tmp/d.sh"
    assert args.min_np == 1 and args.max_np == 4
    assert args.reset_limit == 3
    assert args.command == ["python", "train.py"]


@pytest.mark.slow
def test_hvdrun_elastic_end_to_end(tmp_path, monkeypatch):
    """Full CLI path: discovery script -> ElasticDriver gang -> worker
    exits 0 -> hvdrun returns 0; runtime knobs reach the worker env."""
    _clean_env(monkeypatch)
    discovery = tmp_path / "discover.sh"
    discovery.write_text("#!/bin/sh\necho localhost:2\n")
    discovery.chmod(0o755)

    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os, sys\n"
        "assert os.environ.get('HOROVOD_ELASTIC') == '1'\n"
        "assert 'HOROVOD_RANK' in os.environ\n"
        "assert os.environ.get('HOROVOD_TIMELINE'), 'runtime knob lost'\n"
        "sys.exit(0)\n"
    )

    rc = run_commandline(
        [
            "-np", "2",
            "--host-discovery-script", str(discovery),
            "--timeline-filename", str(tmp_path / "tl.json"),
            "--placement", "per-slot",
            "--", sys.executable, str(worker),
        ]
    )
    assert rc == 0


def test_inconsistent_elastic_bounds_rejected(tmp_path):
    discovery = tmp_path / "d.sh"
    discovery.write_text("#!/bin/sh\necho localhost:2\n")
    discovery.chmod(0o755)
    with pytest.raises(SystemExit, match="inconsistent elastic bounds"):
        run_commandline(
            [
                "-np", "4", "--min-np", "4", "--max-np", "2",
                "--host-discovery-script", str(discovery),
                "--", "true",
            ]
        )
