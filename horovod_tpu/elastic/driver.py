"""The elastic driver: membership tracking, gang (re)launch, notification.

Rebuild of the reference's ElasticDriver (ref:
horovod/runner/elastic/driver.py + registration.py + rendezvous.py [V] —
SURVEY.md §2.5, §3.4). Same responsibilities: poll discovery on an
interval, compute slot assignments within [min_np, max_np], blacklist
hosts whose workers fail, re-key the rendezvous, notify live workers,
and collect exit codes.

TPU divergence (SURVEY.md §5.3): the world cannot be resized in place —
ICI topology is fixed per slice — so every membership change is a *gang
restart*: terminate the current processes, bump the rendezvous epoch,
relaunch on the new host set. Workers resume from their last committed
``State`` (state.py), which is exactly the reference's recovery path
after a HorovodInternalError; the only thing lost relative to the
reference is in-place continuation on *grow*, which TPU hardware cannot
express anyway.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..runner.hosts import HostInfo, SlotInfo, assign_slots
from ..runner.launch import _free_port, _is_local, worker_envs
from ..runner.rendezvous import RendezvousServer
from ..common.logging import get_logger

_log = get_logger("elastic")
from ..runner.secret import make_secret_key
from ..runner.service import BasicClient
from .discovery import HostDiscovery, HostManager


# Consecutive fresh-heartbeat flags before a rank's slice is DOWN-
# WEIGHTED (HOROVOD_REBALANCE) — deliberately below the quarantine
# threshold: shed work first, restart the gang only if the rank stays
# flagged past HOROVOD_STRAGGLER_QUARANTINE_POLLS.
_REBALANCE_STREAK = 2

# Expert-load entries whose published ts stops advancing age out of the
# driver's gauges after this many seconds of driver-monotonic time (a
# departed rank's last KV blob must not skew the fleet view forever).
_EXPERT_LOAD_STALE_S = 60.0


class SlotAssignment:
    """One epoch's worth of placement: which ranks on which hosts."""

    def __init__(self, epoch: int, slots: Sequence[SlotInfo]) -> None:
        self.epoch = epoch
        self.slots = list(slots)

    @property
    def world_size(self) -> int:
        return len(self.slots)

    @property
    def hostnames(self) -> List[str]:
        return sorted({s.hostname for s in self.slots})


class ElasticDriver:
    """Supervises an elastic job.

    Synchronous core + optional background monitor thread, so tests can
    drive every transition in-process with fake discovery — the
    reference's own test strategy (test_elastic_driver.py [V],
    SURVEY.md §4.2).
    """

    def __init__(
        self,
        discovery: HostDiscovery,
        command: Sequence[str],
        min_np: int,
        max_np: Optional[int] = None,
        slots_per_host: Optional[int] = None,
        discovery_interval: float = 1.0,
        placement: str = "auto",
        start_timeout: float = 600.0,
        output_filename: Optional[str] = None,
        reset_limit: Optional[int] = None,
        extra_env: Optional[Dict[str, str]] = None,
        ssh_port: Optional[int] = None,
        verbose: bool = False,
    ) -> None:
        self.host_manager = HostManager(discovery)
        self._command = list(command)
        self._min_np = min_np
        self._max_np = max_np or 2**31
        self._slots_per_host = slots_per_host
        self._interval = discovery_interval
        self._placement = placement
        self._start_timeout = start_timeout
        self._output_filename = output_filename
        self._reset_limit = reset_limit
        self._extra_env = dict(extra_env or {})
        self._ssh_port = ssh_port
        self._verbose = verbose
        self._epoch = 0
        self._resets = 0
        self._secret = make_secret_key()
        self._server: Optional[RendezvousServer] = None
        self._procs: List[subprocess.Popen] = []
        self._blocks: List[Dict[str, str]] = []
        self._assignment: Optional[SlotAssignment] = None
        self._last_gang: tuple = (None, [])  # survives _reset()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # Cross-process stall signal (stall_inspector.cc's "ranks
        # absent" report [V]): workers stamp heartbeat/<rank> into the
        # rendezvous KV (elastic/worker.py); the run loop relays those
        # stamps into this inspector, which warns/escalates on silence.
        from ..common.config import Config
        from ..common.stall_inspector import StallInspector

        _cfg = Config.from_env()
        self.stall_inspector = StallInspector(
            warning_seconds=_cfg.stall_warning_seconds,
            shutdown_seconds=_cfg.stall_shutdown_seconds,
            straggler_factor=_cfg.straggler_factor,
        )
        self._last_hb_poll = 0.0
        self._last_stragglers: tuple = ()
        # self-healing: a rank flagged a straggler for this many
        # CONSECUTIVE heartbeat polls gets its host quarantined
        # (blacklist + proactive gang restart); 0 = observe only
        self._quarantine_polls = _cfg.straggler_quarantine_polls
        self._quarantine_capacity_warned = False
        # divergence audit (audit.py): workers publish parameter-tree
        # digests into the rendezvous KV; the driver compares them and
        # quarantines replicas that disagree with the majority
        self._last_audit_poll = 0.0
        self._last_audit_step: Optional[int] = None
        # collective-schedule audit (analysis/sched_audit.py): workers
        # publish rolling schedule fingerprints beside the digests; a
        # rank whose compiled collective schedule diverges is flagged
        # (reason `sched_divergence`) BEFORE the mismatch manifests as
        # a collective hang the stall inspector would need minutes to
        # escalate on
        self._last_sched_step: Optional[int] = None
        # straggler-aware scheduling (HOROVOD_REBALANCE): instead of
        # only logging a flagged rank, publish micro-batch weights that
        # shift work away from slices whose step p50 STAYS flagged —
        # the soft remedy BELOW the quarantine threshold (rebalance at
        # streak >= _REBALANCE_STREAK, quarantine at >=
        # HOROVOD_STRAGGLER_QUARANTINE_POLLS)
        self._rebalance = _cfg.rebalance
        self._rebalance_weights: Dict[int, float] = {}
        # expert-load freshness ledger: rank -> (last ts seen, driver
        # monotonic stamp of the last ADVANCE) — see _poll_expert_loads
        self._expert_load_seen: Dict[int, tuple] = {}
        # serve-capacity freshness ledger, same contract — see
        # _poll_serve_capacity
        self._serve_cap_seen: Dict[int, tuple] = {}
        # warm standby (HOROVOD_WARM_STANDBY): hosts held OUT of the
        # gang, pre-initialized by elastic/standby.py warmers, swapped
        # in on quarantine/divergence restarts and serve saturation
        # instead of cold-starting fresh capacity
        self._warm_standby = _cfg.warm_standby
        self._standby_current: set = set()  # reserved this epoch
        self._standby_released: set = set()  # folded back into the pool
        self._standby_warmers: Dict[str, Optional[subprocess.Popen]] = {}
        self._standby_swapins = 0
        self._scaleup_reason: Optional[str] = None
        self._last_scaleup = 0.0
        # trace plane (common/tracing.py): one context per restart
        # CYCLE — quarantine / standby-swap / restart events between
        # two gang launches share it, so the assembled fleet view shows
        # the whole remediation as one connected trace
        self._cycle_tctx = None

    def _cycle_trace(self):
        from ..common import tracing as _tracing

        if self._cycle_tctx is None:
            self._cycle_tctx = _tracing.mint()
        return self._cycle_tctx

    # ---------------------------------------------------------- planning

    def compute_assignment(self, epoch: Optional[int] = None) -> Optional[SlotAssignment]:
        """Slot assignment for the current host set, or None when the
        available capacity is below min_np (ref: driver.py
        _update_host_assignments [V])."""
        hosts = self.host_manager.current_hosts()
        if self._slots_per_host is not None:
            hosts = [HostInfo(h.hostname, self._slots_per_host) for h in hosts]
        reserved = self._reserve_standbys(hosts)
        active = [h for h in hosts if h.hostname not in reserved]
        capacity = sum(h.slots for h in active)
        if capacity < self._min_np:
            return None
        self._standby_current = reserved
        np_ = min(capacity, self._max_np)
        return SlotAssignment(
            self._epoch if epoch is None else epoch,
            assign_slots(active, np_),
        )

    def _reserve_standbys(self, hosts) -> set:
        """Up to ``HOROVOD_WARM_STANDBY`` hosts held OUT of the
        assignment — only while the remaining capacity clears min_np (a
        warm standby is a luxury; a gang below min_np is an outage).
        Released hosts (swapped in by a restart or scale-up) are never
        re-reserved; existing reservations are kept stable so a warmer
        mid-staging is not churned away; new reservations come from the
        tail of the sorted host list (rank-0 placement stays put)."""
        if self._warm_standby <= 0:
            return set()
        by_name = {h.hostname: h for h in hosts}
        # stability first: existing reservations still in the pool
        candidates = [
            hn for hn in self._standby_warmers
            if hn in by_name and hn not in self._standby_released
        ]
        for hn in sorted(by_name, reverse=True):
            if hn not in candidates and hn not in self._standby_released:
                candidates.append(hn)
        capacity = sum(h.slots for h in hosts)
        reserved: set = set()
        for hn in candidates:
            if len(reserved) >= self._warm_standby:
                break
            slots = by_name[hn].slots
            if capacity - slots >= self._min_np:
                reserved.add(hn)
                capacity -= slots
        return reserved

    def handle_host_failure(self, hostname: str) -> None:
        """Blacklist + force re-plan (ref: blacklist on worker failure).
        With a warm standby held, the lost capacity is backfilled by
        releasing one standby into the pool — the restart that follows
        swaps it in instead of shrinking the world."""
        self.host_manager.blacklist(hostname)
        self._publish_dead_hosts()
        self._release_standby(f"host {hostname} failed")

    def _publish_dead_hosts(self) -> None:
        """Push the blacklist into the serve KV scope (dead-set
        channel, runner/rendezvous.py): the serving Router evicts a
        dead worker's announcement the moment the driver declares the
        host dead, instead of waiting out the announcement freshness
        window — failure detection feeding routing. Best-effort: a KV
        hiccup must never block the failure handling itself."""
        if self._server is None:
            return
        try:
            dead = self.host_manager.blacklisted
            with self._lock:
                ranks = sorted(
                    int(b["HOROVOD_RANK"]) for b in self._blocks
                    if b.get("HOROVOD_HOSTNAME") in dead
                )
            from ..runner.rendezvous import put_dead_hosts

            put_dead_hosts(self._server.store, dead, ranks=ranks)
        except Exception as e:  # noqa: BLE001 — observability, not control
            _log.debug("dead-host publication failed: %s", e)

    # ---------------------------------------------------------- gang ops

    def _rendezvous(self) -> RendezvousServer:
        if self._server is None:
            self._server = RendezvousServer(secret_key=self._secret)
            self._server.start()
        return self._server

    def _launch_gang(self, assignment: SlotAssignment) -> None:
        _log.info(
            "launching gang epoch=%d world=%d hosts=%s",
            assignment.epoch,
            assignment.world_size,
            ",".join(sorted(set(assignment.hostnames))),
        )
        server = self._rendezvous()
        from ..runner.rendezvous import (
            AUDIT_SCOPE,
            HEARTBEAT_SCOPE,
            SCHED_SCOPE,
        )

        self.stall_inspector.reset_heartbeats()
        try:
            server.store.drop_scope(HEARTBEAT_SCOPE)
            server.store.drop_scope(AUDIT_SCOPE)
            server.store.drop_scope(SCHED_SCOPE)
        except Exception:
            pass
        self._last_audit_step = None
        self._last_sched_step = None
        placement = self._placement
        if placement == "auto":
            placement = (
                "per-slot"
                if all(_is_local(h) for h in assignment.hostnames)
                else "per-host"
            )
        addr = "127.0.0.1" if all(
            _is_local(h) for h in assignment.hostnames
        ) else os.uname().nodename
        blocks = worker_envs(
            assignment.slots,
            placement,
            addr,
            server.port,
            _free_port(),
            self._secret.hex(),
            extra={
                **self._extra_env,  # CLI runtime knobs (hvdrun elastic)
                "HOROVOD_ELASTIC_EPOCH": str(assignment.epoch),
                "HOROVOD_ELASTIC": "1",
            },
        )
        procs: List[subprocess.Popen] = []
        for block in blocks:
            hostname = block["HOROVOD_HOSTNAME"]
            stdout = stderr = None
            if self._output_filename:
                os.makedirs(self._output_filename, exist_ok=True)
                tag = f"epoch.{assignment.epoch}.rank.{block['HOROVOD_RANK']}"
                stdout = open(
                    os.path.join(self._output_filename, tag + ".out"), "wb"
                )
                stderr = open(
                    os.path.join(self._output_filename, tag + ".err"), "wb"
                )
            if self._verbose:
                print(
                    f"[hvdrun-elastic] epoch {assignment.epoch} rank "
                    f"{block['HOROVOD_RANK']} on {hostname}: "
                    + " ".join(self._command),
                    file=sys.stderr,
                )
            if _is_local(hostname):
                env = dict(os.environ)
                env.update(block)
                cwd = os.getcwd()
                prior = env.get("PYTHONPATH")
                env["PYTHONPATH"] = (
                    cwd if not prior else cwd + os.pathsep + prior
                )
                procs.append(
                    subprocess.Popen(
                        self._command, env=env, stdout=stdout, stderr=stderr
                    )
                )
            else:
                # remote member of the gang: same ssh shape as the
                # non-elastic launcher (launch.py [V]); the HMAC secret
                # rides stdin, never the command line
                from ..runner.launch import _ssh_wrap

                cmd = _ssh_wrap(
                    hostname, self._ssh_port, block, self._command
                )
                proc = subprocess.Popen(
                    cmd, stdin=subprocess.PIPE, stdout=stdout,
                    stderr=stderr,
                )
                assert proc.stdin is not None
                proc.stdin.write(
                    (block.get("HOROVOD_SECRET_KEY", "") + "\n").encode()
                )
                proc.stdin.close()
                procs.append(proc)
        with self._lock:
            self._procs = procs
            self._blocks = blocks
            self._assignment = assignment
            self._last_gang = (
                assignment.epoch,
                [int(b["HOROVOD_RANK"]) for b in blocks],
            )
        self._sync_standby_warmers(assignment, addr, server.port)

    # ------------------------------------------------------ warm standby

    def _sync_standby_warmers(
        self, assignment: SlotAssignment, addr: str, kv_port: int
    ) -> None:
        """Reconcile warmer processes with the current reservation:
        launch a warmer (elastic/standby.py) on each newly reserved
        LOCAL host, reap warmers whose host left the reservation.
        Remote reserved hosts are announced-only (the operator runs the
        warmer there; the reservation itself still holds the capacity
        out of the gang)."""
        from ..common.metrics import registry as _metrics

        reserved = set(self._standby_current)
        for hn in list(self._standby_warmers):
            if hn not in reserved:
                proc = self._standby_warmers.pop(hn)
                if proc is not None and proc.poll() is None:
                    proc.terminate()
        for hn in sorted(reserved):
            proc = self._standby_warmers.get(hn)
            if proc is not None and proc.poll() is None:
                continue  # warmer already running
            launched = None
            if _is_local(hn):
                env = dict(os.environ)
                env.update(self._extra_env)
                env.update(
                    HOROVOD_GLOO_RENDEZVOUS_ADDR=addr,
                    HOROVOD_GLOO_RENDEZVOUS_PORT=str(kv_port),
                    HOROVOD_SECRET_KEY=self._secret.hex(),
                    HOROVOD_STANDBY_HOSTNAME=hn,
                    # the gang's world size: the warmer's preload must
                    # target the fingerprint of the world it would JOIN,
                    # not its own single-process view
                    HOROVOD_SIZE=str(assignment.world_size),
                )
                cwd = os.getcwd()
                prior = env.get("PYTHONPATH")
                env["PYTHONPATH"] = (
                    cwd if not prior else cwd + os.pathsep + prior
                )
                try:
                    launched = subprocess.Popen(
                        [
                            sys.executable, "-m",
                            "horovod_tpu.elastic.standby",
                        ],
                        env=env,
                    )
                except OSError:
                    _log.warning(
                        "standby warmer launch failed on %s", hn,
                        exc_info=True,
                    )
            else:
                _log.info(
                    "host %s reserved as warm standby (remote: warmer "
                    "not auto-launched)", hn,
                )
            self._standby_warmers[hn] = launched
            _log.info("warm standby reserved on %s", hn)
        _metrics.gauge("driver.standby.reserved", len(reserved))

    def standby_status(self) -> Dict[str, dict]:
        """``{hostname: announcement}`` of every standby the warmers
        have published (rendezvous ``standby`` scope) — the operator /
        test view of the announce → stage → armed lifecycle."""
        if self._server is None:
            return {}
        from ..runner.rendezvous import read_standbys

        try:
            return read_standbys(self._server.store)
        except Exception:
            return {}

    def _release_standby(self, reason: str) -> Optional[str]:
        """Swap-in: fold one reserved standby back into the discovery
        pool so the NEXT assignment includes it. Prefers an ``armed``
        host (staging done) over one still staging. Returns the
        released hostname, or None when no standby is held."""
        candidates = [
            hn for hn in sorted(self._standby_warmers)
            if hn not in self._standby_released
        ]
        if not candidates:
            return None
        status = self.standby_status()
        armed = [
            hn for hn in candidates
            if status.get(hn, {}).get("state") == "armed"
        ]
        hostname = (armed or candidates)[0]
        self._standby_released.add(hostname)
        self._standby_swapins += 1
        if self._server is not None:
            from ..runner.rendezvous import STANDBY_SCOPE

            try:  # tell the warmer to stand down and exit
                self._server.store.put(
                    STANDBY_SCOPE, f"release.{hostname}", b"1"
                )
            except Exception:
                pass
        from ..common.metrics import registry as _metrics

        _metrics.counter("driver.standby.swapins")
        _metrics.gauge(
            "driver.standby.reserved",
            len(candidates) - 1,
        )
        from ..common import tracing as _tracing

        sspan = _tracing.start_span(
            "elastic.standby_swap", self._cycle_trace(),
            host=hostname, reason=reason,
            armed=hostname in armed,
        )
        if sspan is not None:
            sspan.end()
        _log.info(
            "releasing warm standby %s into the gang (%s); swap-in #%d",
            hostname, reason, self._standby_swapins,
        )
        return hostname

    def _maybe_scale_up(self, per_role: Dict[str, dict]) -> None:
        """Router-observed serve saturation: a role with live workers
        and ZERO admission headroom (free slots AND free pages) while a
        standby is armed releases the standby and schedules a grow
        restart (reason ``serve scaleup``). Rate-limited to one
        scale-up per staleness window so one saturated poll cannot
        drain the whole standby pool."""
        if self._scaleup_reason is not None or not self._standby_warmers:
            return
        if time.monotonic() - self._last_scaleup < _EXPERT_LOAD_STALE_S:
            return
        saturated = [
            role
            for role, agg in per_role.items()
            if agg["workers"] > 0
            and agg["free_slots"] <= 0
            and agg["free_pages"] <= 0
        ]
        if not saturated:
            return
        released = self._release_standby(
            f"serve saturation: role(s) {','.join(sorted(saturated))}"
        )
        if released is None:
            return
        self._last_scaleup = time.monotonic()
        self._scaleup_reason = (
            f"serve scaleup: standby {released} absorbs saturated "
            f"role(s) {','.join(sorted(saturated))}"
        )

    def _terminate_gang(self, grace: float = 10.0) -> None:
        with self._lock:
            procs = list(self._procs)
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + grace
        for p in procs:
            timeout = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)

    def _notify_workers(self, message_type: str) -> None:
        """Tell every live worker the membership changed (ref:
        WorkerNotificationService HTTP ping [V]). Worker addresses come
        from the rendezvous KV, where each notification manager
        registers itself."""
        from ..common.retry import RetryPolicy

        server = self._rendezvous()
        scope = f"workers.{self._epoch}"
        # short, bounded policy: notification is best-effort fan-out —
        # retry a flaky worker endpoint twice, but never let one dead
        # peer stall the notify sweep (its circuit opens after repeated
        # exhaustions and later sweeps skip it in one fast error)
        retry = RetryPolicy.from_env(
            "driver.notify", attempts=2, deadline_s=10.0
        )
        for key in server.store.keys(scope):
            value = server.store.get(scope, key)
            if value is None:
                continue
            host, _, port = value.decode().partition(":")
            try:
                BasicClient(
                    host, int(port), self._secret, timeout=5, retry=retry
                ).request({"type": message_type, "epoch": self._epoch})
            except OSError:
                # worker already gone (incl. RetryError/CircuitOpen
                # after exhaustion); its exit will be collected
                pass

    # ---------------------------------------------------------- main loop

    def _poll_gang(self) -> Optional[int]:
        """Collect exits. Returns an overall exit code when the gang is
        done (0 only if ALL workers exited 0), or None while running.
        Worker failure blacklists its host and triggers a reset."""
        with self._lock:
            procs = list(self._procs)
            blocks = list(self._blocks)
        if not procs:
            return None
        codes = [p.poll() for p in procs]
        failed = [
            (blocks[i]["HOROVOD_HOSTNAME"], rc)
            for i, rc in enumerate(codes)
            if rc not in (None, 0)
        ]
        if failed:
            for hostname, _ in failed:
                self.handle_host_failure(hostname)
            return failed[0][1]
        if all(rc == 0 for rc in codes):
            return 0
        return None

    def run(self) -> int:
        """Supervise until success, stop(), or capacity exhaustion.
        Returns the job's exit code."""
        last_refresh = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            restart_reason = self._poll_heartbeats(now) or self._poll_audit(
                now
            )
            if restart_reason:
                self._terminate_gang()
                if not self._reset(reason=restart_reason):
                    return 1
                continue
            if now - last_refresh >= self._interval:
                changed = self.host_manager.refresh()
                last_refresh = now
                if changed and self._assignment is not None:
                    # Membership changed under a live gang: tell workers
                    # (they commit + exit for re-launch), then restart.
                    _log.info("host membership changed; restarting gang")
                    self._notify_workers("hosts_updated")
                    self._terminate_gang()
                    if not self._reset(reason="membership change"):
                        return 1
                    continue
            if self._assignment is None:
                new = self.compute_assignment()
                if new is not None:
                    self._launch_gang(new)
                elif not self._wait_for_capacity(last_refresh):
                    return 1
                continue
            rc = self._poll_gang()
            if rc == 0:
                return 0
            if rc is not None:
                self._terminate_gang()
                if not self._reset(reason=f"worker failed rc={rc}"):
                    return rc
            time.sleep(0.05)
        self._terminate_gang()
        return 0

    def _poll_heartbeats(self, now: float) -> Optional[str]:
        """Relay worker heartbeats from the rendezvous KV into the
        stall inspector (rate-limited to once per discovery interval).
        Returns a restart *reason* when the gang should be proactively
        torn down — either the inspector escalated past
        HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, or the straggler ledger
        held a rank flagged for ``HOROVOD_STRAGGLER_QUARANTINE_POLLS``
        consecutive polls and its host got quarantined. None while the
        gang looks healthy; the caller owns the actual restart."""
        if self._server is None or now - self._last_hb_poll < self._interval:
            return None
        self._last_hb_poll = now
        from ..common.basics import HorovodInternalError
        from ..runner.rendezvous import read_heartbeat_stats

        try:
            heartbeats = read_heartbeat_stats(self._server.store)
        except Exception:
            _log.debug("heartbeat poll failed", exc_info=True)
            return None
        deferred_max = 0.0
        for rank, payload in heartbeats.items():
            self.stall_inspector.record_heartbeat(
                rank,
                payload["ts"],
                step=payload.get("step"),
                step_ms_p50=payload.get("step_ms_p50"),
                last_step_ts=payload.get("last_step_ts"),
            )
            deferred_max = max(
                deferred_max,
                float(payload.get("local_sgd_rounds_deferred", 0.0)),
            )
        if deferred_max > 0.0:
            # local-SGD deferral ledger (piggybacked on the heartbeat):
            # workers whose sync rounds keep getting pushed out are
            # training on a degraded DCN — visible in the gang view
            # WITHOUT tripping the straggler/stall machinery (their
            # beats are fresh and their local steps are full speed)
            from ..common.metrics import registry as _metrics

            _metrics.gauge(
                "driver.local_sgd.rounds_deferred", deferred_max
            )
        try:
            # check() publishes stall.pending / stall.stale_ranks /
            # stall.straggler.* through the metrics registry, so the
            # driver's /metrics or JSON-lines sink carries the gang view
            self.stall_inspector.check()
        except HorovodInternalError as e:
            # NOT swallowed: silence past the shutdown threshold is a
            # worker failure; escalate to the gang-restart path.
            _log.error("stall escalation: %s", e)
            return "worker heartbeat silence"
        stragglers = tuple(self.stall_inspector.straggler_ranks())
        if stragglers != self._last_stragglers:
            # log on CHANGE only (check() already warns once per rank):
            # the driver loop polls every interval and must not spam
            if stragglers:
                _log.warning(
                    "straggler ranks (slow, not silent): %s",
                    ",".join(map(str, stragglers)),
                )
            elif self._last_stragglers:
                _log.info("straggler ranks recovered")
            self._last_stragglers = stragglers
        self._maybe_rebalance()
        self._poll_expert_loads()
        self._poll_serve_capacity()
        reason = self._maybe_quarantine()
        if reason is not None:
            return reason
        # serve-saturation scale-up queued by _maybe_scale_up: restart
        # the gang with the released standby folded in (grow restart)
        reason, self._scaleup_reason = self._scaleup_reason, None
        return reason

    def _poll_expert_loads(self) -> None:
        """Aggregate the gang's published expert-load summaries (PR 12,
        rendezvous EXPERT_LOAD_SCOPE — the rebalance plumbing's
        expert-heat sibling) into driver gauges: the fleet-summed
        per-expert histogram's imbalance (hottest / mean kept tokens)
        and the aggregate overflow-drop rate. Observability only — the
        SOFT remedy for expert heat is the capacity autotuner on the
        workers; these gauges are how an operator (and the flight
        recorder) see it working. Best-effort: a malformed or absent
        ledger is silence, never a driver fault.

        Staleness follows the heartbeat lesson: a rank's entry counts
        only while its ``ts`` keeps ADVANCING (judged on the driver's
        monotonic clock, so cross-host wall skew cannot drop a live
        rank) — a departed rank's last KV blob stops advancing and
        ages out of the gauges instead of skewing them forever."""
        if self._server is None:
            return
        from ..runner.rendezvous import read_expert_loads

        try:
            loads = read_expert_loads(self._server.store)
        except Exception:
            return
        if not loads:
            return
        import time as _time

        now = _time.monotonic()
        fresh = {}
        for rank, payload in loads.items():
            ts = float(payload.get("ts", 0.0))
            prev = self._expert_load_seen.get(rank)
            if prev is None or ts > prev[0]:
                self._expert_load_seen[rank] = (ts, now)
                fresh[rank] = payload
            elif now - prev[1] <= _EXPERT_LOAD_STALE_S:
                fresh[rank] = payload
        # forget ranks whose blobs vanished (scope dropped on restart)
        for rank in list(self._expert_load_seen):
            if rank not in loads:
                del self._expert_load_seen[rank]
        loads = fresh
        if not loads:
            return
        hist: dict = {}
        dropped = total = 0.0
        for payload in loads.values():
            for i, t in enumerate(payload.get("expert_tokens", ())):
                hist[i] = hist.get(i, 0.0) + float(t)
            dropped += float(payload.get("dropped", 0.0))
            total += float(payload.get("total", 0.0))
        if not hist or total <= 0:
            return
        kept = sum(hist.values())
        mean = kept / len(hist) if kept > 0 else 0.0
        from ..common.metrics import registry as _metrics

        _metrics.gauge("driver.expert_load.ranks", len(loads))
        _metrics.gauge(
            "driver.expert_load.imbalance",
            max(hist.values()) / mean if mean > 0 else 1.0,
        )
        _metrics.gauge("driver.expert_load.drop_rate", dropped / total)

    def _poll_serve_capacity(self) -> None:
        """Aggregate the serving fleet's capacity announcements
        (serving/frontend.py, rendezvous scope ``serve``) into per-ROLE
        driver gauges — the disaggregated fleet's operator view: how
        many prefill vs decode workers are live, and how much admission
        headroom (slots / pages) each side of the wire has left. An
        empty decode side with a busy prefill side is the signature of
        a fleet about to fall back wholesale
        (``serve.transfer_fallbacks`` on the workers). Best-effort and
        staleness-guarded exactly like :meth:`_poll_expert_loads`:
        entries count while their ts ADVANCES on the driver's clock.
        Blobs with no ``role`` field (old workers mid-rollout) count as
        ``unified`` — the Router's parsing rule, applied fleet-wide."""
        if self._server is None:
            return
        try:
            from ..serving.frontend import read_announcements
            from ..serving.kv_transfer import worker_role

            anns = read_announcements(self._server.store)
        except Exception:
            return
        if not anns:
            return
        import time as _time

        now = _time.monotonic()
        fresh = {}
        for rank, ann in anns.items():
            ts = float(ann.get("ts", 0.0))
            prev = self._serve_cap_seen.get(rank)
            if prev is None or ts > prev[0]:
                self._serve_cap_seen[rank] = (ts, now)
                fresh[rank] = ann
            elif now - prev[1] <= _EXPERT_LOAD_STALE_S:
                fresh[rank] = ann
        for rank in list(self._serve_cap_seen):
            if rank not in anns:
                del self._serve_cap_seen[rank]
        if not fresh:
            return
        per_role: dict = {}
        for ann in fresh.values():
            agg = per_role.setdefault(
                worker_role(ann),
                {"workers": 0.0, "free_slots": 0.0, "free_pages": 0.0},
            )
            agg["workers"] += 1.0
            if not ann.get("draining"):
                agg["free_slots"] += float(ann.get("free_slots", 0))
                agg["free_pages"] += float(ann.get("free_pages", 0))
        from ..common.metrics import registry as _metrics

        for role, agg in per_role.items():
            for key, val in agg.items():
                _metrics.gauge(f"driver.serve.{role}.{key}", val)
        self._maybe_scale_up(per_role)

    def _maybe_rebalance(self) -> None:
        """Consume the straggler ledger as a SCHEDULING signal
        (HOROVOD_REBALANCE, ROADMAP item 3): ranks whose step p50 has
        stayed flagged for ``_REBALANCE_STREAK`` consecutive fresh
        heartbeats get a micro-batch weight of ``gang-median-p50 /
        their-p50`` (clamped to [0.25, 1.0]); everyone else 1.0. The
        map is published into the rendezvous KV on CHANGE only —
        workers read it via ``hvd.elastic.rebalance_weight()`` and
        scale their local micro-batch, so a persistently slow slice
        sheds work instead of gating every step, without the cost of a
        gang restart (the quarantine path remains the hard remedy)."""
        if not self._rebalance or self._server is None:
            return
        import statistics as _stats

        streaks = self.stall_inspector.straggler_streaks()
        hb = self.stall_inspector.heartbeat_stats()
        p50s = {
            r: s["step_ms_p50"]
            for r, s in hb.items()
            if s.get("step_ms_p50", 0) > 0
        }
        weights = {r: 1.0 for r in hb}
        if len(p50s) >= 2:
            median = _stats.median(p50s.values())
            for r, n in streaks.items():
                if n >= _REBALANCE_STREAK and p50s.get(r, 0) > 0 and median > 0:
                    w = max(0.25, min(1.0, median / p50s[r]))
                    weights[r] = round(w, 2)
        down_now = any(w < 1.0 for w in weights.values())
        down_before = any(
            w < 1.0 for w in self._rebalance_weights.values()
        )
        if not down_now and not down_before:
            return  # nothing to say: the gang never left parity
        if weights == self._rebalance_weights:
            return
        from ..common.metrics import registry as _metrics
        from ..runner.rendezvous import put_rebalance_weights

        try:
            put_rebalance_weights(
                self._server.store, weights, epoch=self._epoch
            )
        except Exception:
            _log.debug("rebalance publish failed", exc_info=True)
            return
        self._rebalance_weights = dict(weights)
        slowed = sorted(r for r, w in weights.items() if w < 1.0)
        _metrics.gauge("driver.rebalance.active", len(slowed))
        _metrics.counter("driver.rebalance.updates")
        if slowed:
            _log.warning(
                "rebalancing micro-batch weights away from straggling "
                "rank(s) %s: %s",
                ",".join(map(str, slowed)),
                ",".join(f"{r}={weights[r]}" for r in slowed),
            )
        else:
            _log.info("straggler rebalance cleared: all weights 1.0")

    def _maybe_quarantine(self) -> Optional[str]:
        """Self-healing half of ROADMAP Open item 3: consume the
        straggler ledger instead of only logging it. A rank flagged for
        K CONSECUTIVE polls (hysteresis — one noisy poll is not a
        scheduling signal) quarantines its host through the existing
        blacklist machinery and returns a restart reason, so the gang
        relaunches WITHOUT the slow host. Skipped — with a one-time
        warning — when losing those hosts would drop capacity below
        min_np: a slow gang beats no gang."""
        if self._quarantine_polls <= 0:
            return None
        ranks = self.stall_inspector.quarantine_candidates(
            self._quarantine_polls
        )
        if not ranks:
            return None
        hosts = self._hosts_of_ranks(ranks)
        if not hosts:
            return None
        if not self._try_blacklist(hosts, "straggler quarantine"):
            return None
        _log.warning(
            "quarantining straggler host(s) %s (ranks %s flagged for "
            "%d consecutive polls); restarting gang without them",
            ",".join(hosts), ",".join(map(str, ranks)),
            self._quarantine_polls,
        )
        return (
            f"straggler quarantine: hosts {','.join(hosts)} "
            f"(ranks {','.join(map(str, ranks))})"
        )

    def _hosts_of_ranks(self, ranks) -> List[str]:
        """Hostnames currently running the given ranks (empty when the
        gang layout no longer knows them)."""
        with self._lock:
            rank_to_host = {
                int(b["HOROVOD_RANK"]): b["HOROVOD_HOSTNAME"]
                for b in self._blocks
            }
        return sorted(
            {rank_to_host[r] for r in ranks if r in rank_to_host}
        )

    def _try_blacklist(self, hosts, why: str) -> bool:
        """Shared quarantine gate (stragglers AND divergence): refuse —
        with a one-time warning — when losing ``hosts`` would drop
        capacity below min_np; otherwise blacklist them and count
        ``driver.quarantined_hosts``."""
        hosts_info = self.host_manager.current_hosts()
        slots = {
            h.hostname: (
                self._slots_per_host
                if self._slots_per_host is not None
                else h.slots
            )
            for h in hosts_info
        }
        remaining = sum(
            s for hn, s in slots.items() if hn not in hosts
        )
        if remaining < self._min_np:
            if not self._quarantine_capacity_warned:
                self._quarantine_capacity_warned = True
                _log.warning(
                    "%s of %s would drop capacity to %d (< min_np=%d); "
                    "keeping the host(s)",
                    why, ",".join(hosts), remaining, self._min_np,
                )
            return False
        from ..common.metrics import registry as _metrics

        from ..common import tracing as _tracing

        for hostname in hosts:
            self.host_manager.blacklist(hostname)
            _metrics.counter("driver.quarantined_hosts")
            qspan = _tracing.start_span(
                "elastic.quarantine", self._cycle_trace(),
                host=hostname, reason=why,
            )
            if qspan is not None:
                qspan.end()
            self._release_standby(f"{why}: {hostname}")
        self._publish_dead_hosts()
        return True

    def _poll_audit(self, now: float) -> Optional[str]:
        """Divergence detection, both halves of the audit plane once
        per discovery interval: parameter digests (audit.py) and
        collective-schedule fingerprints (analysis/sched_audit.py). A
        replica disagreeing with the majority gets its host
        quarantined and the gang restarts (reason ``divergence`` /
        ``sched_divergence``) — the restore re-replicates state from
        the root, which repairs the divergence even when the capacity
        guard keeps the host. The schedule half fires BEFORE a
        mismatched collective sequence can hang: the divergent rank is
        flagged at its next audit publish, not after the stall
        inspector's heartbeat-silence window."""
        if self._server is None or now - self._last_audit_poll < self._interval:
            return None
        self._last_audit_poll = now
        sched_reason = self._check_sched_divergence()
        if sched_reason:
            return sched_reason
        from ..audit import find_divergent
        from ..runner.rendezvous import read_audit_digests

        try:
            digests = read_audit_digests(self._server.store)
        except Exception:
            _log.debug("audit poll failed", exc_info=True)
            return None
        found = find_divergent(digests)
        if found is None:
            return None
        step, bad_ranks = found
        if step == self._last_audit_step:
            return None  # this round was already judged
        self._last_audit_step = step
        from ..common.metrics import registry as _metrics

        _metrics.counter("driver.divergence_restarts")
        hosts = self._hosts_of_ranks(bad_ranks)
        quarantined = hosts and self._try_blacklist(
            hosts, "divergence quarantine"
        )
        _log.error(
            "replica divergence at audit step %d: ranks %s disagree "
            "with the gang majority%s; restarting gang",
            step, ",".join(map(str, bad_ranks)),
            (
                f" (hosts {','.join(hosts)} quarantined)"
                if quarantined
                else " (hosts kept: capacity guard — restore re-syncs)"
            ),
        )
        return (
            f"divergence: ranks {','.join(map(str, bad_ranks))} at "
            f"audit step {step}"
        )

    def _check_sched_divergence(self) -> Optional[str]:
        """The schedule half of :meth:`_poll_audit`: compare the
        gang's published collective-schedule fingerprints at the
        newest quorum step (majority fingerprint wins, the
        parameter-digest arbitration reused). A divergent rank's host
        is quarantined through the shared blacklist gate and the gang
        restarts with reason ``sched_divergence`` — logging the FIRST
        divergent dispatch index recovered from the published rings,
        so the postmortem starts at the exact dispatch."""
        from ..analysis import sched_audit as _sched
        from ..runner.rendezvous import read_sched_fingerprints

        try:
            entries = read_sched_fingerprints(self._server.store)
        except Exception:
            _log.debug("sched audit poll failed", exc_info=True)
            return None
        found = _sched.find_divergent(entries)
        if found is None:
            return None
        step, bad_ranks = found
        if step == self._last_sched_step:
            return None  # this round was already judged
        self._last_sched_step = step
        from ..common.metrics import registry as _metrics

        _metrics.counter("driver.sched_divergence_restarts")
        good_ranks = sorted(
            r
            for r in entries
            if r not in bad_ranks
            and isinstance(entries[r], dict)
            and entries[r].get("step") == step
        )
        first_idx = None
        if good_ranks and bad_ranks:
            first_idx = _sched.first_divergent_index(
                entries[bad_ranks[0]], entries[good_ranks[0]]
            )
        counts = {
            r: entries[r].get("dispatches")
            for r in sorted(entries)
            if isinstance(entries[r], dict)
        }
        hosts = self._hosts_of_ranks(bad_ranks)
        quarantined = hosts and self._try_blacklist(
            hosts, "sched divergence quarantine"
        )
        _log.error(
            "collective-schedule divergence at audit step %d: ranks %s "
            "disagree with the gang's majority fingerprint (first "
            "divergent dispatch %s; dispatch counts %s)%s; restarting "
            "gang before the mismatched schedule can hang a collective",
            step, ",".join(map(str, bad_ranks)),
            ("#%d" % first_idx) if first_idx is not None else "outside ring",
            counts,
            (
                f" (hosts {','.join(hosts)} quarantined)"
                if quarantined
                else " (hosts kept: capacity guard — restart re-syncs)"
            ),
        )
        return (
            f"sched_divergence: ranks {','.join(map(str, bad_ranks))} at "
            f"audit step {step}"
            + (
                f" (first divergent dispatch #{first_idx})"
                if first_idx is not None
                else ""
            )
        )

    def _reset(self, reason: str) -> bool:
        """Bump epoch and clear the assignment so the loop relaunches.
        False when the reset budget is exhausted (HOROVOD_ELASTIC
        reset_limit parity [V])."""
        self._resets += 1
        if self._reset_limit is not None and self._resets > self._reset_limit:
            _log.error(
                "reset limit %s exhausted (%s)", self._reset_limit, reason
            )
            return False
        _log.info("gang reset #%d: %s", self._resets, reason)
        from ..common.metrics import registry as _metrics

        _metrics.counter("driver.gang_restarts")
        self._epoch += 1
        _metrics.gauge("driver.epoch", self._epoch)
        # the restart is the cycle trace's ROOT record — quarantine and
        # standby-swap spans emitted since the last launch parent here;
        # the context rotates so the next remediation is its own trace
        from ..common import tracing as _tracing

        rspan = _tracing.root_span(
            "elastic.restart", self._cycle_tctx,
            reason=reason, epoch=self._epoch,
            resets=self._resets,
            warm=bool(self._standby_released),
        )
        if rspan is not None:
            rspan.end()
        self._cycle_tctx = None
        # the restart clock: the NEXT epoch's workers read this stamp
        # at init and publish elastic.restart_ms / serve.scaleup_ms —
        # the telemetry that shows a warm swap-in beating a cold start
        if self._server is not None:
            from ..runner.rendezvous import put_restart_stamp

            try:
                put_restart_stamp(
                    self._server.store,
                    epoch=self._epoch,
                    reason=reason,
                    warm=bool(self._standby_released),
                    kind=(
                        "scaleup" if "scaleup" in reason else "restart"
                    ),
                )
            except Exception:
                pass
        with self._lock:
            self._assignment = None
            self._procs = []
            self._blocks = []
        return True

    def _wait_for_capacity(self, last_refresh: float) -> bool:
        """Below min_np: keep polling discovery up to start_timeout."""
        deadline = time.monotonic() + self._start_timeout
        while not self._stop.is_set() and time.monotonic() < deadline:
            time.sleep(self._interval)
            self.host_manager.refresh()
            if self.compute_assignment() is not None:
                return True
        return self.compute_assignment() is not None

    def gang_info(self):
        """``(epoch, lead_ranks)`` of the LAST LAUNCHED gang — what an
        executor needs to collect per-rank results from the right epoch
        directory (per-host placement launches one process per host, so
        result files exist at LEAD ranks only). Survives _reset(): after
        a failed gang drains capacity, the failed ranks' error pickles
        are still the best diagnostic and must stay reachable."""
        with self._lock:
            return self._last_gang

    def stop(self) -> None:
        self._stop.set()

    def shutdown(self) -> None:
        self.stop()
        self._terminate_gang()
        for hn, proc in list(self._standby_warmers.items()):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
        self._standby_warmers.clear()
        if self._server is not None:
            self._server.stop()
            self._server = None
