"""Packaging contract (ref: setup.py + CMake, SURVEY.md §2.7): the
project is pip-installable with working hvdrun/horovodrun entry points.
The full `pip install -e . && hvdrun -np 2` transcript is exercised in
CI-style by the runner tests; here we pin the declared contract."""

import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    import tomllib
except ImportError:  # py<3.11
    tomllib = None

import pytest


@pytest.fixture(scope="module")
def pyproject():
    if tomllib is None:
        pytest.skip("tomllib unavailable")
    with open(os.path.join(_REPO, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)


def test_console_scripts_declared(pyproject):
    scripts = pyproject["project"]["scripts"]
    assert scripts["hvdrun"] == "horovod_tpu.runner.launch:main"
    assert scripts["horovodrun"] == "horovod_tpu.runner.launch:main"


def test_entry_point_importable(pyproject):
    """The declared entry point must resolve to a callable."""
    from horovod_tpu.runner.launch import main

    assert callable(main)


def test_native_so_in_package_data(pyproject):
    data = pyproject["tool"]["setuptools"]["package-data"]
    assert "*.so" in data["horovod_tpu._native"]


def test_version_coherent(pyproject):
    import horovod_tpu

    # Major.minor tracked in both places; pyproject is the release
    # authority, module version must not be ahead of it.
    assert pyproject["project"]["version"].split(".")[0] == (
        horovod_tpu.__version__.split(".")[0]
    )
