"""Language-model pretraining throughput — BERT-large / GPT-2-medium.

The two tracked LM configs from BASELINE.json [V]: BERT-large with
Adasum gradient combination (config #3) and GPT-2 medium with
hierarchical allreduce (config #4). Prints ONE JSON line:
  {"metric": "<model>_samples_per_sec", "value": N, "unit": "samples/s"}

Env: BENCH_MODEL=bert_large|gpt2_medium (default bert_large),
BENCH_BATCH (default 8), BENCH_SEQ (default: model max 512/1024 capped
at 512), BENCH_ITERS (default 10), BENCH_PLATFORM=cpu + tiny model for
the harness smoke test (BENCH_TINY=1).
"""

import json
import os
import time
from functools import partial

import numpy as np


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import Transformer, TransformerConfig

    model_name = os.environ.get("BENCH_MODEL", "bert_large")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    iters = int(os.environ.get("BENCH_ITERS", "10"))

    hvd.init()
    mesh = hvd.mesh()

    if os.environ.get("BENCH_TINY"):
        cfg = TransformerConfig.tiny(causal=(model_name == "gpt2_medium"))
    elif model_name == "gpt2_medium":
        cfg = TransformerConfig.gpt2_medium()
    else:
        cfg = TransformerConfig.bert_large()
    # remat trades FLOPs for memory; at bench batch sizes the model may
    # fit without it, making it pure recompute overhead — BENCH_REMAT=0
    # measures that. Default stays on (the large-model-safe setting).
    remat = not os.environ.get("BENCH_TINY") and os.environ.get(
        "BENCH_REMAT", "1"
    ) not in ("0", "false", "off")
    cfg = dataclasses_replace(cfg, remat=remat)
    if os.environ.get("BENCH_FLASH", "auto") in ("0", "false", "off"):
        # escape hatch: dense attention (e.g. if the Pallas kernel
        # misbehaves on a new libtpu)
        cfg = dataclasses_replace(cfg, flash_attention=False)
    if os.environ.get("BENCH_HEAD") == "fp32":
        # A/B escape hatch for the mixed-precision LM head default
        cfg = dataclasses_replace(cfg, head_mixed_precision=False)
    if os.environ.get("BENCH_KV_HEADS"):
        # grouped-query attention A/B: fewer KV heads (must divide the
        # model's head count); the kernels read shared KV rows directly
        cfg = dataclasses_replace(
            cfg, num_kv_heads=int(os.environ["BENCH_KV_HEADS"])
        )
    if os.environ.get("BENCH_FLASH_BLOCK"):
        bq = int(os.environ["BENCH_FLASH_BLOCK"])
        if bq < 8 or (bq & (bq - 1)) != 0:
            raise SystemExit(
                f"BENCH_FLASH_BLOCK={bq}: must be a power of two >= 8 "
                "(Mosaic tiling; see ops/flash_attention.py)"
            )
        cfg = dataclasses_replace(cfg, flash_block_q=bq, flash_block_k=bq)
    seq = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_len, 512))))

    # The BASELINE pairing: BERT-large exercises Adasum, GPT-2 medium the
    # hierarchical two-level reduction (BASELINE.json configs [V]).
    if model_name == "bert_large":
        reduce_op = hvd.Adasum
    else:
        reduce_op = hvd.Average
        os.environ.setdefault("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")

    model = Transformer(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    params = jax.jit(
        lambda: model.init(jax.random.PRNGKey(0), tokens, train=False)
    )()
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), op=reduce_op
    )
    opt_state = opt.init(params)

    # Chunked fused linear-cross-entropy (ops/fused_xent.py): never
    # materializes the (batch·seq, vocab) logits — the step's largest
    # activation (~823 MB fp32 at GPT-2-medium b8/s512) — at the cost
    # of one logits recompute in backward. BENCH_FUSED_XENT=1 enables
    # it for the on-chip A/B; BENCH_XENT_CHUNK tunes the vocab chunk.
    fused_xent = os.environ.get("BENCH_FUSED_XENT", "0") not in (
        "0", "false", "off"
    )
    xent_chunk = int(os.environ.get("BENCH_XENT_CHUNK", "8192"))
    # BENCH_PADDED=1: right-padded batch (uniform lengths in
    # [seq*3/4, seq]) driven through the kernels' native lengths=
    # support — measures the padded-path overhead vs the dense-mask
    # alternative the reference-style stack would pay. Loss masks
    # padded positions.
    padded = os.environ.get("BENCH_PADDED", "0") not in (
        "0", "false", "off"
    )

    # Padded mode: fixed synthetic lengths (the bench reuses one batch,
    # so a closed-over constant is consistent with its style). Loss
    # averages over valid positions only — the fused loss composes
    # because it returns per-token losses (masking the reduction zeroes
    # the masked tokens' cotangents through the custom VJP).
    bench_lens = (
        jnp.asarray(
            np.random.default_rng(7).integers(
                3 * seq // 4, seq + 1, size=(batch,)
            ),
            jnp.int32,
        )
        if padded
        else None
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def train_step(params, opt_state, tokens, labels):
        tokens, labels = tokens[0], labels[0]

        def loss_fn(p):
            if fused_xent:
                from horovod_tpu.ops.fused_xent import (
                    fused_linear_cross_entropy,
                )

                hidden = model.apply(
                    p, tokens, train=True, return_hidden=True,
                    lengths=bench_lens,
                )
                head = p["params"]["lm_head"]
                per_tok = fused_linear_cross_entropy(
                    hidden.reshape(-1, cfg.d_model),
                    head["kernel"],
                    head["bias"],
                    labels.reshape(-1),
                    chunk=xent_chunk,
                    compute_dtype=(
                        cfg.dtype if cfg.head_mixed_precision else None
                    ),
                )
                if padded:
                    valid = (
                        jnp.arange(tokens.shape[1])[None, :]
                        < bench_lens[:, None]
                    ).reshape(-1)
                    return jnp.sum(
                        jnp.where(valid, per_tok, 0.0)
                    ) / jnp.sum(valid)
                return per_tok.mean()
            if padded:
                logits = model.apply(
                    p, tokens, train=True, lengths=bench_lens
                )
                per_tok = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), labels
                )
                valid = (
                    jnp.arange(tokens.shape[1])[None, :]
                    < bench_lens[:, None]
                )
                return jnp.sum(
                    jnp.where(valid, per_tok, 0.0)
                ) / jnp.sum(valid)
            logits = model.apply(p, tokens, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, hvd.WORLD_AXIS)

    # No donation here: fresh-initialized params contain aliased
    # (deduplicated) zero buffers, and donating the same buffer twice is
    # an XLA error.
    step = jax.jit(train_step)
    rng = np.random.default_rng(0)
    world = hvd.size()
    toks = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(world, batch, seq)), jnp.int32
    )
    labels = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(world, batch, seq)), jnp.int32
    )

    from _benchlib import aot_compile, bytes_accessed, mfu_fields

    step, flops = aot_compile(step, params, opt_state, toks, labels)
    step_bytes = bytes_accessed(step)
    flops_note = None
    if flops and cfg.uses_flash(seq=seq):
        # The Pallas flash-attention kernels are custom calls — invisible
        # to XLA cost analysis — so add their matmul FLOPs analytically:
        # fwd 2 matmuls (QKᵀ, PV) = 4·b·s²·d, bwd ≈ 2× fwd (dq/dk/dv +
        # blockwise recompute), halved for causal masking.
        attn = 12.0 * batch * world * (seq**2) * cfg.d_model * cfg.num_layers
        if cfg.causal:
            attn /= 2.0
        flops += attn
        flops_note = (
            "xla_cost_analysis + analytic flash-attention matmul flops"
        )
    from _benchlib import sync as _sync

    params, opt_state, loss = step(params, opt_state, toks, labels)
    _sync(loss)  # warm; host transfer is the only trustworthy sync
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, toks, labels)
    _sync(loss)  # loss chains through every step's params
    dt = time.perf_counter() - t0
    samples_per_sec = batch * world * iters / dt
    result = {
        "metric": f"{model_name}_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "batch": batch,
        "seq": seq,
        "world": world,
        "remat": remat,
        "head": "mixed" if cfg.head_mixed_precision else "fp32",
        "xent": "fused" if fused_xent else "dense",
        # padded mode: samples/s counts whole padded rows; MFU uses the
        # full-seq analytic attention flops, so it UNDERSTATES true
        # utilization on the valid tokens (conservative)
        "padded": padded,
        "kv_heads": cfg.num_kv_heads or cfg.num_heads,
        # provenance: the kernel auto-shrinks to the sequence, so record
        # the EFFECTIVE block, not the config ask (r04 flipped the
        # default 128->512 mid-capture-chain; without this field those
        # artifacts would be indistinguishable)
        "flash_block": (
            _effective_block(seq, cfg) if cfg.uses_flash(seq=seq) else None
        ),
        "platform": jax.devices()[0].platform,
    }
    result.update(mfu_fields(flops, iters, dt, jax.devices()[0].platform,
                             step_bytes=step_bytes))
    if flops_note:
        result["flops_note"] = flops_note
    print(json.dumps(result))


def _effective_block(seq, cfg):
    from horovod_tpu.ops.flash_attention import _pick_block

    return _pick_block(seq, cfg.flash_block_q)


def dataclasses_replace(cfg, **kw):
    import dataclasses

    return dataclasses.replace(cfg, **kw)


if __name__ == "__main__":
    main()
