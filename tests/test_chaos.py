"""Chaos matrix: every injection site x fault kind must end in one of
the specified outcomes — absorbed by the unified RetryPolicy, degraded
as designed (gang restart / checkpoint fallback / fail-fast circuit),
or fatal on purpose. The reference proves its elastic story by killing
PIDs and flipping discovery files (SURVEY.md §4.3); this suite drives
the same faults through the seeded FaultPlan so CI reproduces them
bit-for-bit."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common.metrics import registry
from horovod_tpu.common.retry import (
    CircuitOpenError,
    RetryError,
    RetryPolicy,
    _reset_breakers,
    backoff_delays,
)
from horovod_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    """Every test starts with no plan and closed circuits."""
    monkeypatch.delenv("HOROVOD_FAULT_PLAN", raising=False)
    chaos.reset()
    _reset_breakers()
    yield
    chaos.reset()
    _reset_breakers()


def _fast_policy(site, **kw):
    kw.setdefault("attempts", 3)
    kw.setdefault("backoff_ms", 1.0)
    kw.setdefault("backoff_max_ms", 5.0)
    kw.setdefault("deadline_s", 10.0)
    kw.setdefault("circuit_threshold", 2)
    kw.setdefault("circuit_cooldown_s", 0.2)
    return RetryPolicy(site, **kw)


def _delta(name, before):
    return registry.snapshot().get(name, 0.0) - before.get(name, 0.0)


# ---------------------------------------------------------------- FaultPlan


class TestFaultPlan:
    def test_parse_full_syntax(self):
        p = chaos.FaultPlan.parse(
            "seed=9;kv.request@2:reset;heartbeat:p=0.25:delay:ms=50;"
            "svc:5xx:n=3;train.step@4:kill"
        )
        assert p.seed == 9
        kinds = {(r.site, r.kind) for r in p.rules}
        assert kinds == {
            ("kv.request", "reset"), ("heartbeat", "delay"),
            ("svc", "5xx"), ("train.step", "kill"),
        }
        by_site = {r.site: r for r in p.rules}
        assert by_site["kv.request"].at == 2
        assert by_site["kv.request"].remaining == 1  # @N defaults 1-shot
        assert by_site["heartbeat"].p == 0.25
        assert by_site["heartbeat"].ms == 50.0
        assert by_site["heartbeat"].remaining == -1  # unlimited
        assert by_site["svc"].remaining == 3

    def test_parse_rejects_unknown_token_and_kind(self):
        with pytest.raises(ValueError):
            chaos.FaultPlan.parse("kv.request:bogus")
        with pytest.raises(ValueError):
            chaos.FaultPlan.parse("kv.request@1:p=0.5")  # @ and p exclusive

    def test_at_rule_fires_exactly_once_on_the_nth_hit(self):
        plan = chaos.configure("seed=1;site.a@3:reset")
        chaos.inject("site.a")
        chaos.inject("site.a")
        with pytest.raises(ConnectionResetError):
            chaos.inject("site.a")
        for _ in range(5):
            chaos.inject("site.a")  # one-shot: never again
        assert plan.fired() == [{"site": "site.a", "kind": "reset", "hit": 3}]
        assert plan.hits("site.a") == 8

    def test_probability_rules_are_deterministic_per_seed(self):
        def pattern(seed):
            plan = chaos.FaultPlan(
                [chaos.FaultRule("s", kind="timeout", p=0.5, n=1000)],
                seed=seed,
            )
            fired = []
            for i in range(40):
                try:
                    plan.fire("s")
                    fired.append(0)
                except TimeoutError:
                    fired.append(1)
            return fired

        a, b, c = pattern(7), pattern(7), pattern(8)
        assert a == b                      # same seed -> same schedule
        assert a != c                      # seed actually matters
        assert 5 < sum(a) < 35             # p=0.5 is roughly half

    def test_unrelated_site_interleaving_does_not_perturb_schedule(self):
        """Per-site RNG streams: site B's hits cannot shift site A's
        draws — the property that makes multi-site plans reproducible."""
        def run(interleave):
            plan = chaos.FaultPlan(
                [chaos.FaultRule("a", kind="5xx", p=0.5, n=1000)], seed=3
            )
            out = []
            for i in range(20):
                if interleave:
                    plan.fire("b")
                try:
                    plan.fire("a")
                    out.append(0)
                except chaos.InjectedServerError:
                    out.append(1)
            return out

        assert run(False) == run(True)

    def test_env_loading_and_file_indirection(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HOROVOD_FAULT_PLAN", "seed=5;x@1:timeout")
        chaos.reset()
        plan = chaos.active()
        assert plan is not None and plan.seed == 5
        spec_file = tmp_path / "plan.txt"
        spec_file.write_text("seed=6;y@1:reset\n")
        monkeypatch.setenv("HOROVOD_FAULT_PLAN", f"@{spec_file}")
        chaos.reset()
        plan = chaos.active()
        assert plan.seed == 6 and plan.rules[0].site == "y"

    def test_delay_kind_sleeps(self):
        chaos.configure("d@1:delay:ms=120")
        t0 = time.monotonic()
        chaos.inject("d")
        assert time.monotonic() - t0 >= 0.1

    def test_injection_counters(self):
        before = registry.snapshot()
        chaos.configure("c@1:5xx")
        with pytest.raises(chaos.InjectedServerError):
            chaos.inject("c")
        assert _delta("faults_injected", before) == 1
        assert _delta("chaos.c.5xx", before) == 1

    def test_no_plan_inject_is_noop(self):
        for _ in range(3):
            chaos.inject("anything")  # must not raise


# -------------------------------------------------------------- RetryPolicy


class TestRetryPolicy:
    def test_absorbs_transient_failures(self):
        pol = _fast_policy("t.ok")
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionResetError("flake")
            return "done"

        before = registry.snapshot()
        assert pol.call(flaky) == "done"
        assert calls["n"] == 3
        assert _delta("retry.t.ok.attempts", before) == 3
        assert _delta("retry.t.ok.retries", before) == 2
        assert _delta("retry.retries_total", before) == 2
        assert _delta("retry.t.ok.exhausted", before) == 0

    def test_non_retryable_raises_immediately(self):
        pol = _fast_policy("t.perm")
        calls = {"n": 0}

        def denied():
            calls["n"] += 1
            raise PermissionError("bad HMAC")

        with pytest.raises(PermissionError):
            pol.call(denied)
        assert calls["n"] == 1

    def test_exhaustion_raises_retry_error_with_cause(self):
        pol = _fast_policy("t.dead")
        before = registry.snapshot()
        with pytest.raises(RetryError) as ei:
            pol.call(lambda: (_ for _ in ()).throw(TimeoutError("slow")))
        assert isinstance(ei.value.__cause__, TimeoutError)
        assert isinstance(ei.value, ConnectionError)  # existing handlers
        assert _delta("retry.t.dead.exhausted", before) == 1

    def test_deadline_stops_the_ladder_early(self):
        pol = _fast_policy(
            "t.deadline", attempts=10, backoff_ms=500.0,
            backoff_max_ms=500.0, deadline_s=0.2,
        )
        calls = {"n": 0}

        def failing():
            calls["n"] += 1
            raise ConnectionResetError("x")

        t0 = time.monotonic()
        with pytest.raises(RetryError) as ei:
            pol.call(failing)
        assert time.monotonic() - t0 < 1.0
        assert calls["n"] < 10  # nowhere near the attempt budget
        # the error reports the attempts that RAN, not the budget
        assert ei.value.attempts == calls["n"]

    def test_circuit_opens_then_half_opens(self):
        pol = _fast_policy("t.circuit")

        def dead():
            raise ConnectionRefusedError("down")

        before = registry.snapshot()
        for _ in range(2):  # threshold=2 consecutive exhausted rounds
            with pytest.raises(RetryError):
                pol.call(dead, peer="host:1")
        assert pol.circuit_state("host:1") == "open"
        t0 = time.monotonic()
        with pytest.raises(CircuitOpenError):
            pol.call(dead, peer="host:1")
        # fail-fast: no attempts, no backoff sleeps
        assert time.monotonic() - t0 < 0.05
        assert _delta("retry.t.circuit.circuit_open", before) == 1
        time.sleep(0.25)  # cooldown=0.2 -> half-open probe allowed
        with pytest.raises(RetryError):
            pol.call(dead, peer="host:1")  # probe ran (and failed)
        # recovery: a successful probe closes the circuit
        time.sleep(0.25)
        assert pol.call(lambda: "up", peer="host:1") == "up"
        assert pol.circuit_state("host:1") == "closed"

    def test_non_retryable_failures_do_not_move_the_breaker(self):
        """An auth/4xx failure is a protocol problem, not peer death:
        however many land, the circuit stays closed."""
        pol = _fast_policy("t.auth")
        for _ in range(5):
            with pytest.raises(PermissionError):
                pol.call(
                    lambda: (_ for _ in ()).throw(PermissionError("hmac")),
                    peer="p:1",
                )
        assert pol.circuit_state("p:1") == "closed"
        assert pol.call(lambda: 1, peer="p:1") == 1

    def test_breaker_is_per_peer(self):
        pol = _fast_policy("t.peers")
        for _ in range(2):
            with pytest.raises(RetryError):
                pol.call(
                    lambda: (_ for _ in ()).throw(ConnectionResetError()),
                    peer="dead:1",
                )
        assert pol.circuit_state("dead:1") == "open"
        assert pol.call(lambda: 1, peer="alive:2") == 1

    def test_backoff_delays_shape(self):
        delays = backoff_delays(0.1, 1.0, jitter=0.25)
        seq = [next(delays) for _ in range(8)]
        assert 0.075 <= seq[0] <= 0.125      # jitter window of initial
        assert all(d <= 1.25 for d in seq)   # cap (+jitter) respected
        assert seq[3] > seq[0]               # it actually grows
        nojit = backoff_delays(0.05, 1.0, jitter=0.0)
        assert [round(next(nojit), 4) for _ in range(6)] == [
            0.05, 0.1, 0.2, 0.4, 0.8, 1.0
        ]


# ------------------------------------------------------ rendezvous KV chaos


@pytest.fixture
def kv(monkeypatch):
    """Python-backend rendezvous server + a fast-retry client."""
    from horovod_tpu.runner.rendezvous import (
        RendezvousClient,
        RendezvousServer,
    )
    from horovod_tpu.runner.secret import make_secret_key

    monkeypatch.setenv("HOROVOD_RENDEZVOUS_BACKEND", "python")
    key = make_secret_key()
    server = RendezvousServer(secret_key=key)
    port = server.start()
    client = RendezvousClient(
        "127.0.0.1", port, secret_key=key,
        retry=_fast_policy("kv.request", attempt_timeout_s=5.0),
    )
    yield server, client
    server.stop()


class TestKVChaos:
    @pytest.mark.parametrize("kind", ["reset", "timeout", "5xx"])
    def test_client_side_fault_absorbed(self, kv, kind):
        _, client = kv
        chaos.configure(f"seed=2;kv.request@1:{kind}")
        before = registry.snapshot()
        client.put("s", "k", b"v")
        assert client.get("s", "k") == b"v"
        assert _delta("retry.kv.request.retries", before) >= 1
        assert _delta("faults_injected", before) == 1

    @pytest.mark.parametrize("kind", ["5xx", "reset"])
    def test_server_side_fault_absorbed(self, kv, kind):
        server, client = kv
        client.put("s", "k", b"v")  # hits 1-2 (put) land clean
        chaos.configure(f"seed=2;kv.server@1:{kind}")
        before = registry.snapshot()
        assert client.get("s", "k") == b"v"
        assert _delta("retry.kv.request.retries", before) >= 1

    def test_exhaustion_then_circuit_fail_fast(self, kv):
        _, client = kv
        chaos.configure("seed=2;kv.request:reset")  # EVERY attempt dies
        with pytest.raises(RetryError):
            client.put("s", "k", b"v")
        with pytest.raises(RetryError):
            client.put("s", "k", b"v")  # threshold=2 -> circuit opens
        t0 = time.monotonic()
        with pytest.raises(CircuitOpenError):
            client.put("s", "k", b"v")
        assert time.monotonic() - t0 < 0.05  # fail-FAST, no ladder

    def test_wait_backoff_cuts_poll_volume(self, kv):
        """The satellite fix: a parked wait() must back off toward the
        ~1s cap instead of hammering at a fixed 50ms — over this 1.2s
        window that is <=9 polls where the old loop fired ~24."""
        _, client = kv
        chaos.configure("seed=1")  # no rules: pure hit counter
        with pytest.raises(TimeoutError):
            client.wait("nope", "missing", timeout=1.2)
        polls = chaos.active().hits("kv.request")
        assert 2 <= polls <= 9, polls

    def test_wait_aborts_on_should_stop(self, kv):
        _, client = kv
        stop = threading.Event()
        threading.Timer(0.15, stop.set).start()
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="shutdown"):
            client.wait(
                "nope", "missing", timeout=30.0, should_stop=stop.is_set
            )
        assert time.monotonic() - t0 < 5.0  # nowhere near the timeout

    def test_wait_still_returns_late_keys(self, kv):
        server, client = kv
        threading.Timer(
            0.3, lambda: server.store.put("s", "late", b"now")
        ).start()
        assert client.wait("s", "late", timeout=10.0) == b"now"

    def test_kill_kind_terminates_a_worker_process(self, tmp_path):
        """The process-death drill actually dies by SIGKILL."""
        script = tmp_path / "victim.py"
        script.write_text(
            "from horovod_tpu.testing import chaos\n"
            "chaos.configure('boom@1:kill')\n"
            "chaos.inject('boom')\n"
            "print('unreachable')\n"
        )
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get(
            "PYTHONPATH", ""
        )
        out = subprocess.run(
            [sys.executable, str(script)], env=env,
            capture_output=True, timeout=60,
        )
        assert out.returncode == -signal.SIGKILL
        assert b"unreachable" not in out.stdout


# ------------------------------------------------------- signed RPC chaos


@pytest.fixture
def rpc():
    from horovod_tpu.runner.secret import make_secret_key
    from horovod_tpu.runner.service import BasicClient, BasicService

    key = make_secret_key()
    service = BasicService("chaos-test", key)
    service.register("ping", lambda req: {"pong": req.get("x")})
    port = service.start()
    client = BasicClient(
        "127.0.0.1", port, key, timeout=5,
        retry=_fast_policy("service.client"),
    )
    yield service, client
    service.stop()


class TestServiceChaos:
    @pytest.mark.parametrize(
        "site,kind",
        [
            ("service.client", "reset"),
            ("service.client", "timeout"),
            ("service.server", "reset"),
            ("service.server", "5xx"),
        ],
    )
    def test_rpc_fault_absorbed(self, rpc, site, kind):
        _, client = rpc
        chaos.configure(f"seed=4;{site}@1:{kind}")
        before = registry.snapshot()
        out = client.request({"type": "ping", "x": 7})
        assert out == {"ok": True, "pong": 7}
        assert _delta("retry.service.client.retries", before) >= 1

    def test_rpc_exhaustion_then_circuit(self, rpc):
        _, client = rpc
        chaos.configure("seed=4;service.client:reset")
        for _ in range(2):
            with pytest.raises(RetryError):
                client.request({"type": "ping"})
        with pytest.raises(CircuitOpenError):
            client.request({"type": "ping"})


# --------------------------------------------------------- heartbeat chaos


class TestHeartbeatChaos:
    def test_heartbeat_survives_kv_flake(self, monkeypatch):
        """The worker's first heartbeat PUT eats an injected reset; the
        KV client's retry absorbs it and the stamp still lands."""
        from horovod_tpu.elastic.worker import WorkerNotificationManager
        from horovod_tpu.runner.rendezvous import RendezvousServer
        from horovod_tpu.runner.secret import make_secret_key

        monkeypatch.setenv("HOROVOD_RENDEZVOUS_BACKEND", "python")
        monkeypatch.setenv("HOROVOD_RETRY_BACKOFF_MS", "5")
        key = make_secret_key()
        server = RendezvousServer(secret_key=key)
        port = server.start()
        monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
        monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
        monkeypatch.setenv("HOROVOD_SECRET_KEY", key.hex())
        monkeypatch.setenv("HOROVOD_RANK", "3")
        monkeypatch.setenv("HOROVOD_HOSTNAME", "localhost")
        # the registration PUT is hit 1; the first heartbeat PUT (hit 2)
        # gets the reset
        chaos.configure("seed=5;kv.request@2:reset")
        before = registry.snapshot()
        mgr = WorkerNotificationManager()
        mgr.init()
        try:
            deadline = time.monotonic() + 10
            hb = None
            while time.monotonic() < deadline:
                hb = server.store.get("heartbeat", "3")
                if hb is not None:
                    break
                time.sleep(0.05)
            assert hb is not None, "heartbeat never landed"
            assert _delta("retry.kv.request.retries", before) >= 1
            assert _delta("faults_injected", before) >= 1
        finally:
            mgr.shutdown()
            server.stop()

    def test_heartbeat_site_delay_does_not_kill_the_loop(self):
        chaos.configure("heartbeat:delay:ms=1:n=5")
        for _ in range(5):
            chaos.inject("heartbeat")  # absorbed as slow beats
        assert chaos.active().hits("heartbeat") == 5


# --------------------------------------------------------- checkpoint chaos


def _corrupt_step_dir(ckdir, step):
    """Damage every array/metadata payload of one committed step —
    post-commit disk damage, the case the atomic-save marker cannot
    guard and the restore fallback must."""
    root = None
    for dirpath, dirnames, filenames in os.walk(ckdir):
        if os.path.basename(dirpath) == str(step):
            root = dirpath
            break
    assert root is not None, f"no step dir {step} under {ckdir}"
    clobbered = 0
    for dirpath, _, filenames in os.walk(root):
        for fn in filenames:
            path = os.path.join(dirpath, fn)
            with open(path, "wb") as f:
                f.write(b"\x00CORRUPT\x00")
            clobbered += 1
    assert clobbered > 0
    return root


class TestCheckpointChaos:
    def test_restore_falls_back_past_corruption(self, hvd, tmp_path):
        import jax.numpy as jnp

        from horovod_tpu.checkpoint import CheckpointManager

        like = {"x": jnp.zeros(4)}
        with CheckpointManager(str(tmp_path / "ck"), max_to_keep=3) as mgr:
            for step in (1, 2):
                mgr.save(step, {"x": jnp.full(4, float(step))})
                mgr.wait_until_finished()
            _corrupt_step_dir(str(tmp_path / "ck"), 2)
            before = registry.snapshot()
            step, out = mgr.restore_latest_good(like=like)
        assert step == 1
        np.testing.assert_allclose(np.asarray(out["x"]), 1.0)
        assert _delta("checkpoint.fallback", before) >= 1

    def test_all_corrupt_raises_instead_of_fresh_start(
        self, hvd, tmp_path
    ):
        import jax.numpy as jnp

        from horovod_tpu.checkpoint import CheckpointManager

        with CheckpointManager(str(tmp_path / "ck")) as mgr:
            mgr.save(1, {"x": jnp.ones(2)})
            mgr.wait_until_finished()
            _corrupt_step_dir(str(tmp_path / "ck"), 1)
            with pytest.raises(Exception):
                mgr.restore_latest_good(like={"x": jnp.zeros(2)})

    def test_durable_state_resumes_from_newest_good(self, hvd, tmp_path):
        import jax.numpy as jnp

        from horovod_tpu.checkpoint import DurableJaxState

        ckdir = str(tmp_path / "ck")
        state = DurableJaxState(
            checkpoint_dir=ckdir, params={"w": jnp.zeros(3)}, step=0,
            max_to_keep=4,
        )
        for i in (1, 2, 3):
            state.params = {"w": jnp.full(3, float(i))}
            state.step = i
            state.commit()
        state.wait_until_finished()
        state.close()
        _corrupt_step_dir(ckdir, 3)

        before = registry.snapshot()
        fresh = DurableJaxState(
            checkpoint_dir=ckdir, params={"w": jnp.zeros(3)}, step=0,
            max_to_keep=4,
        )
        assert fresh.resume_latest()
        assert fresh.step == 2  # newest GOOD, not newest
        np.testing.assert_allclose(np.asarray(fresh.params["w"]), 2.0)
        assert _delta("checkpoint.fallback", before) >= 1
        fresh.close()

    def test_sigkill_mid_save_never_trusts_a_torn_file(
        self, hvd, tmp_path
    ):
        """Regression (satellite 2): a SIGKILL landing while the async
        save of step 2 is in flight must leave either a fully-committed
        step 2 or nothing past step 1 — the restore may fall back but
        can NEVER hand back torn data."""
        ckdir = str(tmp_path / "ck")
        script = tmp_path / "saver.py"
        script.write_text(
            "import os, signal\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import jax, jax.numpy as jnp\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from horovod_tpu.checkpoint import CheckpointManager\n"
            f"mgr = CheckpointManager({ckdir!r}, max_to_keep=3)\n"
            "mgr.save(1, {'x': jnp.full(4096, 1.0)})\n"
            "mgr.wait_until_finished()\n"
            "mgr.save(2, {'x': jnp.full(4096, 2.0)})\n"
            "# no wait: the write is in flight when the kill lands\n"
            "os.kill(os.getpid(), signal.SIGKILL)\n"
        )
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get(
            "PYTHONPATH", ""
        )
        out = subprocess.run(
            [sys.executable, str(script)], env=env,
            capture_output=True, timeout=120,
        )
        assert out.returncode == -signal.SIGKILL, out.stderr

        import jax.numpy as jnp

        from horovod_tpu.checkpoint import CheckpointManager

        with CheckpointManager(ckdir) as mgr:
            step, tree = mgr.restore_latest_good(
                like={"x": jnp.zeros(4096)}
            )
        assert step in (1, 2)
        np.testing.assert_allclose(
            np.asarray(tree["x"]), float(step)
        )  # whichever step won, its data is EXACT — never torn


# ------------------------------------------------------ fusion-path chaos


class TestFusionChaos:
    @pytest.mark.parametrize("kind", ["reset", "timeout", "5xx"])
    def test_dispatch_fault_surfaces_as_internal_error(self, hvd, kind):
        chaos.configure(f"seed=6;fusion.dispatch@1:{kind}")
        with pytest.raises(hvd.HorovodInternalError):
            hvd.allreduce(np.ones((8, 4), np.float32), name="chaos_ar")

    def test_elastic_run_absorbs_dispatch_fault(self, hvd):
        """The degradation contract end to end: fault at the collective
        -> HorovodInternalError -> hvd.elastic.run restores the last
        commit and the retried body completes."""
        from horovod_tpu.elastic import ObjectState
        from horovod_tpu.elastic.worker import run as elastic_run

        chaos.configure("seed=6;fusion.dispatch@1:timeout")
        state = ObjectState(step=0)
        attempts = {"n": 0}

        @elastic_run
        def train(st):
            attempts["n"] += 1
            st.step += 1
            out = hvd.allreduce(
                np.ones((hvd.size(), 4), np.float32),
                op=hvd.Average, name="chaos_elastic",
            )
            return st.step, np.asarray(out)

        step, out = train(state)
        assert attempts["n"] == 2          # failed once, absorbed once
        assert step == 1                   # rollback discarded the bump
        np.testing.assert_allclose(out, 1.0)  # average of ones


# ------------------------------------------------- self-healing driver


class _StoreServer:
    """Duck-typed stand-in for RendezvousServer in driver unit tests."""

    def __init__(self, store):
        self.store = store


def _put_hb(store, rank, p50, step=100):
    from horovod_tpu.runner.rendezvous import HEARTBEAT_SCOPE

    store.put(
        HEARTBEAT_SCOPE, str(rank),
        json.dumps({
            "ts": time.time(), "step": step,
            "step_ms_p50": p50, "last_step_ts": time.time(),
        }).encode(),
    )


class TestDriverSelfHealing:
    def _driver(self, monkeypatch, hosts, polls=3, min_np=1):
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.rendezvous import KVStore

        from tests.test_elastic import FakeDiscovery

        monkeypatch.setenv(
            "HOROVOD_STRAGGLER_QUARANTINE_POLLS", str(polls)
        )
        d = ElasticDriver(
            FakeDiscovery([HostInfo(h, s) for h, s in hosts]),
            ["true"], min_np=min_np,
        )
        d.host_manager.refresh()
        d._server = _StoreServer(KVStore())
        # synthetic gang: ranks 0-1 on host a, 2-7 on host b
        d._blocks = [
            {"HOROVOD_RANK": str(r), "HOROVOD_HOSTNAME": h}
            for r, h in enumerate(["a"] * 2 + ["b"] * 6)
        ]
        return d

    def _poll(self, d):
        d._last_hb_poll = -1e9
        return d._poll_heartbeats(time.monotonic())

    def test_quarantine_after_k_consecutive_polls(self, monkeypatch):
        d = self._driver(monkeypatch, [("a", 2), ("b", 6)], polls=3)
        before = registry.snapshot()
        for poll in range(3):
            for r in range(8):
                _put_hb(d._server.store, r, 500.0 if r < 2 else 10.0)
            reason = self._poll(d)
            if poll < 2:
                assert reason is None  # hysteresis: not yet
        assert reason is not None and "quarantine" in reason
        assert d.host_manager.is_blacklisted("a")
        assert not d.host_manager.is_blacklisted("b")
        # re-plan excludes the quarantined host: 8 -> 6
        assert d.compute_assignment().world_size == 6
        assert _delta("driver.quarantined_hosts", before) == 1

    def test_recovery_resets_the_streak(self, monkeypatch):
        d = self._driver(monkeypatch, [("a", 2), ("b", 6)], polls=3)
        for _ in range(2):
            for r in range(8):
                _put_hb(d._server.store, r, 500.0 if r < 2 else 10.0)
            assert self._poll(d) is None
        # the slow ranks recover for one poll -> streak resets
        for r in range(8):
            _put_hb(d._server.store, r, 10.0)
        assert self._poll(d) is None
        for _ in range(2):
            for r in range(8):
                _put_hb(d._server.store, r, 500.0 if r < 2 else 10.0)
            assert self._poll(d) is None  # streak only at 2 again
        assert not d.host_manager.is_blacklisted("a")

    def test_stale_heartbeat_does_not_advance_streak(self, monkeypatch):
        """The driver polls ~10x faster than workers beat: re-judging
        ONE noisy heartbeat payload on every poll must not reach the
        quarantine threshold — streaks only advance on fresh stamps."""
        d = self._driver(monkeypatch, [("a", 2), ("b", 6)], polls=3)
        for r in range(8):  # one noisy observation, stamped once
            _put_hb(d._server.store, r, 500.0 if r < 2 else 10.0)
        for _ in range(6):  # driver re-reads the SAME payloads
            assert self._poll(d) is None
        assert not d.host_manager.is_blacklisted("a")
        assert max(
            d.stall_inspector.straggler_streaks().values(), default=0
        ) == 1

    def test_capacity_guard_keeps_slow_host(self, monkeypatch):
        """Quarantining the straggler would leave < min_np slots: a
        slow gang beats no gang, so the driver keeps it (warning once)."""
        d = self._driver(
            monkeypatch, [("a", 2), ("b", 6)], polls=2, min_np=8
        )
        for _ in range(3):
            for r in range(8):
                _put_hb(d._server.store, r, 500.0 if r < 2 else 10.0)
            assert self._poll(d) is None
        assert not d.host_manager.is_blacklisted("a")
        assert d._quarantine_capacity_warned

    def test_quarantine_disabled_by_zero_polls(self, monkeypatch):
        d = self._driver(monkeypatch, [("a", 2), ("b", 6)], polls=0)
        for _ in range(5):
            for r in range(8):
                _put_hb(d._server.store, r, 500.0 if r < 2 else 10.0)
            assert self._poll(d) is None
        assert not d.host_manager.is_blacklisted("a")


# ------------------------------------------------------- end-to-end drill


def _clean_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""
    return env


@pytest.mark.slow
class TestChaosDrill:
    """The acceptance scenario as one chained story: KV flake during
    rendezvous (absorbed by retry) -> straggler quarantine (hysteresis)
    -> gang restart 8 -> 6 excluding the slow host -> resume from the
    last GOOD checkpoint past a corrupt newest one."""

    def test_full_drill(self, monkeypatch, tmp_path, hvd):
        import jax.numpy as jnp

        from horovod_tpu.checkpoint import DurableJaxState
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo

        from tests.test_elastic import FakeDiscovery

        # ---- phase 0: two durable checkpoints from the "epoch-0 job"
        ckdir = str(tmp_path / "ck")
        state = DurableJaxState(
            checkpoint_dir=ckdir, params={"w": jnp.zeros(4)}, step=0,
            max_to_keep=4,
        )
        for i in (1, 2):
            state.params = {"w": jnp.full(4, float(i))}
            state.step = i
            state.commit()
        state.wait_until_finished()
        state.close()

        # ---- phase 1: gang of 8 under a seeded KV-flake plan; the
        # workers each hit one injected reset during rendezvous traffic
        # and must absorb it (nonzero retry counters in their metrics
        # dumps), while the driver quarantines the straggler host
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        monkeypatch.setenv("HOROVOD_STRAGGLER_QUARANTINE_POLLS", "3")
        results = tmp_path / "results"
        results.mkdir()
        script = tmp_path / "w.py"
        script.write_text(
            "import json, os, sys, time\n"
            "sys.path.insert(0, os.getcwd())\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "from horovod_tpu.common.config import Config\n"
            "from horovod_tpu.common.metrics import registry\n"
            "from horovod_tpu.runner.rendezvous import _client_from_cfg\n"
            "rank = os.environ['HOROVOD_RANK']\n"
            "epoch = int(os.environ.get('HOROVOD_ELASTIC_EPOCH', '0'))\n"
            "cfg = Config.from_env()\n"
            "client = _client_from_cfg(cfg)\n"
            "# rendezvous traffic: the seeded plan resets each\n"
            "# worker's first KV request; the RetryPolicy absorbs it\n"
            "client.put('drill', rank, str(epoch).encode())\n"
            "assert client.get('drill', rank) == str(epoch).encode()\n"
            f"out = os.path.join({str(results)!r}, "
            "f'e{epoch}.r{rank}.json')\n"
            "with open(out, 'w') as f:\n"
            "    json.dump(registry.snapshot(), f)\n"
            "if epoch >= 1:\n"
            "    sys.exit(0)\n"
            "time.sleep(120)\n"  # epoch 0 parks until the restart
        )
        d = ElasticDriver(
            FakeDiscovery(
                [HostInfo("127.0.0.1", 2), HostInfo("localhost", 6)]
            ),
            [sys.executable, str(script)],
            min_np=1,
            discovery_interval=0.2,
            extra_env={
                "HOROVOD_FAULT_PLAN": "seed=11;kv.request@1:reset",
                "HOROVOD_RETRY_BACKOFF_MS": "5",
            },
        )
        try:
            d.host_manager.refresh()
            result = {}
            t = threading.Thread(target=lambda: result.update(rc=d.run()))
            t.start()
            # wait for the epoch-0 gang's 8 result files (all absorbed
            # their KV flake and are parked)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(list(results.glob("e0.*.json"))) == 8:
                    break
                time.sleep(0.1)
            assert len(list(results.glob("e0.*.json"))) == 8
            with d._lock:
                rank_to_host = {
                    int(b["HOROVOD_RANK"]): b["HOROVOD_HOSTNAME"]
                    for b in d._blocks
                }
            slow_ranks = {
                r for r, h in rank_to_host.items() if h == "127.0.0.1"
            }
            assert len(slow_ranks) == 2
            # straggler ledger: host 127.0.0.1's ranks report 50x the
            # gang median p50 until the driver quarantines them
            stop_beats = threading.Event()

            def _stamp():
                while not stop_beats.is_set() and d._epoch == 0:
                    for r, h in rank_to_host.items():
                        _put_hb(
                            d._server.store, r,
                            500.0 if r in slow_ranks else 10.0,
                        )
                    time.sleep(0.1)

            beater = threading.Thread(target=_stamp)
            beater.start()
            t.join(timeout=90)
            stop_beats.set()
            beater.join(timeout=5)
            assert not t.is_alive(), "driver did not converge"
            assert result["rc"] == 0
            assert d._resets == 1, "expected exactly one gang restart"
            assert d.host_manager.is_blacklisted("127.0.0.1")
        finally:
            d.shutdown()

        # ---- phase 2 assertions: epoch-1 gang is 6 workers, every
        # worker absorbed its injected KV reset (retry counters > 0)
        e1 = sorted(results.glob("e1.*.json"))
        assert len(e1) == 6, [p.name for p in e1]
        for path in list(results.glob("e0.*.json"))[:1] + e1[:1]:
            snap = json.loads(path.read_text())
            assert snap.get("retry.kv.request.retries", 0) > 0, path.name
            assert snap.get("faults_injected", 0) > 0, path.name

        # ---- phase 3: resume from the last GOOD checkpoint — the
        # newest one is corrupt (the failed epoch's parting gift)
        _corrupt_step_dir(ckdir, 2)
        before = registry.snapshot()
        fresh = DurableJaxState(
            checkpoint_dir=ckdir, params={"w": jnp.zeros(4)}, step=0,
            max_to_keep=4,
        )
        assert fresh.resume_latest()
        assert fresh.step == 1
        np.testing.assert_allclose(np.asarray(fresh.params["w"]), 1.0)
        assert _delta("checkpoint.fallback", before) >= 1
        fresh.close()


# ------------------------------------------------------ serving chaos sites


class TestServeChaos:
    def test_serve_sites_parse_and_fire(self):
        plan = chaos.configure(
            "seed=3;serve.worker_kill@1:reset;serve.migrate@1:timeout"
        )
        with pytest.raises(ConnectionResetError):
            chaos.inject("serve.worker_kill")
        with pytest.raises(TimeoutError):
            chaos.inject("serve.migrate")
        assert {(f["site"], f["kind"]) for f in plan.fired()} == {
            ("serve.worker_kill", "reset"),
            ("serve.migrate", "timeout"),
        }

    def test_worker_kill_transport_fault_crashes_scheduler_to_replay(self):
        """A transport-kind fault at serve.worker_kill lands at the top
        of the batcher's step: the scheduler dies, accepted requests
        fail LOUDLY and new submissions are refused — the dark-worker
        face the Router's replay path keys on. (The ``kill`` kind
        SIGKILLs outright for subprocess drills; its mechanics are
        covered by test_kill_kind_terminates_a_worker_process.)"""
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.transformer import (
            Transformer,
            TransformerConfig,
        )
        from horovod_tpu.serving.batcher import ContinuousBatcher, Rejected
        from horovod_tpu.serving.engine import InferenceEngine

        cfg = TransformerConfig(
            vocab_size=31, num_layers=1, d_model=8, num_heads=2,
            d_ff=16, max_len=32, causal=True, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        params = model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32),
            train=False,
        )
        eng = InferenceEngine(
            model, params, slots=2, max_len=32, min_bucket=4
        )
        bat = ContinuousBatcher(eng, default_max_new_tokens=4)
        chaos.configure("seed=3;serve.worker_kill@1:reset")
        before = registry.snapshot()
        r = bat.submit([1, 2, 3])  # accepted BEFORE the fault lands
        bat.start()
        try:
            assert r.wait(timeout=30), "waiter stranded after kill fault"
            assert r.status == "error"
            with pytest.raises(Rejected):
                bat.submit([4, 5])
        finally:
            bat.stop()
        assert _delta("chaos.serve.worker_kill.reset", before) == 1


def test_driver_publishes_dead_hosts_to_serve_scope():
    """handle_host_failure/_try_blacklist wiring: the blacklisted host
    set (plus the ranks mapped onto it) lands in the serve scope so the
    Router can evict its announcements without waiting out the TTL."""
    import threading
    import types

    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.rendezvous import KVStore, read_dead_hosts

    store = KVStore()
    fake = types.SimpleNamespace(
        _server=_StoreServer(store),
        host_manager=types.SimpleNamespace(blacklisted=["a"]),
        _lock=threading.Lock(),
        _blocks=[
            {"HOROVOD_RANK": str(r), "HOROVOD_HOSTNAME": h}
            for r, h in enumerate(["a"] * 2 + ["b"] * 2)
        ],
    )
    ElasticDriver._publish_dead_hosts(fake)
    dead = read_dead_hosts(store)
    assert dead["hosts"] == ["a"]
    assert dead["ranks"] == [0, 1]
