"""Parameter-shard layouts: the ONE source of truth for how a pytree
leaf maps onto per-rank shards.

Two styles share this module so their math can never drift apart:

* **Flat ZeRO layout** (``shard_cols`` / ``pad_to`` / ``host_shard`` /
  ``host_shard_rows`` / ``dyn_shard`` / ``host_unshard``): every
  nonscalar leaf is flattened, zero-padded to a multiple of the world
  size, and split rank-major into ``[world, cols]`` rows. This is the
  layout ``ShardedDistributedOptimizer`` uses for optimizer state
  (ZeRO-1), gradient shards (ZeRO-2), and parameter storage (ZeRO-3),
  and what ``reshard_state`` / ``reshard_params`` re-split elastically
  across world changes. It is deliberately shape-oblivious — one rule
  for every leaf — so bucketed collectives can concatenate member
  panes column-wise and the shard slice of a bucket's reduce-scatter
  output IS the storage slice (PAPERS.md arXiv:2004.13336; the ZeRO
  recipe).
* **GSPMD NamedSharding rule** (``fsdp_spec`` / ``fsdp_sharding`` /
  ``fsdp_shard``): for the jit + NamedSharding style, annotate each
  leaf as sharded along its largest divisible dimension and let GSPMD
  insert the all-gathers and reduce-scatters. Kept for the
  compiler-driven path; the explicit-collective stack above is the
  optimizer's layout.

Before PR 9 the flat-layout helpers lived as private duplicates inside
``sharded_optimizer.py``; they were folded here so the ZeRO-2/3
parameter/gradient shards, the elastic reshard, and the GSPMD rule all
read one definition.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.topology import WORLD_AXIS


# ------------------------------------------------------ flat ZeRO layout


def shard_cols(size: int, world: int) -> int:
    """Per-rank shard length of a flattened leaf of ``size`` elements:
    ``ceil(size / world)`` (the zero-padded split)."""
    return -(-int(size) // int(world))


def pad_to(flat, n):
    """Zero-pad a 1-D array to a multiple of ``n`` (traced-safe).
    Pad elements are ZEROS by contract: they quantize to zeros, never
    raise an int8 block's absmax, and carry zero EF residual — the
    by-construction pad-exclusion the sharded wire relies on."""
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def host_shard(x, n, r):
    """Host-side shard ``r`` of leaf ``x`` (init path, outside jit);
    0-d leaves replicate."""
    x = jnp.asarray(x)
    if x.ndim == 0:
        return x
    flat = pad_to(x.reshape(-1), n)
    return flat.reshape(n, -1)[r]


def host_shard_rows(x, n):
    """All ``n`` shards of leaf ``x`` stacked rank-major: ``[n, cols]``
    (0-d leaves broadcast to ``[n]``) — the ZeRO-3 parameter-storage
    layout, matching the optimizer state's leading-world-axis
    convention so both ride ``shard_map`` with one ``P(axis)`` spec."""
    x = jnp.asarray(x)
    if x.ndim == 0:
        return jnp.broadcast_to(x, (n,))
    return pad_to(x.reshape(-1), n).reshape(n, -1)


def dyn_shard(x, n, idx):
    """Traced shard selection by the rank's axis_index (update path)."""
    flat = pad_to(x.reshape(-1), n)
    return jax.lax.dynamic_index_in_dim(
        flat.reshape(n, -1), idx, axis=0, keepdims=False
    )


def host_unshard(rows, shape, dtype=None):
    """Invert :func:`host_shard_rows` on the host: ``[n, cols]`` rows →
    the original leaf (drop the zero-pad tail, restore ``shape``)."""
    rows = np.asarray(rows)
    if len(tuple(shape)) == 0:
        out = rows.reshape(-1)[0]
    else:
        size = int(np.prod(shape, dtype=np.int64))
        out = rows.reshape(-1)[:size].reshape(shape)
    return jnp.asarray(out, dtype) if dtype is not None else jnp.asarray(out)


def reshard_rows(rows, size: int, new_world: int, dtype=None):
    """Re-split one leaf's shard rows at a new world size, preserving
    values bit-exactly: concat the old shards, re-pad (or drop only
    zero-pad tail) for the new split. ``size`` is the ORIGINAL
    (unpadded) element count; entries past it are padding zeros that no
    consumer ever reads back."""
    rows = np.asarray(rows)
    per = shard_cols(size, new_world)
    flat = rows.reshape(-1)
    need = new_world * per
    if flat.size < need:
        flat = np.pad(flat, (0, need - flat.size))
    else:
        flat = flat[:need]
    out = flat.reshape(new_world, per)
    return jnp.asarray(out, dtype) if dtype is not None else jnp.asarray(out)


# ------------------------------------------- GSPMD NamedSharding rule


def fsdp_spec(
    leaf, axis_size: int, axis: str = WORLD_AXIS, min_elems: int = 2**14
) -> P:
    """PartitionSpec for one leaf under the GSPMD FSDP rule: the
    largest dimension divisible by the axis size is sharded; leaves
    with no divisible dimension or fewer than ``min_elems`` elements
    replicate (tiny leaves cost more to gather than they save).
    Deliberately static and predictable — no cost model."""
    shape = np.shape(leaf)
    if int(np.prod(shape, dtype=np.int64)) < min_elems:
        return P()
    best_dim, best_len = None, 0
    for d, length in enumerate(shape):
        if length % axis_size == 0 and length > best_len:
            best_dim, best_len = d, length
    if best_dim is None:
        return P()
    spec = [None] * len(shape)
    spec[best_dim] = axis
    return P(*spec)


def fsdp_sharding(
    params,
    mesh: Mesh,
    axis: str = WORLD_AXIS,
    min_elems: int = 2**14,
):
    """Pytree of NamedShardings implementing the FSDP rule over ``mesh``."""
    n = int(np.prod([mesh.shape[a] for a in ([axis] if isinstance(axis, str) else axis)]))
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(
            mesh, fsdp_spec(x, n, axis=axis, min_elems=min_elems)
        ),
        params,
    )


def fsdp_shard(
    params,
    mesh: Mesh,
    axis: str = WORLD_AXIS,
    min_elems: int = 2**14,
):
    """device_put every leaf onto its FSDP sharding (1/N of each large
    leaf per rank; XLA gathers on use)."""
    shardings = fsdp_sharding(params, mesh, axis=axis, min_elems=min_elems)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
