"""hvdrun elastic mode: --host-discovery-script switches the CLI into
ElasticDriver supervision (ref: horovodrun's elastic launch flags [V],
SURVEY.md §2.5 CLI row). Live multi-process test in the style of
tests/test_runner.py / test_elastic.py."""

import os
import sys

import pytest

from horovod_tpu.runner.launch import parse_args, run_commandline


def _clean_env(monkeypatch):
    for var in list(os.environ):
        if var.startswith("HOROVOD_"):
            monkeypatch.delenv(var, raising=False)


def test_elastic_flags_parse():
    args = parse_args(
        [
            "-np", "2", "--host-discovery-script", "/tmp/d.sh",
            "--min-np", "1", "--max-np", "4", "--reset-limit", "3",
            "--", "python", "train.py",
        ]
    )
    assert args.host_discovery_script == "/tmp/d.sh"
    assert args.min_np == 1 and args.max_np == 4
    assert args.reset_limit == 3
    assert args.command == ["python", "train.py"]


@pytest.mark.slow
def test_hvdrun_elastic_end_to_end(tmp_path, monkeypatch):
    """Full CLI path: discovery script -> ElasticDriver gang -> worker
    exits 0 -> hvdrun returns 0; runtime knobs reach the worker env."""
    _clean_env(monkeypatch)
    discovery = tmp_path / "discover.sh"
    discovery.write_text("#!/bin/sh\necho localhost:2\n")
    discovery.chmod(0o755)

    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os, sys\n"
        "assert os.environ.get('HOROVOD_ELASTIC') == '1'\n"
        "assert 'HOROVOD_RANK' in os.environ\n"
        "assert os.environ.get('HOROVOD_TIMELINE'), 'runtime knob lost'\n"
        "sys.exit(0)\n"
    )

    rc = run_commandline(
        [
            "-np", "2",
            "--host-discovery-script", str(discovery),
            "--timeline-filename", str(tmp_path / "tl.json"),
            "--placement", "per-slot",
            "--", sys.executable, str(worker),
        ]
    )
    assert rc == 0


def test_inconsistent_elastic_bounds_rejected(tmp_path):
    discovery = tmp_path / "d.sh"
    discovery.write_text("#!/bin/sh\necho localhost:2\n")
    discovery.chmod(0o755)
    with pytest.raises(SystemExit, match="inconsistent elastic bounds"):
        run_commandline(
            [
                "-np", "4", "--min-np", "4", "--max-np", "2",
                "--host-discovery-script", str(discovery),
                "--", "true",
            ]
        )


def test_remote_gang_members_launch_over_ssh(monkeypatch):
    """Non-local discovered hosts must get ssh-wrapped worker launches
    with the HMAC secret on stdin (review finding: the elastic path
    used to Popen everything locally)."""
    from horovod_tpu.elastic import driver as driver_mod
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo

    _clean_env(monkeypatch)
    launched = []

    class FakeProc:
        def __init__(self, cmd, **kwargs):
            self.cmd = cmd
            self.kwargs = kwargs
            self.stdin = None
            if kwargs.get("stdin") is not None:
                import io

                self.stdin = io.BytesIO()

        def poll(self):
            return 0

        def wait(self, timeout=None):
            return 0

    def fake_popen(cmd, **kwargs):
        proc = FakeProc(cmd, **kwargs)
        launched.append(proc)
        return proc

    monkeypatch.setattr(driver_mod.subprocess, "Popen", fake_popen)

    class OneShotDiscovery:
        def find_available_hosts_and_slots(self):
            return [
                HostInfo("localhost", 1),
                HostInfo("tpu-worker-7", 1),
            ]

    d = ElasticDriver(
        OneShotDiscovery(), ["python", "train.py"], min_np=2, max_np=2
    )
    try:
        d.host_manager.refresh()
        assignment = d.compute_assignment()
        assert assignment is not None and assignment.world_size == 2
        d._launch_gang(assignment)
        assert len(launched) == 2
        local = [p for p in launched if p.cmd[0] != "ssh"]
        remote = [p for p in launched if p.cmd[0] == "ssh"]
        assert len(local) == 1 and len(remote) == 1
        joined = " ".join(remote[0].cmd)
        assert "tpu-worker-7" in joined
        assert "HOROVOD_RANK" in joined  # env exported through ssh
        # the secret VALUE must not ride argv (only the shell `read`
        # stanza names the variable); it arrives via the stdin pipe
        assert d._secret.hex() not in joined
        assert remote[0].stdin is not None  # secret went via stdin pipe
    finally:
        d.stop()
