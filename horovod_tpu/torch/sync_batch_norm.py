"""Cross-rank synchronized BatchNorm for the torch shim.

TPU-native rebuild of the reference's ``hvd.SyncBatchNorm``
(ref: horovod/torch/sync_batch_norm.py [V]): batch statistics are
reduced across all ranks in forward, and the two gradient reductions
of the exact BN backward are likewise cross-rank, so every replica
normalizes — and differentiates — with global-batch statistics. Where
the reference routes the five reductions through its allreduce ring,
this implementation concatenates the forward stats into ONE fused
vector per direction (sum | sumsq | count) and rides the shim's eager
allreduce, i.e. one XLA psum over the mesh per pass instead of three.

The flax ``SyncBatchNorm`` (models/resnet.py) serves JAX models; this
module serves torch-shim users — the verdict's missing-row #7.
"""

from __future__ import annotations

from typing import Optional



def _torch():
    import torch

    return torch


def _allreduce_sum(vec):
    """Sum a 1-D torch tensor across the mesh via the shim's eager path."""
    from . import Sum, allreduce

    return allreduce(vec, op=Sum)


class _SyncBatchNormFunction:
    """Holder for the autograd.Function, built lazily so importing this
    module never drags torch in before the caller does."""

    _fn = None

    @classmethod
    def get(cls):
        if cls._fn is not None:
            return cls._fn
        torch = _torch()

        class Fn(torch.autograd.Function):
            @staticmethod
            def forward(ctx, x, weight, bias, mean, invstd, count_global):
                shape = [1, -1] + [1] * (x.dim() - 2)
                xhat = (x - mean.reshape(shape)) * invstd.reshape(shape)
                ctx.save_for_backward(x, weight, mean, invstd)
                ctx.count_global = count_global
                if weight is not None:
                    return xhat * weight.reshape(shape) + bias.reshape(shape)
                return xhat

            @staticmethod
            def backward(ctx, dy):
                torch = _torch()
                x, weight, mean, invstd = ctx.saved_tensors
                shape = [1, -1] + [1] * (x.dim() - 2)
                dims = [0] + list(range(2, x.dim()))
                xhat = (x - mean.reshape(shape)) * invstd.reshape(shape)
                sum_dy = dy.sum(dims)
                sum_dy_xhat = (dy * xhat).sum(dims)
                # The exact BN backward needs GLOBAL Σdy and Σdy·x̂ (ref:
                # sync_batch_norm.py backward [V]); one fused allreduce.
                fused = torch.cat([sum_dy, sum_dy_xhat]).detach()
                fused_g = _allreduce_sum(fused).to(fused.dtype)
                c = sum_dy.numel()
                sum_dy_g = fused_g[:c]
                sum_dy_xhat_g = fused_g[c:]
                n = ctx.count_global
                g = (
                    weight.reshape(shape)
                    if weight is not None
                    else torch.ones_like(mean).reshape(shape)
                )
                dx = (
                    invstd.reshape(shape)
                    * g
                    * (
                        dy
                        - sum_dy_g.reshape(shape) / n
                        - xhat * sum_dy_xhat_g.reshape(shape) / n
                    )
                )
                # weight/bias grads stay local — DistributedOptimizer
                # reduces parameter grads, exactly like the reference.
                grad_weight = sum_dy_xhat if weight is not None else None
                grad_bias = sum_dy if weight is not None else None
                return dx, grad_weight, grad_bias, None, None, None

        cls._fn = Fn
        return Fn


def _sync_batch_norm_base():
    torch = _torch()

    class SyncBatchNorm(torch.nn.Module):
        """Drop-in for torch.nn.BatchNorm1d/2d/3d that synchronizes
        batch statistics across all horovod ranks during training
        (ref: horovod/torch/sync_batch_norm.py [V])."""

        def __init__(
            self,
            num_features: int,
            eps: float = 1e-5,
            momentum: Optional[float] = 0.1,
            affine: bool = True,
            track_running_stats: bool = True,
        ):
            super().__init__()
            self.num_features = num_features
            self.eps = eps
            self.momentum = momentum
            self.affine = affine
            self.track_running_stats = track_running_stats
            if affine:
                self.weight = torch.nn.Parameter(torch.ones(num_features))
                self.bias = torch.nn.Parameter(torch.zeros(num_features))
            else:
                self.register_parameter("weight", None)
                self.register_parameter("bias", None)
            if track_running_stats:
                self.register_buffer(
                    "running_mean", torch.zeros(num_features)
                )
                self.register_buffer("running_var", torch.ones(num_features))
                self.register_buffer(
                    "num_batches_tracked", torch.tensor(0, dtype=torch.long)
                )
            else:
                self.register_buffer("running_mean", None)
                self.register_buffer("running_var", None)
                self.register_buffer("num_batches_tracked", None)

        def forward(self, x):
            if x.dim() < 2:
                raise ValueError(
                    f"expected at least 2D input, got {x.dim()}D"
                )
            if x.shape[1] != self.num_features:
                raise ValueError(
                    f"expected {self.num_features} channels, got "
                    f"{x.shape[1]}"
                )
            if not self.training and not self.track_running_stats:
                # torch semantics: no running stats -> eval normalizes
                # with LOCAL batch statistics and performs NO collective
                # (torch.nn.SyncBatchNorm only syncs in training).
                dims = [0] + list(range(2, x.dim()))
                mean = x.mean(dims)
                var = x.var(dims, unbiased=False)
                shape = [1, -1] + [1] * (x.dim() - 2)
                out = (x - mean.reshape(shape)) * torch.rsqrt(
                    var + self.eps
                ).reshape(shape)
                if self.affine:
                    out = out * self.weight.reshape(shape) + (
                        self.bias.reshape(shape)
                    )
                return out
            if not self.training and self.track_running_stats:
                shape = [1, -1] + [1] * (x.dim() - 2)
                invstd = 1.0 / torch.sqrt(self.running_var + self.eps)
                out = (x - self.running_mean.reshape(shape)) * (
                    invstd.reshape(shape)
                )
                if self.affine:
                    out = out * self.weight.reshape(shape) + (
                        self.bias.reshape(shape)
                    )
                return out

            dims = [0] + list(range(2, x.dim()))
            count_local = float(x.numel() // x.shape[1])
            local_sum = x.sum(dims)
            local_sumsq = (x * x).sum(dims)
            # One fused vector (sum | sumsq | count) → one allreduce —
            # the reference performs the same sync with its
            # sync_batch_norm allgather/allreduce pair [V].
            fused = torch.cat(
                [
                    local_sum.detach(),
                    local_sumsq.detach(),
                    local_sum.new_tensor([count_local]),
                ]
            )
            fused_g = _allreduce_sum(fused).to(fused.dtype)
            c = self.num_features
            n = float(fused_g[2 * c].item())
            mean = fused_g[:c] / n
            var = fused_g[c : 2 * c] / n - mean * mean
            var = torch.clamp(var, min=0.0)
            invstd = 1.0 / torch.sqrt(var + self.eps)

            if self.track_running_stats:
                self.num_batches_tracked += 1
                m = (
                    self.momentum
                    if self.momentum is not None
                    else 1.0 / float(self.num_batches_tracked)
                )
                unbiased = var * (n / max(n - 1.0, 1.0))
                with torch.no_grad():
                    self.running_mean.mul_(1 - m).add_(mean, alpha=m)
                    self.running_var.mul_(1 - m).add_(unbiased, alpha=m)

            fn = _SyncBatchNormFunction.get()
            return fn.apply(x, self.weight, self.bias, mean, invstd, n)

        def extra_repr(self):
            return (
                f"{self.num_features}, eps={self.eps}, "
                f"momentum={self.momentum}, affine={self.affine}, "
                f"track_running_stats={self.track_running_stats}"
            )

    return SyncBatchNorm


_cls = None


def _get_class():
    """The real SyncBatchNorm class, built on first access so this file
    imports without torch. It IS a type: isinstance checks and user
    subclassing work like the reference's class."""
    global _cls
    if _cls is None:
        _cls = _sync_batch_norm_base()
    return _cls


def __getattr__(name):  # PEP 562: lazy module attribute
    if name == "SyncBatchNorm":
        return _get_class()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
