"""Elastic state objects: commit / restore / sync.

Rebuild of the reference's State hierarchy (ref:
horovod/common/elastic.py `State`/`ObjectState` +
horovod/torch/elastic/state.py `TorchState` [V] — SURVEY.md §2.5, §5.4):
a State wraps everything that must survive a membership change —
model/optimizer pytrees plus scalars like the step counter.

* ``commit()`` snapshots to host memory (the reference's in-memory
  checkpoint) and checks for pending host updates;
* ``restore()`` rolls back to the last commit after a failure;
* ``sync()`` re-replicates state across the (new) world at the top of
  every elastic retry.

``JaxState`` is the TorchState analog: registered pytrees are committed
with ``jax.device_get`` (host numpy) and restored with
``jax.device_put`` back to replicated placement on the current mesh —
after a gang restart the mesh object itself is new, which is why restore
re-resolves it through basics rather than caching shardings.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List

import jax
import numpy as np


class State:
    """Commit/restore/sync interface + reset callbacks
    (ref: horovod/common/elastic.py State [V])."""

    def __init__(self) -> None:
        self._reset_callbacks: List[Callable[[], None]] = []

    def register_reset_callbacks(
        self, callbacks: List[Callable[[], None]]
    ) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def commit(self) -> None:
        # The guard escalation fires BEFORE save: a commit is the act
        # of blessing the current state as a rollback point, and a job
        # that just skipped K consecutive non-finite steps must restore
        # to the PREVIOUS blessing, not mint a new one mid-incident.
        from ..common import guard as _guard

        _guard.check()
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        """Raise HostsUpdatedInterrupt when the driver signalled a
        membership change (delivered via WorkerNotificationManager)."""
        from .worker import notification_manager

        notification_manager.raise_if_updated()

    # subclass surface
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """State over plain-Python attributes; commit = deepcopy
    (ref: ObjectState [V])."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._saved: Dict[str, Any] = {}
        for key, value in kwargs.items():
            setattr(self, key, value)
        self._known = list(kwargs)
        ObjectState.save(self)

    def _attrs(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._known}

    def save(self) -> None:
        self._saved = copy.deepcopy(self._attrs())

    def restore(self) -> None:
        for key, value in copy.deepcopy(self._saved).items():
            setattr(self, key, value)

    def sync(self) -> None:
        """Broadcast plain attributes from the root across processes
        (rank 0's values win — ref: ObjectState.sync broadcast_object
        [V])."""
        from ..optimizer import broadcast_object

        synced = broadcast_object(self._attrs(), root_rank=0)
        for key, value in synced.items():
            setattr(self, key, value)


class JaxState(ObjectState):
    """State whose pytree attributes are device arrays (params,
    opt_state, batch_stats, ...). Scalars ride the ObjectState path;
    pytrees are snapshotted to host numpy and re-placed on the current
    mesh, replicated, on restore/sync — the broadcast-from-root that
    TorchState does with hvd.broadcast_parameters [V].

    ZeRO note: a ShardedDistributedOptimizer state carries a leading
    [world] axis; after a WORLD-SIZE change, run it through
    ``opt.reshard_state(state.opt_state, state.params, hvd.size())``
    in your reset/on_hosts_updated callback before training resumes —
    it carries the optimizer moments (and, at zero_stage>=2, the guard
    counters and error-feedback wire residuals) across the new gang
    instead of resetting them. At zero_stage=3 the PARAMETERS are a
    [world, cols] shard-row tree too: register the row tree (not full
    params) and additionally run
    ``opt.reshard_params(state.pstate, params_template, hvd.size())``
    — both trees ride this class unchanged, since commit/restore/sync
    only ever device_get/device_put them (docs/api.md,
    tests/test_zero.py).
    """

    _TREE_PREFIX = "_tree_"

    def __init__(self, **kwargs: Any) -> None:
        trees = {
            k: v for k, v in kwargs.items() if self._is_tree(v)
        }
        scalars = {k: v for k, v in kwargs.items() if k not in trees}
        self._trees: Dict[str, Any] = {}
        self._trees_saved: Dict[str, Any] = {}
        # registered data cursors (samplers/datasets with
        # state_dict/load_state_dict): committed and rolled back WITH
        # the model state, so an elastic restore rewinds the sample
        # stream to the same point as the parameters — exactly-once
        # delivery under the commit/restore contract
        self._data: Dict[str, Any] = {}
        self._data_saved: Dict[str, Dict] = {}
        super().__init__(**scalars)
        for key, value in trees.items():
            self._trees[key] = value
        self.save()

    @staticmethod
    def _is_tree(value: Any) -> bool:
        leaves = jax.tree_util.tree_leaves(value)
        return any(
            isinstance(leaf, (jax.Array, np.ndarray)) for leaf in leaves
        )

    def __getattr__(self, name: str):
        # only called when normal lookup fails
        trees = object.__getattribute__(self, "__dict__").get("_trees", {})
        if name in trees:
            return trees[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        if name != "_trees" and hasattr(self, "_trees") and name in self._trees:
            self._trees[name] = value
        else:
            object.__setattr__(self, name, value)

    def register_data(self, name: str, obj: Any) -> "JaxState":
        """Attach a data-cursor carrier (``ShardedIndexSampler`` /
        ``ShardedFileDataset`` — anything with ``state_dict()`` /
        ``load_state_dict()``): its cursor is snapshotted at every
        ``save()``/``commit()`` and rewound on ``restore()``, and
        ``DurableJaxState`` persists it beside the model tree so a
        full-job restart resumes the epoch at the exact next sample.

        Use a WORLD-SIZE-INDEPENDENT name set (one name per logical
        stream — e.g. ``"train"`` — not one per rank): the cursor is
        global, every rank's sampler reports the same one, and the
        durable tree's structure must match across a gang resize for
        the restore to land. Returns self for chaining."""
        if not (
            hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict")
        ):
            raise TypeError(
                f"register_data({name!r}): object has no "
                "state_dict/load_state_dict"
            )
        self._data[name] = obj
        self._data_saved[name] = dict(obj.state_dict())
        return self

    def save(self) -> None:
        super().save()
        self._trees_saved = {
            key: jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
            for key, tree in self._trees.items()
        }
        self._data_saved = {
            key: dict(obj.state_dict()) for key, obj in self._data.items()
        }

    def _replicate(self, tree):
        from ..common import basics
        from ..common.topology import replicated_sharding

        if not basics.is_initialized():
            return jax.tree_util.tree_map(jax.numpy.asarray, tree)
        sharding = replicated_sharding(basics.mesh())
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), tree
        )

    def restore(self) -> None:
        super().restore()
        for key, host_tree in self._trees_saved.items():
            self._trees[key] = self._replicate(host_tree)
        for key, snap in self._data_saved.items():
            obj = self._data.get(key)
            if obj is not None:
                obj.load_state_dict(dict(snap))

    def sync(self) -> None:
        super().sync()
        for key, tree in self._trees.items():
            self._trees[key] = self._replicate(jax.device_get(tree))
