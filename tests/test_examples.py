"""Examples must keep running — they are the user-facing contract
(the reference ships its examples as de-facto integration tests via CI
[V], SURVEY.md §4.5)."""

import os
import re
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from _hermetic import hermetic_cpu_env  # noqa: E402


def _run_example(name, *args, timeout=600):
    # Examples must never contend for the single real chip.
    env = hermetic_cpu_env()
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", name), *args],
        env=env,
        capture_output=True,
        timeout=timeout,
        text=True,
    )
    assert proc.returncode == 0, (
        f"{name} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    return proc.stdout


@pytest.mark.slow
def test_mnist_example():
    out = _run_example(
        "mnist.py", "--epochs", "1", "--steps-per-epoch", "3",
        "--batch-size", "8",
    )
    assert "eval accuracy" in out


@pytest.mark.slow
def test_synthetic_benchmark_example():
    out = _run_example(
        "synthetic_benchmark.py", "--model", "mnist", "--batch-size", "8",
        "--num-iters", "1", "--num-batches-per-iter", "2",
        "--num-warmup-batches", "1",
    )
    assert "Total img/sec" in out


@pytest.mark.slow
def test_transformer_lm_example():
    out = _run_example("transformer_lm.py", "--steps", "4")
    assert "loss decreased" in out


@pytest.mark.slow
def test_llama_shape_example():
    out = _run_example("llama_shape_train.py", "--steps", "6")
    assert "llama-shape loss" in out


@pytest.mark.slow
def test_long_context_ring_example():
    out = _run_example(
        "long_context_ring.py", "--seq-len", "512", "--steps", "4"
    )
    assert "512 tokens over 8 chips" in out


@pytest.mark.slow
def test_pipeline_1f1b_example():
    out = _run_example(
        "pipeline_1f1b_train.py", "--steps", "8", "--pp", "4"
    )
    assert "1F1B (pp=4, v=1, 4 global stages) works" in out


@pytest.mark.slow
def test_pipeline_1f1b_interleaved_example():
    out = _run_example(
        "pipeline_1f1b_train.py",
        "--steps", "8", "--pp", "2", "--virtual-stages", "2",
    )
    assert "1F1B (pp=2, v=2, 4 global stages) works" in out


@pytest.mark.slow
def test_elastic_example():
    out = _run_example("elastic_train.py")
    assert "elastic training complete" in out


@pytest.mark.slow
def test_estimator_example():
    out = _run_example("estimator_train.py", "--epochs", "2")
    assert "save/load round-trip ok" in out


@pytest.mark.slow
def test_torch_mnist_example():
    pytest.importorskip("torch")
    out = _run_example("torch_mnist.py", "--epochs", "1", "--batch-size",
                       "128")
    assert "torch shim example done" in out


@pytest.mark.slow
def test_tensorflow2_mnist_example():
    pytest.importorskip("tensorflow")
    out = _run_example("tensorflow2_mnist.py", "--steps", "25")
    assert "tf2 shim example done" in out


@pytest.mark.slow
def test_zero1_example():
    out = _run_example("zero1_data_parallel.py")
    assert re.search(r"\dx smaller", out)


@pytest.mark.slow
def test_tensorflow2_keras_mnist_example():
    pytest.importorskip("tensorflow")
    out = _run_example(
        "tensorflow2_keras_mnist.py", "--steps", "4", "--batch", "8",
    )
    assert "DONE" in out
