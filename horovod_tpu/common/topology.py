"""Device topology discovery and world-mesh construction.

TPU-native replacement for the reference's rank/communicator bootstrap
(ref: horovod/common/mpi/mpi_context.cc + horovod/common/gloo/gloo_context.cc
[V], SURVEY.md §2.1): where the reference derives (rank, local_rank,
cross_rank) from MPI communicators or rendezvous env vars, we derive them from
the JAX runtime's view of the TPU slice, with the ``HOROVOD_*`` env contract
as an override so the runner keeps working.

Rank semantics on TPU (documented divergence, SURVEY.md §7.1): Horovod runs
one process per accelerator; single-controller JAX runs one process per host
driving ``local_size`` chips. We keep Horovod's *one rank per chip* contract:

- ``size``        = total chips in the slice (the parallel width),
- ``local_size``  = chips driven by this process,
- ``rank``        = global index of this process's lead chip,
- ``cross_rank``  = this process's index among processes (one per host),
- ``cross_size``  = number of processes.

Per-chip rank identity inside a collective is ``lax.axis_index('hvd')`` in
traced code; eager helpers (`shard_from_rank_fn`) construct rank-dependent
global arrays.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import Config

# The canonical data-parallel ("world") mesh axis name, used everywhere the
# reference would say "the global communicator".
WORLD_AXIS = "hvd"

# Canonical axis names of the two-level ("inter", "intra") world mesh:
# ``intra`` rides ICI within a slice, ``inter`` rides DCN across slices.
# The inter NAME is overridable (HOROVOD_INTER_AXIS) for deployments
# whose own meshes already spell the DCN axis differently.
INTRA_AXIS = "intra"
INTER_AXIS = "inter"


@dataclasses.dataclass(frozen=True)
class Topology:
    """Immutable view of the slice this job runs on."""

    devices: tuple  # all addressable + non-addressable devices, rank order
    process_index: int
    process_count: int
    local_device_count: int

    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def local_size(self) -> int:
        return self.local_device_count

    @property
    def rank(self) -> int:
        return self.process_index * self.local_device_count

    @property
    def local_rank(self) -> int:
        return 0

    @property
    def cross_rank(self) -> int:
        return self.process_index

    @property
    def cross_size(self) -> int:
        return self.process_count

    def world_mesh(self) -> Mesh:
        """1-D mesh over every chip: the global communicator equivalent."""
        return Mesh(np.asarray(self.devices), (WORLD_AXIS,))

    def sub_mesh(self, ranks: Sequence[int]) -> Mesh:
        """Mesh over a subset of chips — the process-set communicator
        equivalent (ref: horovod/common/process_set.cc [V])."""
        devs = np.asarray([self.devices[r] for r in ranks])
        return Mesh(devs, (WORLD_AXIS,))

    @property
    def intra_size(self) -> int:
        """Chips per slice (the ICI-connected unit) — the L of the
        two-level decomposition. Detected from the JAX devices'
        ``slice_index`` when they expose one, else the process
        structure; ``HOROVOD_INTRA_SIZE`` overrides. Degrades to
        ``gcd(intra, world)`` when the override no longer divides an
        elastically resized world."""
        return detect_intra_size(
            self.devices, self.local_device_count, self.process_count
        )

    def two_level_mesh(
        self, intra_size: Optional[int] = None, inter_axis: Optional[str] = None
    ) -> Mesh:
        """The 2-axis ``(inter, intra)`` world mesh alongside the flat
        ``"hvd"`` axis — the TPU shape of the reference's node
        hierarchy (NCCL intra-node + MPI inter-node,
        HOROVOD_HIERARCHICAL_ALLREDUCE [V]). Devices stay in rank
        order, reshaped ``[world/L, L]``; the inter axis name follows
        ``HOROVOD_INTER_AXIS`` (default ``"inter"``)."""
        if intra_size is None:
            intra_size = self.intra_size
        if inter_axis is None:
            inter_axis = Config.from_env().inter_axis
        devices = np.asarray(self.devices)
        if intra_size < 1 or devices.size % intra_size:
            raise ValueError(
                f"intra_size {intra_size} must divide world {devices.size}"
            )
        grid = devices.reshape(devices.size // intra_size, intra_size)
        return Mesh(grid, (inter_axis, INTRA_AXIS))


def discover(config: Optional[Config] = None) -> Topology:
    """Build the topology from the JAX runtime and validate it against the
    HOROVOD_* env contract.

    The reference learns world shape from MPI_Init or rendezvous env
    (HOROVOD_RANK/SIZE/...); under JAX those arrive via
    ``jax.distributed.initialize``, which the runner performs before user
    code. When the launcher additionally exported HOROVOD_RANK/SIZE/...,
    they must agree with what the runtime reports — a silent mismatch
    would mean the job is running on a different slice than the launcher
    assigned, so it is an error.
    """
    devices = tuple(jax.devices())
    topo = Topology(
        devices=devices,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
    )
    if config is not None:
        checks = [
            ("HOROVOD_SIZE", config.size, topo.size),
            ("HOROVOD_LOCAL_SIZE", config.local_size, topo.local_size),
            ("HOROVOD_CROSS_SIZE", config.cross_size, topo.cross_size),
            ("HOROVOD_RANK", config.rank, topo.rank),
            ("HOROVOD_LOCAL_RANK", config.local_rank, topo.local_rank),
            ("HOROVOD_CROSS_RANK", config.cross_rank, topo.cross_rank),
        ]
        mismatches = [
            f"{name}={want} but the JAX runtime reports {got}"
            for name, want, got in checks
            if want is not None and want != got
        ]
        if mismatches:
            raise ValueError(
                "HOROVOD_* env contract does not match the discovered "
                "slice topology: " + "; ".join(mismatches)
            )
    return topo


# ---------------------------------------------------------------------------
# Two-level (intra-slice / inter-slice) topology detection.
#
# Everything below answers one question: where is the slice boundary —
# the point past which bytes leave ICI and cross DCN? The answer drives
# the hierarchical wire (ops/traced.py recipe family) that the fused
# dispatcher, the overlap buckets and the ZeRO exchange legs route
# through by default (HOROVOD_HIERARCHICAL).
# ---------------------------------------------------------------------------


def _gcd_degrade(intra: int, world: int) -> int:
    """Largest split compatible with ``world``: a non-dividing intra
    size (an elastic 8 -> 6 reshard under HOROVOD_INTRA_SIZE=4)
    degrades to gcd(intra, world) — the two-level world survives the
    resize with a coarser but valid slice boundary instead of
    crashing, and a gcd of 1 falls back to flat."""
    if intra < 1:
        return 1
    if world % intra == 0:
        return intra
    return math.gcd(intra, world)


def _slice_index_split(devices) -> Optional[int]:
    """Chips per slice from the devices' ``slice_index`` attribute
    (multi-slice TPU runtimes expose it), or None when the devices
    don't expose one / only one slice exists / slices are uneven."""
    indices = []
    for d in devices:
        si = getattr(d, "slice_index", None)
        if si is None:
            return None
        indices.append(si)
    counts: dict = {}
    for si in indices:
        counts[si] = counts.get(si, 0) + 1
    if len(counts) < 2:
        return None
    sizes = set(counts.values())
    if len(sizes) != 1:
        return None  # uneven slices: no uniform two-level split
    return sizes.pop()


def detect_intra_size(
    devices=(),
    local_device_count: int = 1,
    process_count: int = 1,
    override: Optional[int] = None,
) -> int:
    """The L of the two-level world. Resolution order:

    1. ``override`` / ``HOROVOD_INTRA_SIZE`` — the operator knows the
       topology;
    2. JAX device ``slice_index`` groups (multi-slice runtimes);
    3. process structure: >1 process with >1 chip each reads as one
       slice per process (the single-controller-per-host contract);
    4. otherwise the whole world is one slice.

    Non-dividing answers degrade via gcd (see :func:`_gcd_degrade`) so
    the split survives elastic resizes."""
    world = max(len(devices), 1)
    if override is None:
        override = Config.from_env().intra_size
    if override is not None:
        return _gcd_degrade(int(override), world)
    split = _slice_index_split(devices)
    if split is not None:
        return _gcd_degrade(split, world)
    if 1 < local_device_count < world:
        # one controller per slice: its addressable chips are the slice
        # (covers the multi-process runtime, where local·processes =
        # world, and a topology whose local count was pinned smaller)
        return _gcd_degrade(int(local_device_count), world)
    return world


def hierarchical_stage_groups(world: int, local: int):
    """Replica groups for the two-level decomposition, or None when the
    hierarchy degenerates (single slice, or slices of one chip):
    stage 1 = one group per slice (intra, ICI), stage 2 = one group per
    slice-local slot across slices (inter, DCN). Summing stage 1 then
    stage 2 equals the flat world sum."""
    if local <= 1 or world <= local or world % local:
        return None
    hosts = world // local
    intra = [list(range(h * local, (h + 1) * local)) for h in range(hosts)]
    inter = [[i + h * local for h in range(hosts)] for i in range(local)]
    return intra, inter


def hierarchy_stages(
    world: Optional[int] = None,
    mode: Optional[str] = None,
    intra: Optional[int] = None,
):
    """THE routing decision every hierarchical-by-default wire consults
    (fused dispatcher, overlap buckets, ZeRO legs): the two-level
    ``(intra_groups, inter_groups)`` replica groups of the current
    topology, or None when bytes never leave the slice.

    ``mode`` defaults to ``HOROVOD_HIERARCHICAL``:

    * ``off``  — always None (flat wire everywhere);
    * ``on``   — the split whenever one is resolvable (an explicit
      ``HOROVOD_INTRA_SIZE`` works even on a single host — the test /
      bench posture);
    * ``auto`` — the split only when a REAL inter axis exists: an
      explicit override, distinct device ``slice_index`` values, or a
      multi-process runtime driving >1 chip per process. A single-slice
      job never pays the two-stage decomposition.

    The legacy ``HOROVOD_HIERARCHICAL_ALLREDUCE=1`` is honored as
    ``on``. ``world`` defaults to the discovered topology's size; pass
    the traced axis size when deciding inside a shard_mapped program.
    """
    from . import basics as _basics

    cfg = (
        _basics.state().config
        if _basics.is_initialized() and _basics.state().config is not None
        else Config.from_env()
    )
    if mode is None:
        mode = cfg.hierarchical
        if (
            cfg.hierarchical_allreduce or cfg.hierarchical_allgather
        ) and mode != "off":
            mode = "on"
    if mode == "off":
        return None
    topo = _basics.state().topology if _basics.is_initialized() else None
    devices = topo.devices if topo is not None else ()
    local_count = topo.local_device_count if topo is not None else 1
    proc_count = topo.process_count if topo is not None else 1
    if world is None:
        world = len(devices) or 1
    if intra is None:
        if mode == "auto":
            # require positive evidence of a second level
            evidence = (
                cfg.intra_size is not None
                or _slice_index_split(devices) is not None
                or (proc_count > 1 and local_count > 1)
            )
            if not evidence:
                return None
        if cfg.intra_size is not None:
            # the override stands on its own (trace-time decisions may
            # run before hvd.init, when no device list exists yet)
            intra = cfg.intra_size
        else:
            intra = detect_intra_size(devices, local_count, proc_count)
    intra = _gcd_degrade(int(intra), int(world))
    return hierarchical_stage_groups(int(world), intra)


def stage_positions(groups) -> "np.ndarray":
    """Static [world] int32 table: each rank's index WITHIN its group —
    the lookup the grouped quantized recipes need for chunk ownership
    (position-j members across groups exchange chunk j)."""
    world = sum(len(g) for g in groups)
    pos = np.zeros(world, dtype=np.int32)
    for g in groups:
        for j, r in enumerate(g):
            pos[r] = j
    return pos


# ---------------------------------------------------------------------------
# Rank-major global arrays: the eager-mode data model.
#
# An eager Horovod collective sees one same-shaped tensor per rank. Under a
# single controller the natural representation is one global jax.Array with a
# leading "rank" axis of length `size`, sharded over the world mesh so row r
# lives on chip r. Collectives over it lower to real ICI collectives.
# ---------------------------------------------------------------------------


def rank_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(WORLD_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_from_rank_fn(
    fn: Callable[[int], np.ndarray], mesh: Mesh, dtype=None
) -> jax.Array:
    """Build a rank-major global array where row r = fn(r), placed on chip r.

    Test/benchmark helper mirroring the reference's per-rank tensor
    construction pattern (`tensor = torch.ones(...) * hvd.rank()` in
    test/parallel/test_torch.py [V]).
    """
    n = mesh.devices.size
    rows = [np.asarray(fn(r), dtype=dtype) for r in range(n)]
    stacked = np.stack(rows, axis=0)
    return jax.device_put(stacked, rank_sharding(mesh))
