"""Data-sharding utilities: the input-pipeline half of the porting
recipe.

The reference leans on each framework's loader plus a rank-sharding
idiom (ref: examples use
``torch.utils.data.distributed.DistributedSampler(dataset,
num_replicas=hvd.size(), rank=hvd.rank())`` [V]); the TPU-native
equivalents here serve the same three needs without assuming torch:

* :class:`ShardedIndexSampler` — the DistributedSampler analog: a
  rank's epoch-shuffled slice of ``range(n)``, padded to equal length
  (SPMD needs identical step counts everywhere).
* :func:`shard_array` — slice host arrays by rank (the synthetic-data
  examples' one-liner).
* :func:`prefetch_to_device` — overlap host→device transfer with
  compute by keeping ``size`` batches in flight (the tf.data
  ``prefetch`` role for plain Python iterators).
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterable, Iterator, Optional

import numpy as np


class ShardedIndexSampler:
    """Per-rank index sampler with epoch shuffling (ref:
    DistributedSampler semantics [V]: equal-length shards, optional
    shuffle keyed by (seed, epoch), padding by wrap-around).

    **Reshard determinism + exactly-once resume** (the elastic data
    contract): the epoch's global order is keyed by ``(seed, epoch)``
    ONLY — never by the world size — and each rank takes the
    ``rank::num_replicas`` stripe of it, so an 8→6 reshard mid-run
    walks a suffix of the *same* global permutation instead of a fresh
    one. :meth:`state_dict` captures a GLOBAL cursor (the SPMD
    contract — every rank has consumed equally — makes
    ``consumed_per_rank × num_replicas`` exact); :meth:`load_state_dict`
    seeks the epoch to it, under any world size: the remaining indices
    are re-striped over the new replica count, so across a
    save/kill/restore cycle no sample inside the epoch is replayed or
    dropped (up to the usual wrap-around padding on ragged tails).
    """

    def __init__(
        self,
        n: int,
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        from .common import basics

        self.n = int(n)
        self.num_replicas = (
            num_replicas if num_replicas is not None else basics.size()
        )
        self.rank = rank if rank is not None else basics.rank()
        if not 0 <= self.rank < self.num_replicas:
            raise ValueError(
                f"rank {self.rank} out of range for "
                f"{self.num_replicas} replicas"
            )
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # exactly-once cursor: global offset into the epoch's order
        # (samples consumed across ALL ranks) + this iteration's
        # per-rank progress
        self._start = 0
        self._consumed = 0
        if drop_last:
            self.num_samples = self.n // self.num_replicas
        else:
            self.num_samples = -(-self.n // self.num_replicas)  # ceil

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle differently each epoch (same contract as the
        torch sampler — call before iterating). Resets the mid-epoch
        cursor: a new epoch starts from its beginning."""
        self.epoch = int(epoch)
        self._start = 0
        self._consumed = 0

    def _epoch_order(self) -> np.ndarray:
        """The epoch's GLOBAL sample order — a function of
        ``(seed, epoch)`` alone, so every world size walks the same
        permutation (reshard determinism)."""
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            return rng.permutation(self.n)
        return np.arange(self.n)

    def _per_rank_remaining(self) -> int:
        remaining = max(self.n - self._start, 0)
        if self.drop_last:
            return remaining // self.num_replicas
        return -(-remaining // self.num_replicas)  # ceil

    def __len__(self) -> int:
        """Per-rank items the NEXT iteration will yield — the full
        epoch from a fresh sampler, the remainder after a mid-epoch
        :meth:`load_state_dict` seek."""
        return self._per_rank_remaining()

    def state_dict(self) -> dict:
        """The resumable cursor: epoch + GLOBAL position. Capture it at
        a commit boundary (DurableJaxState does); loading it into a
        fresh sampler — of ANY world size — continues the epoch at the
        exact next unseen sample."""
        return {
            "epoch": int(self.epoch),
            "cursor": int(
                self._start + self._consumed * self.num_replicas
            ),
            "seed": int(self.seed),
        }

    def load_state_dict(self, state: dict) -> None:
        """Seek to a :meth:`state_dict` cursor. A cursor at/past ``n``
        means the epoch was fully consumed (the tail the saver saw was
        wrap-around padding): the next iteration yields nothing and
        the caller advances the epoch as usual."""
        if int(state.get("seed", self.seed)) != self.seed:
            raise ValueError(
                f"sampler state has seed {state.get('seed')} but this "
                f"sampler uses {self.seed}; the epoch orders would "
                "disagree and the cursor would be meaningless"
            )
        self.epoch = int(state["epoch"])
        self._start = min(max(int(state["cursor"]), 0), self.n)
        self._consumed = 0

    def __iter__(self) -> Iterator[int]:
        rem = self._epoch_order()[self._start:]
        per = self._per_rank_remaining()
        total = per * self.num_replicas
        if self.drop_last:
            rem = rem[:total]
        else:
            # wrap-around padding so every rank sees ``per`` items;
            # np.resize repeats the remainder as many times as needed
            # (n < num_replicas included — a single rem[:pad] slice
            # would underfill the high ranks and deadlock SPMD loops).
            if total > len(rem):
                rem = np.resize(rem, total)
        mine = rem[self.rank :: self.num_replicas].tolist()
        self._consumed = 0

        def _gen():
            for i, idx in enumerate(mine):
                self._consumed = i + 1
                yield idx

        return _gen()


def shard_array(x, num_replicas: Optional[int] = None,
                rank: Optional[int] = None):
    """This rank's contiguous dim-0 shard of a host array (drops the
    ragged tail so shards are equal — SPMD shape discipline)."""
    from .common import basics

    num_replicas = (
        num_replicas if num_replicas is not None else basics.size()
    )
    rank = rank if rank is not None else basics.rank()
    x = np.asarray(x)
    per = x.shape[0] // num_replicas
    if per == 0:
        raise ValueError(
            f"cannot shard dim0={x.shape[0]} across {num_replicas} ranks"
        )
    return x[rank * per : (rank + 1) * per]


def prefetch_to_device(
    iterator: Iterable,
    size: int = 2,
    devices=None,
    sharding=None,
):
    """Wrap a host batch iterator so device transfer runs ahead of
    compute: ``size`` batches are put on device before the first yield
    and one more is enqueued per step (jax device puts are async, so
    the copy of batch t+1 overlaps the compute of batch t).

    ``sharding`` (a jax.sharding.Sharding) places each pytree leaf;
    default is the first addressable device.
    """
    import jax

    if sharding is None:
        dev = (devices or jax.local_devices())[0]
        put = lambda t: jax.device_put(t, dev)  # noqa: E731
    else:
        put = lambda t: jax.device_put(t, sharding)  # noqa: E731

    queue = collections.deque()
    it = iter(iterator)

    def enqueue(k: int) -> None:
        for batch in itertools.islice(it, k):
            queue.append(jax.tree_util.tree_map(put, batch))

    enqueue(max(int(size), 1))
    while queue:
        yield queue.popleft()
        enqueue(1)


def write_shards(
    path, x, y=None, rows_per_shard: int = 4096, compressed: bool = True
) -> int:
    """Materialize arrays as a shard directory readable by
    :class:`ShardedFileDataset` — the writer half of the reference's
    Store/Petastorm data-materialization step (ref:
    horovod/spark/common/util.py prepare_data → parquet row groups [V]).

    ``compressed=True`` (default) writes ``shard_NNNNN.npz`` (zip
    container); ``compressed=False`` writes raw ``shard_NNNNN.x.npy``
    (+ ``.y.npy``) pairs — larger on disk but readable by the NATIVE
    mmap row-gather (csrc/npyio.cc), the fast path for shuffled access
    to datasets bigger than memory. Returns the number of shards."""
    import os

    os.makedirs(path, exist_ok=True)
    x = np.asarray(x)
    n = x.shape[0]
    if y is not None:
        y = np.asarray(y)
        if y.shape[0] != n:
            raise ValueError(
                f"x has {n} rows but y has {y.shape[0]}"
            )
    k = 0
    for start in range(0, n, rows_per_shard):
        sl = slice(start, start + rows_per_shard)
        if compressed:
            fname = os.path.join(path, f"shard_{k:05d}.npz")
            if y is None:
                np.savez(fname, x=x[sl])
            else:
                np.savez(fname, x=x[sl], y=y[sl])
        else:
            stem = os.path.join(path, f"shard_{k:05d}")
            np.save(stem + ".x.npy", x[sl])
            if y is not None:
                np.save(stem + ".y.npy", y[sl])
        k += 1
    return k


def _npz_member_shape(path: str, member: str):
    """Shape/dtype of one array inside an .npz WITHOUT loading its data
    (reads only the npy header from the zip member)."""
    import zipfile

    from numpy.lib import format as npfmt

    with zipfile.ZipFile(path) as z:
        with z.open(member + ".npy") as m:
            version = npfmt.read_magic(m)
            if version == (1, 0):
                shape, _, dtype = npfmt.read_array_header_1_0(m)
            else:
                shape, _, dtype = npfmt.read_array_header_2_0(m)
    return shape, dtype


class ShardedFileDataset:
    """Per-rank batch iterable over a directory of ``.npz`` shards — the
    Petastorm-reader slot of the reference's Spark stack (ref:
    horovod/spark: materialized parquet + petastorm ``make_reader``
    feeding each rank a disjoint row subset [V]).

    Semantics match :class:`ShardedIndexSampler`: the GLOBAL row space
    (concatenated over shard files) is epoch-shuffled with a
    ``(seed, epoch)`` key, split into equal-length rank slices (padding
    by wrap-around — SPMD needs identical step counts everywhere), and
    served as ``(x_batch, y_batch)`` numpy pairs (or bare ``x_batch``
    for label-less directories). Shard files are loaded lazily with a
    small LRU cache, so datasets far larger than memory stream through.

    Feed it straight to :func:`prefetch_to_device`, or pass it to
    ``TpuEstimator.fit`` (which re-iterates it per epoch and advances
    ``set_epoch`` automatically).
    """

    def __init__(
        self,
        path: str,
        batch_size: int,
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = True,
        seed: int = 0,
        cache_files: int = 2,
    ):
        import glob
        import os

        self.path = path
        self.batch_size = int(batch_size)
        files = sorted(glob.glob(os.path.join(path, "*.npz")))
        self._fmt = "npz"
        if not files:
            # uncompressed pairs: the native mmap-gather format
            files = sorted(glob.glob(os.path.join(path, "*.x.npy")))
            self._fmt = "npy"
        if not files:
            raise ValueError(
                f"no .npz or .x.npy shard files under {path!r}"
            )
        self.files = files
        self.has_labels = True
        counts = []
        for f in files:
            if self._fmt == "npz":
                shape, _ = _npz_member_shape(f, "x")
                counts.append(shape[0])
                try:
                    _npz_member_shape(f, "y")
                except KeyError:
                    self.has_labels = False
            else:
                mm = np.load(f, mmap_mode="r")
                counts.append(mm.shape[0])
                del mm
                if not os.path.exists(f[: -len(".x.npy")] + ".y.npy"):
                    self.has_labels = False
        self._offsets = np.concatenate([[0], np.cumsum(counts)])
        self.n = int(self._offsets[-1])
        self._sampler = ShardedIndexSampler(
            self.n,
            num_replicas=num_replicas,
            rank=rank,
            shuffle=shuffle,
            seed=seed,
        )
        self._cache: "collections.OrderedDict[int, dict]" = (
            collections.OrderedDict()
        )
        self._cache_files = max(int(cache_files), 1)
        self._batches_done = 0  # this iteration's progress (resume)

    # -- epoch control (DistributedSampler parity) ---------------------
    def set_epoch(self, epoch: int) -> None:
        self._sampler.set_epoch(epoch)
        self._batches_done = 0

    def __len__(self) -> int:
        """Batches the NEXT iteration yields per rank (ragged tail
        dropped: every jitted step needs one static shape); reflects a
        mid-epoch seek."""
        return len(self._sampler) // self.batch_size

    # -- exactly-once resume (elastic data contract) -------------------
    def state_dict(self) -> dict:
        """Epoch + GLOBAL sample cursor at batch granularity: batches
        already YIELDED this iteration are counted consumed (the saver
        commits after stepping on a batch, so the in-flight batch is
        behind the cursor, never replayed)."""
        st = self._sampler.state_dict()
        st["cursor"] = int(
            self._sampler._start
            + self._batches_done
            * self.batch_size
            * self._sampler.num_replicas
        )
        return st

    def load_state_dict(self, state: dict) -> None:
        """Seek so the next ``__iter__`` starts at the exact next
        global index — across a save/SIGKILL/restore cycle AND across
        a world-size change (the remaining global order is re-striped
        over the new replica count)."""
        self._sampler.load_state_dict(state)
        self._batches_done = 0

    def _open_column(self, path: str):
        """One shard column: the native mmap row-gather when available
        (csrc/npyio.cc), else a numpy memmap (same semantics, Python
        fancy-index)."""
        from ._native import loader as _native

        reader = _native.npy_reader(path)
        if reader is not None:
            return reader
        return np.load(path, mmap_mode="r")

    def _load(self, file_i: int) -> dict:
        entry = self._cache.get(file_i)
        if entry is None:
            cols = ("x", "y") if self.has_labels else ("x",)
            if self._fmt == "npz":
                with np.load(self.files[file_i]) as z:
                    entry = {k: z[k] for k in cols}
            else:
                stem = self.files[file_i][: -len(".x.npy")]
                entry = {
                    k: self._open_column(f"{stem}.{k}.npy") for k in cols
                }
            self._cache[file_i] = entry
            if self._fmt == "npz":
                # npz entries are fully-loaded ARRAYS — bound the memory.
                # npy entries are mmap handles (pages live in the OS
                # cache, not here); evicting them would re-parse headers
                # on every shuffled batch, so they all stay open.
                while len(self._cache) > self._cache_files:
                    self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(file_i)
        return entry

    @staticmethod
    def _take(col, idx: np.ndarray) -> np.ndarray:
        if getattr(col, "_native_gather", False):
            return col.take(idx)  # one C call (csrc/npyio.cc)
        return np.asarray(col[idx])  # ndarray / memmap fancy index

    def _native_rows(self, global_idx: np.ndarray, file_is: np.ndarray):
        """Whole-batch scattered gather in ONE C call per column
        (csrc/npyio.cc hvd_npy_gather_scattered); None when the native
        library is off or the shards aren't uniform native readers."""
        from ._native import loader as _native

        if _native.get_lib() is None:
            return None
        touched = np.unique(file_is)
        entries = [self._load(int(fi)) for fi in touched]  # refs keep
        # evicted readers alive for the duration of the gather
        pos = np.zeros(int(touched[-1]) + 1, np.int64)
        pos[touched] = np.arange(len(touched))
        hsel = pos[file_is]
        local = (global_idx - self._offsets[file_is]).astype(np.int64)
        outs = []
        for col in ("x", "y") if self.has_labels else ("x",):
            readers = [e[col] for e in entries]
            if not all(
                getattr(r, "_native_gather", False) for r in readers
            ):
                return None
            if len({(r.dtype, r.shape[1:]) for r in readers}) != 1:
                return None  # non-uniform shards: generic path
            out = np.empty(
                (len(global_idx),) + readers[0].shape[1:],
                readers[0].dtype,
            )
            if not _native.npy_gather_scattered(readers, hsel, local, out):
                return None
            outs.append(out)
        return tuple(outs) if self.has_labels else outs[0]

    def _rows(self, global_idx: np.ndarray):
        file_is = (
            np.searchsorted(self._offsets, global_idx, side="right") - 1
        )
        if self._fmt == "npy":
            fast = self._native_rows(global_idx, file_is)
            if fast is not None:
                return fast
        # Group the batch's rows BY FILE: a shuffled batch touches many
        # shards, and loading per-row would decompress a whole .npz per
        # row and thrash the small LRU. One gather per touched file,
        # written back into batch order with a vectorized fancy store.
        order = np.argsort(file_is, kind="stable")
        x_out = y_out = None
        for fi in np.unique(file_is):
            sel = order[file_is[order] == fi]
            local = (global_idx[sel] - self._offsets[fi]).astype(np.int64)
            entry = self._load(int(fi))
            fx = self._take(entry["x"], local)
            if x_out is None:
                x_out = np.empty(
                    (len(global_idx),) + fx.shape[1:], fx.dtype
                )
            x_out[sel] = fx
            if self.has_labels:
                fy = self._take(entry["y"], local)
                if y_out is None:
                    y_out = np.empty(
                        (len(global_idx),) + fy.shape[1:], fy.dtype
                    )
                y_out[sel] = fy
        return (x_out, y_out) if self.has_labels else x_out

    def __iter__(self):
        self._batches_done = 0
        idx = np.fromiter(iter(self._sampler), dtype=np.int64)
        steps = len(idx) // self.batch_size
        for b in range(steps):
            sl = idx[b * self.batch_size: (b + 1) * self.batch_size]
            rows = self._rows(sl)
            self._batches_done = b + 1
            yield rows
