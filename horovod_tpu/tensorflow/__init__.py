"""``import horovod_tpu.tensorflow as hvd`` — gated TensorFlow binding.

Parity target: the reference's TF surface (ref:
horovod/tensorflow/__init__.py + mpi_ops.py + gradients.py [V] —
SURVEY.md §2.4, ~2,500 LoC). Scope decision (docs/design.md "Framework
bindings"): this module is a *gated minimal binding* — the same
host-bridge pattern as the torch shim (horovod_tpu/torch), delegating
every collective to the eager XLA path. It imports only when TF is
present; otherwise it raises immediately with this scope note rather
than failing somewhere deep inside a user script.

What is here when TF is available: init/rank/size identity, allreduce /
allgather / broadcast (sync + _async + in-place variants where TF
semantics allow), broadcast_variables, and DistributedGradientTape —
the TF2 idiom the reference's docs lead with (SURVEY.md §3.5).
Deliberately absent (would need TF to even design honestly): TF1
Session-era DistributedOptimizer, custom-op kernels (`mpi_ops.cc`) and
the XLA custom-call hooks (`xla_mpi_ops.cc`) — on TPU the XLA hook is
the *whole framework* (collectives are compiler-visible), so that row
is subsumed rather than missing.
"""

from __future__ import annotations

try:
    import tensorflow as tf  # noqa: F401
except Exception as _e:  # pragma: no cover - exercised only without TF
    raise ImportError(
        "horovod_tpu.tensorflow requires the 'tensorflow' package, which "
        "is not installed in this environment. This binding is a gated "
        "compatibility layer (see module docstring / docs/design.md); "
        "the TPU-native training path is the JAX API: "
        "`import horovod_tpu as hvd`."
    ) from _e

import numpy as np

from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from ..ops import eager as _eager
from ..ops.reduction_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
)


def _replicated_payload(tensor):
    return _eager.replicate(np.asarray(tensor))


class _TFHandle:
    def __init__(self, inner, like, post=None):
        self._inner = inner
        self._like = like
        self._post = post

    def poll(self):
        return self._inner.poll()

    def wait(self):
        host = np.asarray(_eager.first(self._inner.wait()))
        if self._post is not None:
            host = self._post(host)
        return tf.convert_to_tensor(host, dtype=self._like.dtype)


def allreduce_async(tensor, average=None, name=None, op=None,
                    process_set=None):
    handle = _eager.allreduce_async(
        _replicated_payload(tensor), average=average, name=name, op=op,
        process_set=process_set,
    )
    return _TFHandle(handle, tensor)


def allreduce(tensor, average=None, name=None, op=None, process_set=None):
    return allreduce_async(
        tensor, average=average, name=name, op=op, process_set=process_set
    ).wait()


def allgather_async(tensor, name=None, process_set=None):
    handle = _eager.allgather_async(
        _replicated_payload(tensor), name=name, process_set=process_set
    )
    return _TFHandle(
        handle, tensor,
        post=lambda host: host.reshape((-1,) + host.shape[2:]),
    )


def allgather(tensor, name=None, process_set=None):
    return allgather_async(tensor, name=name, process_set=process_set).wait()


def broadcast(tensor, root_rank, name=None, process_set=None):
    handle = _eager.broadcast_async(
        _replicated_payload(tensor), root_rank, name=name,
        process_set=process_set,
    )
    return _TFHandle(handle, tensor).wait()


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign root's values into ``variables`` in place (ref:
    hvd.broadcast_variables [V])."""
    for var in variables:
        var.assign(broadcast(var, root_rank, name=var.name))


class DistributedGradientTape:
    """Wrap a tf.GradientTape so gradient() allreduces the grads (ref:
    horovod/tensorflow/__init__.py DistributedGradientTape [V])."""

    def __init__(self, tape, op=None, process_set=None):
        self._tape = tape
        self._op = op
        self._process_set = process_set

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def _reduce_one(self, g):
        if g is None:
            return None
        if isinstance(g, tf.IndexedSlices):
            raise NotImplementedError(
                "horovod_tpu.tensorflow does not reduce sparse "
                "(IndexedSlices) gradients; densify with "
                "tf.convert_to_tensor(g) first"
            )
        return allreduce(g, op=self._op, process_set=self._process_set)

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        # **kwargs forwards tf.GradientTape extras (unconnected_gradients)
        # so the wrapper stays a drop-in replacement.
        grads = self._tape.gradient(target, sources, output_gradients,
                                    **kwargs)
        # Mirror tf.GradientTape: single source in -> single grad out.
        if isinstance(grads, (list, tuple)):
            reduced = [self._reduce_one(g) for g in grads]
            return type(grads)(reduced) if isinstance(
                grads, tuple) else reduced
        return self._reduce_one(grads)
