"""Allreduce microbenchmark — bandwidth/latency across message sizes.

The harness behind the reference's headline claim (scaling efficiency of
allreduce-dominated training, docs/benchmarks.rst + the Horovod paper
fig. 5-6 [V]; BASELINE.md north star: allreduce scaling efficiency on an
8→256-chip sweep). On a pod slice this sweeps the whole world; on the
1-chip dev box it measures single-device round-trip overhead, and on the
CPU simulation it validates the sweep logic across an 8-way mesh.

Prints one JSON line per message size:
  {"metric": "allreduce_busbw", "bytes": N, "world": W,
   "value": GB/s, "unit": "GB/s", "lat_us": ...}

Bus bandwidth uses the standard ring-allreduce convention:
  busbw = bytes * 2*(W-1)/W / time
(equals algobw for W=1). Env: BENCH_PLATFORM=cpu for the simulated mesh,
BENCH_SIZES="1024,1048576" to override the sweep, BENCH_ITERS.
"""

import json
import os
import time
from functools import partial

import numpy as np


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.ops import traced

    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    sizes_env = os.environ.get("BENCH_SIZES")
    if sizes_env:
        sizes = [int(s) for s in sizes_env.split(",")]
    else:
        sizes = [1 << p for p in range(10, 28, 2)]  # 1 KB .. 128 MB

    for nbytes in sizes:
        n = max(nbytes // 4, 1)  # float32 elements

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=P(hvd.WORLD_AXIS),
            out_specs=P(hvd.WORLD_AXIS),
            check_vma=False,
        )
        def reduce(x):
            return traced.allreduce(x[0], op=hvd.Sum)[None]

        step = jax.jit(reduce)
        x = jnp.ones((world, n), jnp.float32)
        out = step(x)  # compile + warm
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        ring_factor = 2.0 * (world - 1) / world if world > 1 else 1.0
        busbw = nbytes * ring_factor / dt / 1e9
        print(
            json.dumps(
                {
                    "metric": "allreduce_busbw",
                    "bytes": nbytes,
                    "world": world,
                    "value": round(busbw, 3),
                    "unit": "GB/s",
                    "lat_us": round(dt * 1e6, 1),
                }
            )
        )


if __name__ == "__main__":
    main()
