"""Multi-dimensional parallelism over TPU meshes.

The reference implements data parallelism only (SURVEY.md §2.6); the only
adjacent primitives it ships are alltoall (the expert-parallel building
block) and process sets. This package is the TPU-native superset the
survey's build plan calls for: the same collectives the reference exposes,
composed into tensor (tp), sequence/context (sp, ring attention), pipeline
(pp) and expert (ep) parallelism over a `jax.sharding.Mesh` — each axis
riding ICI via XLA collectives.
"""

from .mesh import MeshSpec  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention,
    ring_flash_attention,
)
from .tp import column_parallel_dense, row_parallel_dense  # noqa: F401
from .pipeline import gpipe, pipeline_1f1b  # noqa: F401
from .moe import MoEParams, moe_ffn, init_moe_params  # noqa: F401
from .fsdp import fsdp_shard, fsdp_sharding, fsdp_spec  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
