"""FSDP sharding helpers (parallel/fsdp.py): the GSPMD-path parameter
sharding rule, and an end-to-end jit training loop where params,
grads, and Adam state all live 1/N-sharded while XLA inserts the
gather/scatter collectives (PAPERS.md arXiv:2004.13336)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd_pkg
from horovod_tpu.parallel import fsdp_shard, fsdp_spec


def test_spec_rule(hvd):
    n = 8
    ax = hvd_pkg.WORLD_AXIS
    # largest divisible dim (24, dim 1) is sharded
    assert fsdp_spec(np.zeros((16, 24, 7)), n, min_elems=0) == P(
        None, ax, None
    )
    # no divisible dim -> replicate
    assert fsdp_spec(np.zeros((7, 9)), n, min_elems=0) == P()
    # tiny leaf -> replicate even when divisible
    assert fsdp_spec(np.zeros((8,)), n) == P()
    # scalar -> replicate
    assert fsdp_spec(np.asarray(1.0), n) == P()


def test_leaves_are_sharded_on_mesh(hvd):
    mesh = hvd_pkg.mesh()
    params = {
        "big": jnp.ones((128, 256), jnp.float32),
        "small": jnp.ones((4,), jnp.float32),
    }
    sharded = fsdp_shard(params, mesh)
    big_shard = sharded["big"].sharding
    assert isinstance(big_shard, NamedSharding)
    assert big_shard.spec != P()
    # per-device memory: 1/8 of the big leaf
    shard_shape = big_shard.shard_shape(sharded["big"].shape)
    assert np.prod(shard_shape) == 128 * 256 // 8
    assert sharded["small"].sharding.spec == P()


def test_jit_training_with_fsdp_params(hvd):
    """Full GSPMD loop: batch over the world axis, params/opt-state
    FSDP-sharded, plain jit — loss must drop and the params must STAY
    sharded across steps (XLA's weight-update sharding, not a gather-
    once-and-replicate)."""
    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(0)
    d_in, d_h = 64, 128
    params = {
        "w1": jnp.asarray(rng.normal(size=(d_in, d_h)) * 0.1, jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(d_h, 1)) * 0.1, jnp.float32),
    }
    params = fsdp_shard(params, mesh, min_elems=64)
    opt = optax.adam(1e-2)
    # GSPMD propagates the param shardings into zeros_like state
    opt_state = jax.jit(opt.init)(params)

    x = rng.normal(size=(64, d_in)).astype(np.float32)
    w = rng.normal(size=(d_in, 1)).astype(np.float32)
    y = (x @ w).astype(np.float32)
    data_sharding = NamedSharding(mesh, P(hvd_pkg.WORLD_AXIS))
    xb = jax.device_put(jnp.asarray(x), data_sharding)
    yb = jax.device_put(jnp.asarray(y), data_sharding)

    def loss_fn(p, xb, yb):
        h = jnp.tanh(xb @ p["w1"])
        return jnp.mean((h @ p["w2"] - yb) ** 2)

    @jax.jit
    def step(p, st, xb, yb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st, loss

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, xb, yb)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # params remained FSDP-sharded through the jitted updates
    assert params["w1"].sharding.spec != P()
    shard_shape = params["w1"].sharding.shard_shape(params["w1"].shape)
    assert np.prod(shard_shape) == d_in * d_h // 8
    # optimizer state too (Adam mu)
    mu = jax.tree_util.tree_leaves(opt_state)
    big_mu = [m for m in mu if getattr(m, "size", 0) == d_in * d_h]
    assert big_mu and big_mu[0].sharding.spec != P()
