"""Ring attention: exact attention over sequences sharded across chips.

Long-context sequence/context parallelism is absent from the reference
(SURVEY.md §5.7 — "no ring attention, no context parallel ... of any
kind"); the survey's build plan adds it as the TPU-native long-context
path: shard the sequence over the 'sp' mesh axis and rotate K/V blocks
around the ring with `ppermute` while accumulating attention online
(flash-attention-style running max/denominator), so each chip only ever
holds seq_len/sp keys — memory O(T/sp) with exact results, and each
ppermute hop overlaps with the block's compute on ICI.

Differentiation is a SECOND ring pass (custom VJP): the forward saves
only (q, k, v, out, lse); the backward recomputes each block's
probabilities from the logsumexp and rotates (k, v, dk, dv) together so
every gradient block arrives back at its owner having accumulated all
ranks' contributions. Without this, autodiff through the forward scan
would checkpoint per-step score matrices — O(sp·T_local²) residuals,
exactly the memory wall ring attention exists to avoid.

Per-device code for use inside shard_map. Causal masking uses global
positions derived from each block's rank of origin.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _ring_perm(sp):
    return [(j, (j + 1) % sp) for j in range(sp)]


def _block_scores(q, k_cur, scale, q_pos, k_pos, causal):
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk",
            q,
            k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    return s


def _ring_fwd_pass(q, k, v, axis_name, causal):
    sp = lax.axis_size(axis_name)
    # axis_index only matters for causal masking; when causal=False
    # the value would be dead code, and a dead cross-replica
    # primitive inside custom_vjp+shard_map lowers to a PartitionId
    # in the auto-SPMD region, which XLA rejects (JAX 0.4.x) —
    # skip it entirely on the non-causal path
    my = lax.axis_index(axis_name) if causal else 0
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    q_pos = my * t + jnp.arange(t)
    perm = _ring_perm(sp)

    def step(carry, i):
        k_cur, v_cur, out, m, denom = carry
        src = (my - i) % sp
        k_pos = src * t + jnp.arange(t)
        scores = _block_scores(qf, k_cur, scale, q_pos, k_pos, causal)
        block_max = jnp.max(scores, axis=-1)  # [B,H,Tq]
        new_m = jnp.maximum(m, block_max)
        # With causal masking a whole block can be -inf; guard the exp.
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
        p = jnp.exp(scores - safe_m[..., None])  # masked entries → 0
        denom = denom * correction + jnp.sum(p, axis=-1)
        out = out * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, out, new_m, denom), None

    out0 = jnp.zeros((b, h, t, d), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    denom0 = jnp.zeros((b, h, t), jnp.float32)
    (_, _, out, m, denom), _ = lax.scan(
        step, (k, v, out0, m0, denom0), jnp.arange(sp)
    )
    denom_safe = jnp.maximum(denom, 1e-30)
    out = out / denom_safe[..., None]
    # lse in the same guarded convention as the flash kernels
    lse = jnp.where(jnp.isfinite(m), m, 0.0) + jnp.log(denom_safe)
    return (
        jnp.einsum("bhqd->bqhd", out).astype(q.dtype),
        lse,  # [B, H, Tq] fp32
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_attention_mha(q, k, v, axis_name: str = "sp",
                        causal: bool = False):
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal)
    return out


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False):
    """q, k, v: [B, T_local, H, Dh] (this chip's sequence shard).

    Returns [B, T_local, H, Dh] — exact softmax(QKᵀ)V over the full
    (sp·T_local)-token sequence. Differentiable via the second-ring-pass
    VJP (module docstring). Grouped-query inputs (fewer kv heads) are
    repeated to full width here, OUTSIDE the custom VJP, so the
    repeat's transpose group-sums dk/dv — the dense path materializes
    scores anyway; use ``ring_flash_attention`` to keep the shared-KV
    saving."""
    if v.shape[2] != k.shape[2] or q.shape[2] % k.shape[2]:
        raise ValueError(
            "kv heads must match and divide q heads: "
            f"q={q.shape[2]}, k={k.shape[2]}, v={v.shape[2]}"
        )
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return _ring_attention_mha(q, k, v, axis_name, causal)


def _ring_attention_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_attention_bwd(axis_name, causal, res, do):
    q, k, v, out, lse = res
    sp = lax.axis_size(axis_name)
    # axis_index only matters for causal masking; when causal=False
    # the value would be dead code, and a dead cross-replica
    # primitive inside custom_vjp+shard_map lowers to a PartitionId
    # in the auto-SPMD region, which XLA rejects (JAX 0.4.x) —
    # skip it entirely on the non-causal path
    my = lax.axis_index(axis_name) if causal else 0
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    q_pos = my * t + jnp.arange(t)
    perm = _ring_perm(sp)
    # delta = rowsum(dO ⊙ O) per query row — [B,H,Tq]
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", dof, out.astype(jnp.float32)
    )

    def step(carry, i):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        src = (my - i) % sp
        k_pos = src * t + jnp.arange(t)
        s = _block_scores(qf, k_cur, scale, q_pos, k_pos, causal)
        p = jnp.exp(s - lse[..., None])  # [B,H,Tq,Tk]; masked → 0
        dp = jnp.einsum(
            "bqhd,bkhd->bhqk", dof, v_cur.astype(jnp.float32)
        )
        ds = p * (dp - delta[..., None])
        dq = dq + scale * jnp.einsum(
            "bhqk,bkhd->bqhd", ds, k_cur.astype(jnp.float32)
        )
        dk_cur = dk_cur + scale * jnp.einsum(
            "bhqk,bqhd->bkhd", ds, qf
        )
        dv_cur = dv_cur + jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        # The gradient blocks travel WITH their K/V blocks; after sp
        # hops every block is home with all contributions on board.
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        dk_next = lax.ppermute(dk_cur, axis_name, perm)
        dv_next = lax.ppermute(dv_cur, axis_name, perm)
        return (k_next, v_next, dk_next, dv_next, dq), None

    dk0 = jnp.zeros((b, t, h, d), jnp.float32)
    dv0 = jnp.zeros((b, t, h, d), jnp.float32)
    dq0 = jnp.zeros((b, t, h, d), jnp.float32)
    (k_back, v_back, dk, dv, dq), _ = lax.scan(
        step, (k, v, dk0, dv0, dq0), jnp.arange(sp)
    )
    del k_back, v_back
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


_ring_attention_mha.defvjp(_ring_attention_fwd, _ring_attention_bwd)


# ---------------------------------------------------------------------------
# Ring attention with the Pallas flash kernels doing the block math.
#
# The dense ring above materializes each hop's [B,H,Tq,Tk] score matrix
# in fp32 HBM; at long context that matrix is the whole memory story.
# The flash kernels never materialize it — so the TPU-native long-
# context path is: per hop, run the flash FORWARD on (q, k_hop, v_hop)
# to get that hop's locally-softmaxed output and logsumexp, then merge
# partials online (exact: o = Σ w_i·o_i with w_i = exp(lse_i − lse),
# lse = logaddexp over hops). The backward is a second ring pass
# invoking the flash backward kernels per hop with the GLOBAL (o, lse)
# — they compute p = exp(s − lse) against whatever lse they are handed,
# which with the global value yields exactly that hop's share of
# dq/dk/dv (the same algebra as the dense second pass above).
#
# Causality across hops is block-structured: a hop whose K block
# originates strictly before this chip's shard is fully visible
# (causal=False kernel), the diagonal hop masks within the kernel
# (causal=True), and future blocks are skipped. The three cases are a
# lax.switch on the (traced) origin rank.
# ---------------------------------------------------------------------------


def _to_bhtd(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_bhtd(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _ring_flash_fwd_pass(q, k, v, axis_name, causal):
    from ..ops.flash_attention import _flash_fwd, _pick_block

    sp = lax.axis_size(axis_name)
    # axis_index only matters for causal masking; when causal=False
    # the value would be dead code, and a dead cross-replica
    # primitive inside custom_vjp+shard_map lowers to a PartitionId
    # in the auto-SPMD region, which XLA rejects (JAX 0.4.x) —
    # skip it entirely on the non-causal path
    my = lax.axis_index(axis_name) if causal else 0
    b, t, h, d = q.shape
    r = h // k.shape[2]  # grouped-query: q heads per kv head
    bq = _pick_block(t)  # DEFAULT_BLOCK preference, shared with the gate
    bk = _pick_block(t)
    qb = _to_bhtd(q)
    kb = _to_bhtd(k)
    vb = _to_bhtd(v)
    perm = _ring_perm(sp)

    def full_hop(kv):
        o, lse = _flash_fwd(qb, kv[0], kv[1], False, bq, bk, h_per_kv=r)
        return o.astype(jnp.float32), lse[..., 0]

    def diag_hop(kv):
        o, lse = _flash_fwd(qb, kv[0], kv[1], True, bq, bk, h_per_kv=r)
        return o.astype(jnp.float32), lse[..., 0]

    def skip_hop(kv):
        return (
            jnp.zeros(qb.shape, jnp.float32),
            jnp.full(qb.shape[:2], -jnp.inf, jnp.float32),
        )

    def step(carry, i):
        k_cur, v_cur, o_acc, lse_acc = carry
        src = (my - i) % sp
        if causal:
            case = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            o_i, lse_i = lax.switch(
                case, (full_hop, diag_hop, skip_hop), (k_cur, v_cur)
            )
        else:
            o_i, lse_i = full_hop((k_cur, v_cur))
        # online merge of softmax partials (both o's are normalized)
        m = jnp.maximum(lse_acc, lse_i)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        w_acc = jnp.exp(jnp.where(jnp.isfinite(lse_acc), lse_acc - m_safe, -jnp.inf))
        w_i = jnp.exp(jnp.where(jnp.isfinite(lse_i), lse_i - m_safe, -jnp.inf))
        denom = w_acc + w_i
        denom_safe = jnp.maximum(denom, 1e-30)
        o_acc = (o_acc * w_acc[..., None] + o_i * w_i[..., None]) / denom_safe[
            ..., None
        ]
        lse_acc = m_safe + jnp.log(denom_safe)
        lse_acc = jnp.where(denom > 0, lse_acc, -jnp.inf)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, o_acc, lse_acc), None

    o0 = jnp.zeros(qb.shape, jnp.float32)
    lse0 = jnp.full(qb.shape[:2], -jnp.inf, jnp.float32)
    (_, _, o, lse), _ = lax.scan(step, (kb, vb, o0, lse0), jnp.arange(sp))
    # every query attends to at least its own position under causal, so
    # lse is finite here; the guard above only protects intermediates
    return _from_bhtd(o, b, h).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_flash_attention(
    q, k, v, axis_name: str = "sp", causal: bool = False
):
    """`ring_attention` with the Pallas flash kernels as the block
    engine: same exact math and [B, T_local, H, Dh] contract, but no
    hop ever materializes a score matrix in HBM — per-hop memory is
    O(T_local·Dh) + the kernel's VMEM tiles. Requires a flash-tileable
    local sequence (`ops.flash_attention.supports_seq`); use
    `ring_attention` for odd lengths or non-TPU backends (the kernels
    run in interpret mode off-TPU — correct but slow, tests only).

    Grouped-query attention: k/v may carry fewer heads than q
    (q heads % kv heads == 0) — the per-hop kernels read shared KV rows
    directly, so long-context GQA rides the ring without ever
    materializing a head repeat."""
    if v.shape[2] != k.shape[2] or q.shape[2] % k.shape[2]:
        raise ValueError(
            "kv heads must match and divide q heads: "
            f"q={q.shape[2]}, k={k.shape[2]}, v={v.shape[2]}"
        )
    from ..ops.flash_attention import _warn_vmem, fits_vmem

    # each backward hop runs the same dK/dV kernel at the LOCAL length,
    # with the same r-fold group staging — the VMEM budget applies
    # per-hop (ADVICE r4)
    r = q.shape[2] // k.shape[2]
    if not fits_vmem(q.shape[1], q.shape[3], r, q.dtype.itemsize):
        _warn_vmem(
            q.shape[1], q.shape[3], r, q.dtype.itemsize,
            what="ring_flash_attention (per hop)",
        )
    out, _ = _ring_flash_fwd_pass(q, k, v, axis_name, causal)
    return out


def _ring_flash_attention_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_flash_attention_bwd(axis_name, causal, res, do):
    from ..ops.flash_attention import _flash_bwd_impl, _pick_block

    q, k, v, out, lse = res
    sp = lax.axis_size(axis_name)
    # axis_index only matters for causal masking; when causal=False
    # the value would be dead code, and a dead cross-replica
    # primitive inside custom_vjp+shard_map lowers to a PartitionId
    # in the auto-SPMD region, which XLA rejects (JAX 0.4.x) —
    # skip it entirely on the non-causal path
    my = lax.axis_index(axis_name) if causal else 0
    b, t, h, d = q.shape
    r = h // k.shape[2]  # grouped-query: q heads per kv head
    bq = _pick_block(t)  # must match the fwd pass tiling
    bk = _pick_block(t)
    qb = _to_bhtd(q)
    kb = _to_bhtd(k)
    vb = _to_bhtd(v)
    ob = _to_bhtd(out)
    dob = _to_bhtd(do)
    perm = _ring_perm(sp)

    def full_hop(kv):
        dq, dk, dv = _flash_bwd_impl(
            qb, kv[0], kv[1], ob, lse, dob, False, bq, bk, h_per_kv=r
        )
        return (
            dq.astype(jnp.float32),
            dk.astype(jnp.float32),
            dv.astype(jnp.float32),
        )

    def diag_hop(kv):
        dq, dk, dv = _flash_bwd_impl(
            qb, kv[0], kv[1], ob, lse, dob, True, bq, bk, h_per_kv=r
        )
        return (
            dq.astype(jnp.float32),
            dk.astype(jnp.float32),
            dv.astype(jnp.float32),
        )

    def skip_hop(kv):
        return (
            jnp.zeros(qb.shape, jnp.float32),
            jnp.zeros(kb.shape, jnp.float32),
            jnp.zeros(kb.shape, jnp.float32),
        )

    def step(carry, i):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        src = (my - i) % sp
        if causal:
            case = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            dq_i, dk_i, dv_i = lax.switch(
                case, (full_hop, diag_hop, skip_hop), (k_cur, v_cur)
            )
        else:
            dq_i, dk_i, dv_i = full_hop((k_cur, v_cur))
        dq = dq + dq_i
        dk_cur = dk_cur + dk_i
        dv_cur = dv_cur + dv_i
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        dk_next = lax.ppermute(dk_cur, axis_name, perm)
        dv_next = lax.ppermute(dv_cur, axis_name, perm)
        return (k_next, v_next, dk_next, dv_next, dq), None

    zq = jnp.zeros(qb.shape, jnp.float32)
    zkv = jnp.zeros(kb.shape, jnp.float32)
    (_, _, dk, dv, dq), _ = lax.scan(
        step, (kb, vb, zkv, zkv, zq), jnp.arange(sp)
    )
    return (
        _from_bhtd(dq, b, h).astype(q.dtype),
        _from_bhtd(dk, b, h // r).astype(k.dtype),
        _from_bhtd(dv, b, h // r).astype(v.dtype),
    )


ring_flash_attention.defvjp(
    _ring_flash_attention_fwd, _ring_flash_attention_bwd
)
