"""Paged KV memory plane (horovod_tpu/serving/paged_kv.py): paged vs
slab bit-parity (incl. staggered multi-slot, RoPE/GQA, slot/page reuse
after eviction), prefix-cache hit parity + accounting, refcount /
copy-on-write correctness, zero-retrace with paging on, pool-exhaustion
admission control (pause/resume, watermark), and the page-aware
router/capacity surfaces."""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _cfg(**kw):
    from horovod_tpu.models.transformer import TransformerConfig

    base = dict(
        vocab_size=61,
        num_layers=1,
        d_model=16,
        num_heads=2,
        d_ff=32,
        max_len=64,
        causal=True,
        dtype=jnp.float32,
    )
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def toy():
    from horovod_tpu.models.transformer import Transformer

    model = Transformer(_cfg())
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    return model, params


def _engine(toy, **kw):
    from horovod_tpu.serving.engine import InferenceEngine

    model, params = toy
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("page_tokens", 16)
    return InferenceEngine(model, params, **kw)


def _greedy_ref(model, params, prompt, n):
    seq = list(map(int, prompt))
    for _ in range(n):
        lg = model.apply(params, jnp.asarray([seq]), train=False)
        seq.append(int(np.asarray(lg)[0, -1].argmax()))
    return seq[len(prompt):]


def _generate(engine, slot, prompt, n):
    out = [engine.prefill(slot, prompt)]
    for _ in range(n - 1):
        toks = np.zeros(engine.slots, np.int32)
        toks[slot] = out[-1]
        nxt = engine.decode_step(toks)
        engine.manager.advance(slot)
        out.append(int(nxt[slot]))
    return out


def _pool_factory(heads=2, head_dim=4, layers=1):
    return lambda pages, pt: [
        {
            "k": jnp.zeros((pages, pt, heads, head_dim)),
            "v": jnp.zeros((pages, pt, heads, head_dim)),
        }
        for _ in range(layers)
    ]


def _manager(**kw):
    from horovod_tpu.serving.paged_kv import PagedKVCacheManager

    kw.setdefault("page_tokens", 4)
    kw.setdefault("prefix_cache", True)
    return PagedKVCacheManager(_pool_factory(), **kw)


# ---------------------------------------------------------------- parity


def test_paged_vs_slab_greedy_bit_parity(toy):
    """THE acceptance property: greedy decode through the page pool is
    token-identical to the contiguous slab at every position."""
    model, params = toy
    paged = _engine(toy, paged=True)
    slab = _engine(toy, paged=False)
    prompt = [5, 7, 11, 13, 17, 19, 23]
    out_p = _generate(paged, paged.manager.alloc("p"), prompt, 8)
    out_s = _generate(slab, slab.manager.alloc("s"), prompt, 8)
    assert out_p == out_s == _greedy_ref(model, params, prompt, 8)


def test_paged_decode_logits_bitwise_equal_to_slab(toy):
    """Stronger than token parity: the decode-step logits of the active
    row are BITWISE equal between layouts (pages tile max_len exactly,
    so shapes — and therefore reductions — match)."""
    from horovod_tpu.models.transformer import init_cache

    model, params = toy
    cfg = model.cfg
    slots, pt = 2, 16
    W = cfg.max_len // pt
    prompt = jnp.asarray([[9, 8, 7, 6, 5]], jnp.int32)

    slab = init_cache(cfg, slots, cfg.max_len)
    row = [{k: v[0:1] for k, v in layer.items()} for layer in slab]
    _, newrow = model.apply(
        params, prompt, train=False, cache=row, cache_index=jnp.array([0])
    )
    for layer, nl in zip(slab, newrow):
        for k in layer:
            layer[k] = layer[k].at[0:1].set(nl[k])

    pool = init_cache(cfg, slots * W, pt)
    tables = np.full((slots, W), slots * W, np.int32)
    tables[0] = [5, 2, 7, 0]  # scrambled physical order on purpose
    _, pool = model.apply(
        params, prompt, train=False, cache=pool,
        cache_index=jnp.array([0]), pages=jnp.asarray(tables[0:1]),
    )

    toks = jnp.asarray([[3], [0]], jnp.int32)
    lengths = jnp.asarray([5, 0], jnp.int32)
    lg_s, _ = model.apply(
        params, toks, train=False, cache=slab, cache_index=lengths
    )
    lg_p, _ = model.apply(
        params, toks, train=False, cache=pool, cache_index=lengths,
        pages=jnp.asarray(tables),
    )
    assert bool(jnp.all(lg_s[0] == lg_p[0]))


def test_paged_parity_staggered_multislot(toy):
    """Two sequences admitted at different times, decoding together
    through the shared pool: both streams stay exact."""
    model, params = toy
    eng = _engine(toy, paged=True)
    p1, p2 = [3, 5, 7], [11, 13, 17, 19, 21]
    s1 = eng.manager.alloc("a")
    out1 = [eng.prefill(s1, p1)]
    for _ in range(3):  # r1 decodes alone first
        toks = np.zeros(eng.slots, np.int32)
        toks[s1] = out1[-1]
        out1.append(int(eng.decode_step(toks)[s1]))
        eng.manager.advance(s1)
    s2 = eng.manager.alloc("b")  # staggered admission mid-stream
    out2 = [eng.prefill(s2, p2)]
    for _ in range(4):
        toks = np.zeros(eng.slots, np.int32)
        toks[s1], toks[s2] = out1[-1], out2[-1]
        nxt = eng.decode_step(toks)
        eng.manager.advance(s1)
        eng.manager.advance(s2)
        out1.append(int(nxt[s1]))
        out2.append(int(nxt[s2]))
    assert out1 == _greedy_ref(model, params, p1, 8)
    assert out2 == _greedy_ref(model, params, p2, 5)


def test_paged_parity_rope_gqa_variant():
    """The paged read/write composes with per-slot RoPE offsets and
    grouped-query heads exactly like the slab does."""
    from horovod_tpu.models.transformer import Transformer

    cfg = _cfg(num_heads=4, num_kv_heads=2, rope=True)
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32), train=False
    )
    toy = (model, params)
    prompt = [31, 33, 35, 37, 39]
    paged = _engine(toy, paged=True)
    slab = _engine(toy, paged=False)
    out_p = _generate(paged, paged.manager.alloc(), prompt, 6)
    out_s = _generate(slab, slab.manager.alloc(), prompt, 6)
    assert out_p == out_s == _greedy_ref(model, params, prompt, 6)


def test_paged_slot_and_page_reuse_after_eviction(toy):
    """A freed slot's pages recycle WITHOUT zeroing; the next occupant
    (and the next owner of those physical pages) still decodes exactly."""
    model, params = toy
    eng = _engine(
        toy, slots=1, paged=True, pages=4, prefix_cache=False
    )  # 4-page pool over a 64-token slot: reuse is guaranteed
    slot = eng.manager.alloc("a")
    _generate(eng, slot, [41, 43, 45, 47, 49, 51, 53], 12)
    eng.manager.free(slot)
    assert eng.manager.stats()["pages_free"] == 4  # all recycled
    slot2 = eng.manager.alloc("b")
    assert slot2 == slot
    out = _generate(eng, slot2, [2, 4], 6)
    assert out == _greedy_ref(model, params, [2, 4], 6)


def test_chunked_prefill_parity_with_paging(toy):
    model, params = toy
    eng = _engine(toy, paged=True, prefill_ceiling=8)
    prompt = list(np.random.default_rng(3).integers(1, 60, size=21))
    slot = eng.manager.alloc()
    out = _generate(eng, slot, prompt, 4)
    assert out == _greedy_ref(model, params, prompt, 4)
    assert eng.stats()["chunked_prefill_chunks"] == 2


# ---------------------------------------------------------- prefix cache


def test_prefix_hit_bit_parity_and_chunk_skip(toy):
    """A request sharing a cached prefix attaches pages instead of
    prefilling them — and its greedy stream is bit-identical to a cold
    prefill of the same tokens."""
    model, params = toy
    eng = _engine(toy, paged=True, page_tokens=8)
    p1 = list(range(1, 21))                  # 2 full pages + tail
    p2 = list(range(1, 21)) + [55, 56, 57]   # shares both full pages
    s1 = eng.manager.alloc("a")
    eng.prefill(s1, p1)
    s2 = eng.manager.alloc("b")
    out = [eng.prefill(s2, p2)]
    st = eng.stats()
    assert st["prefill_chunks_skipped"] == 2
    assert st["prefill_tokens_skipped"] == 16
    m = eng.manager.stats()
    assert m["prefix_hits"] == 2 and m["prefix_hit_requests"] == 1
    for _ in range(5):
        toks = np.zeros(eng.slots, np.int32)
        toks[s2] = out[-1]
        out.append(int(eng.decode_step(toks)[s2]))
        eng.manager.advance(s2)
        eng.manager.advance(s1)
    assert out == _greedy_ref(model, params, p2, 6)


def test_full_prefix_hit_still_recomputes_last_token(toy):
    """A prompt that is ENTIRELY cached (exact page multiple) must
    still recompute its final token — the first output's logits come
    from it — and the output stays exact."""
    model, params = toy
    eng = _engine(toy, paged=True, page_tokens=8)
    prompt = list(range(2, 18))  # 16 tokens = exactly 2 pages
    s1 = eng.manager.alloc("a")
    eng.prefill(s1, prompt)
    eng.manager.free(s1)
    s2 = eng.manager.alloc("b")
    out = _generate(eng, s2, prompt, 5)
    # only the FIRST page may hit: the cap keeps the last token (and
    # its page) recomputed, so no write ever lands in a shared page
    assert eng.stats()["prefill_chunks_skipped"] == 1
    assert out == _greedy_ref(model, params, prompt, 5)


def test_prefix_cache_off_never_hits(toy):
    eng = _engine(toy, paged=True, page_tokens=8, prefix_cache=False)
    prompt = list(range(1, 20))
    eng.prefill(eng.manager.alloc(), prompt)
    eng.prefill(eng.manager.alloc(), prompt)
    assert eng.stats()["prefill_chunks_skipped"] == 0
    assert eng.manager.stats()["prefix_hits"] == 0


def test_page_hashes_chain_commits_to_full_prefix():
    from horovod_tpu.serving.paged_kv import page_hashes

    a = page_hashes(np.arange(16), 4)
    b = page_hashes(np.arange(16), 4)
    assert a == b and len(a) == 4
    # same page-2 CONTENT under a different page-1 history: different
    # hash (the chain commits to the whole prefix, not the chunk)
    c = page_hashes(
        np.concatenate([np.arange(4) + 99, np.arange(4, 16)]), 4
    )
    assert c[1] != b[1] and c[2] != b[2]
    # a partial trailing chunk is never hashed
    assert len(page_hashes(np.arange(15), 4)) == 3


# --------------------------------------------------- refcounts, COW, LRU


def test_refcounts_shared_pages_survive_publisher_eviction():
    mgr = _manager(slots=3, max_len=16, num_pages=12)
    from horovod_tpu.serving.paged_kv import page_hashes

    prompt = np.arange(1, 9)  # 2 full pages
    hashes = page_hashes(prompt, 4)
    a = mgr.alloc("a")
    assert mgr.ensure_pages(a, 8)
    mgr.set_length(a, 8)
    mgr.publish_prefix(a, hashes)
    page0 = int(mgr.table_row(a)[0])
    # a second slot attaches the shared prefix
    b = mgr.alloc("b")
    hits = mgr.lookup_prefix(hashes)
    assert len(hits) == 2
    mgr.attach_prefix(b, hits)
    # publisher retires: shared pages must NOT free (slot b + index)
    mgr.free(a)
    assert int(mgr._ref[page0]) == 2  # slot b + index hold
    mgr.free(b)
    assert int(mgr._ref[page0]) == 1  # index only — reclaimable now
    assert mgr.stats()["pages_cached"] == 2
    assert mgr.free_pages_available() == 12


def test_lru_eviction_only_at_refcount_zero():
    mgr = _manager(slots=2, max_len=16, num_pages=4)
    from horovod_tpu.serving.paged_kv import page_hashes

    h1 = page_hashes(np.arange(1, 9), 4)      # 2 pages
    a = mgr.alloc("a")
    assert mgr.ensure_pages(a, 8)
    mgr.publish_prefix(a, h1)
    # slot a still holds its pages: they are published but NOT
    # reclaimable, so a demand for 3 more pages must fail...
    b = mgr.alloc("b")
    assert not mgr.ensure_pages(b, 12)
    assert mgr.stats()["page_evictions"] == 0
    # ...until a retires: now the index-only pages LRU-evict to serve b
    mgr.free(a)
    assert mgr.ensure_pages(b, 12)
    assert mgr.stats()["page_evictions"] >= 1
    assert mgr.lookup_prefix(h1) == []  # evicted entries miss


def test_cow_guards_writes_into_shared_pages():
    """Defensive copy-on-write: a write landing in a page referenced
    elsewhere copies it first — the sharer's view never changes."""
    mgr = _manager(slots=2, max_len=16, num_pages=6, prefix_cache=False)
    a = mgr.alloc("a")
    assert mgr.ensure_pages(a, 4)
    page = int(mgr.table_row(a)[0])
    # poke a recognizable value into the shared page
    mgr.cache = jax.tree_util.tree_map(
        lambda leaf: leaf.at[page].set(7.0), mgr.cache
    )
    b = mgr.alloc("b")
    mgr.attach_prefix(b, [page])  # synthetic partial-page share
    assert int(mgr._ref[page]) == 2
    # slot b will WRITE inside the shared page -> COW must fire
    assert mgr.ensure_pages(b, 4, write_from=2)
    assert mgr.stats()["page_cow"] == 1
    new = int(mgr.table_row(b)[0])
    assert new != page and int(mgr._ref[page]) == 1
    # the copy carried the content; the original is untouched
    leaf = mgr.cache[0]["k"]
    assert bool(jnp.all(leaf[new] == 7.0)) and bool(
        jnp.all(leaf[page] == 7.0)
    )


def test_detach_keep_reattach_and_release():
    mgr = _manager(slots=2, max_len=16, num_pages=8, prefix_cache=False)
    a = mgr.alloc("a")
    assert mgr.ensure_pages(a, 7)
    mgr.set_length(a, 7)
    kept, length = mgr.detach_keep(a)
    assert length == 7 and len(kept) == 2
    assert mgr.stats()["slots_active"] == 0
    assert mgr.free_pages_available() == 6  # kept pages still held
    b = mgr.alloc("resume")
    mgr.reattach(b, kept, length)
    assert mgr.length(b) == 7
    assert [int(p) for _, p in kept] == [
        int(x) for x in mgr.table_row(b)[:2]
    ]
    kept2, _ = mgr.detach_keep(b)
    mgr.release_kept(kept2)
    assert mgr.free_pages_available() == 8


def test_page_tokens_must_divide_max_len():
    with pytest.raises(ValueError, match="divide"):
        _manager(slots=1, max_len=10, num_pages=4, page_tokens=4)


# ------------------------------------------------- zero-retrace invariant


def test_zero_retrace_with_paging_and_pauses(toy):
    """decode_compiles stays EXACTLY 1 across rolling admissions,
    evictions, prefix hits, pool-exhaustion pauses and resumes — page
    tables are data, never shapes."""
    from horovod_tpu.serving.batcher import ContinuousBatcher

    model, params = toy
    eng = _engine(
        toy, slots=3, paged=True, page_tokens=8, pages=12,
        page_watermark=1,
    )
    b = ContinuousBatcher(
        eng, max_admit_per_step=3, default_max_new_tokens=20
    )
    reqs = [
        b.submit(list(range(i * 4 + 1, i * 4 + 9)), max_new_tokens=20)
        for i in range(5)
    ]
    guard = 0
    while not all(r.finished() for r in reqs):
        b.step()
        guard += 1
        assert guard < 5000, [r.status for r in reqs]
    assert all(r.status == "done" for r in reqs)
    for i, r in enumerate(reqs):
        assert r.out_tokens == _greedy_ref(
            model, params, list(range(i * 4 + 1, i * 4 + 9)), 20
        ), f"request {i} diverged"
    assert eng.stats()["decode_compiles"] == 1


# -------------------------------------------- exhaustion admission control


def test_pool_exhaustion_pauses_youngest_and_resumes(toy):
    from horovod_tpu.common.metrics import registry
    from horovod_tpu.serving.batcher import ContinuousBatcher

    model, params = toy
    registry.reset()
    eng = _engine(
        toy, slots=3, paged=True, page_tokens=8, pages=9,
        page_watermark=1, prefix_cache=False,
    )
    b = ContinuousBatcher(
        eng, max_admit_per_step=3, default_max_new_tokens=24
    )
    reqs = [
        b.submit(list(range(i * 3 + 1, i * 3 + 11)), max_new_tokens=24)
        for i in range(3)
    ]
    guard = 0
    while not all(r.finished() for r in reqs):
        b.step()
        guard += 1
        assert guard < 5000
    snap = registry.snapshot()
    assert snap.get("serve.paused", 0) > 0, "pool never exhausted"
    assert snap.get("serve.resumed", 0) > 0
    for i, r in enumerate(reqs):
        assert r.status == "done"
        assert r.out_tokens == _greedy_ref(
            model, params, list(range(i * 3 + 1, i * 3 + 11)), 24
        ), f"request {i} diverged across pause/resume"


def test_admission_gated_on_page_watermark(toy):
    from horovod_tpu.serving.batcher import ContinuousBatcher

    eng = _engine(
        toy, slots=2, paged=True, page_tokens=16, pages=8,
        page_watermark=4, prefix_cache=False,
    )
    b = ContinuousBatcher(eng, default_max_new_tokens=16)
    r1 = b.submit(list(range(1, 33)))   # 2 prompt pages
    r2 = b.submit(list(range(1, 49)))   # 3 prompt pages
    b.step()
    # r1 admitted (headroom 8-4=4 >= 2); r2 blocked by the watermark
    # (headroom now <= 2 < 3) even though a SLOT is free
    assert b.active() == 1 and b.queue_depth() == 1
    assert eng.manager.stats()["slots_free"] == 1
    guard = 0
    while not (r1.finished() and r2.finished()):
        b.step()
        guard += 1
        assert guard < 1000
    assert r1.status == r2.status == "done"


def test_reject_request_that_can_never_fit_pool(toy):
    from horovod_tpu.serving.batcher import ContinuousBatcher, Rejected

    eng = _engine(
        toy, slots=2, paged=True, page_tokens=16, pages=2,
        prefix_cache=False,
    )
    b = ContinuousBatcher(eng, default_max_new_tokens=16)
    with pytest.raises(Rejected, match="pages"):
        b.submit(list(range(1, 40)))  # 39 + 16 tokens -> 4 pages > 2
    b.submit([1, 2, 3])  # 3 + 16 -> 2 pages: fits


def test_queued_paused_request_expiring_releases_pages(toy):
    from horovod_tpu.serving.batcher import ContinuousBatcher

    eng = _engine(
        toy, slots=2, paged=True, page_tokens=8, prefix_cache=False
    )
    b = ContinuousBatcher(eng, default_max_new_tokens=4)
    r = b.submit([1, 2, 3, 4, 5], deadline_ms=60_000.0)
    b.step()
    assert r.status == "running"
    # pause it by hand (the exhaustion path), then expire it in queue
    slot = next(iter(b._slot_req))
    held_before = eng.manager.free_pages_available()
    b._slot_req.pop(slot)
    r.kept_pages, r.resume_length = eng.manager.detach_keep(slot)
    r.paused = True
    r.status = "queued"
    b._queue.appendleft(r)
    r.deadline_ts = time.monotonic() - 0.001
    b.step()
    assert r.finished() and r.status == "deadline"
    assert r.kept_pages is None
    assert eng.manager.free_pages_available() > held_before


# ----------------------------------------------- capacity + router surface


def test_capacity_reports_pages_and_saturation_flips_slots(toy):
    import horovod_tpu as hvd

    model, params = toy
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=4, addr="127.0.0.1", handle_sigterm=False,
        page_tokens=16, pages=8, page_watermark=2,
    )
    try:
        cap = handle.frontend.capacity()
        assert cap["pages_total"] == 8
        assert cap["free_pages"] == 6  # 8 free - watermark 2
        assert "prefix_hit_rate" in cap
        assert cap["free_slots"] == 2
        # drain the pool: headroom 0 must flip announced slots to 0
        mgr = handle.engine.manager
        s = mgr.alloc("hog")
        assert mgr.ensure_pages(s, 64)  # all 8 pages... (4 pages/slot)
        s2 = mgr.alloc("hog2")
        assert mgr.ensure_pages(s2, 64)
        cap = handle.frontend.capacity()
        assert cap["free_pages"] == 0
        assert cap["free_slots"] == 0  # saturated pool -> no capacity
        mgr.free(s)
        mgr.free(s2)
    finally:
        handle.stop()


def test_router_prefers_page_headroom_with_legacy_blob_compat(toy):
    from horovod_tpu.runner.rendezvous import KVStore
    from horovod_tpu.serving.frontend import Router

    store = KVStore()

    def announce(rank, port, **fields):
        blob = dict(
            rank=rank, addr="127.0.0.1", port=port, ts=time.time(),
            draining=False, queue_depth=0,
        )
        blob.update(fields)
        store.put("serve", str(rank), json.dumps(blob).encode())

    # rank 0: MORE free slots but fewer free pages; rank 1 page-rich.
    announce(0, 9000, free_slots=8, free_pages=1, pages_total=16)
    announce(1, 9001, free_slots=2, free_pages=9, pages_total=16)
    router = Router(store)
    assert router.pick()["rank"] == 1  # pages outrank slots
    # legacy blob (no page fields) parses and routes on slots
    store2 = KVStore()
    blob = {
        "rank": 3, "addr": "127.0.0.1", "port": 9003,
        "free_slots": 4, "queue_depth": 0, "ts": time.time(),
    }
    store2.put("serve", "3", json.dumps(blob).encode())
    router2 = Router(store2)
    assert router2.pick()["rank"] == 3


def test_paged_counters_land_in_flight_recorder(toy, monkeypatch):
    from horovod_tpu.common import telemetry
    from horovod_tpu.serving.batcher import ContinuousBatcher

    monkeypatch.setenv("HOROVOD_TELEMETRY", "1")
    telemetry._reset_hub()
    try:
        eng = _engine(toy, paged=True, page_tokens=8)
        b = ContinuousBatcher(eng, default_max_new_tokens=10)
        r = b.submit([5, 6, 7, 8, 9, 10, 11, 12])
        while not r.finished():
            b.step()
        recs = telemetry.hub().records()
        assert recs
        assert any("serve.page_allocs" in rec for rec in recs)
        assert (
            sum(rec.get("serve.page_allocs", 0) for rec in recs) > 0
        ), "decode frontier crossings produced no page_allocs deltas"
    finally:
        telemetry._reset_hub()
