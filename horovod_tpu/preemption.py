"""Preemption handling: the TPU-native failure mode, handled first-class.

The reference's elastic stack reacts to failures AFTER they break a
collective (`HorovodInternalError` → rollback, SURVEY.md §3.4/§5.3);
preemptible TPU VMs instead deliver an ADVANCE signal (SIGTERM from the
infrastructure, typically ~30s of grace). This module turns that grace
window into a durable checkpoint:

    state = DurableJaxState(checkpoint_dir=..., params=..., step=0)
    with hvd.preemption.GracefulShutdown(state):
        train(state)   # on SIGTERM: finish persisting, then exit(143)

or cooperatively:

    handler = hvd.preemption.PreemptionHandler()
    for step in range(...):
        ...
        if handler.should_stop():   # signal arrived: wind down in-loop
            state.commit(); state.wait_until_finished(); break

After the restart (same or re-acquired slice), ``resume_latest()`` on a
fresh ``DurableJaxState`` continues from the persisted step — the
slice-re-acquisition recovery the survey calls for (§5.3: "elastic on
TPU is restart-with-different-slice").
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Iterable, List, Optional

_DEFAULT_SIGNALS = (signal.SIGTERM,)

# Process-wide drain hooks: subsystems with in-flight work that must
# finish BEFORE the flight-recorder dump and the durable checkpoint
# (the serving frontend registers here so a SIGTERM completes every
# accepted request before the worker leaves the gang). Run in
# registration order by GracefulShutdown._drain(); exceptions in one
# hook never block the next — the checkpoint must still happen.
_drain_hooks: List[Callable[[], None]] = []
_drain_lock = threading.Lock()


def register_drain(fn: Callable[[], None]) -> Callable[[], None]:
    """Register a shutdown drain hook (returns ``fn`` for decorator
    use). Hooks run FIRST in the SIGTERM sequence: drains → flight
    recorder → checkpoint — in-flight work, then observability, then
    durability."""
    with _drain_lock:
        if fn not in _drain_hooks:
            _drain_hooks.append(fn)
    return fn


def unregister_drain(fn: Callable[[], None]) -> None:
    with _drain_lock:
        try:
            _drain_hooks.remove(fn)
        except ValueError:
            pass


def drain_hooks() -> List[Callable[[], None]]:
    with _drain_lock:
        return list(_drain_hooks)


class PreemptionHandler:
    """Latches preemption signals; query with :meth:`should_stop`.

    Chains any previously-installed handler, so stacking on top of a
    launcher's own SIGTERM handling keeps both behaviors.
    """

    def __init__(
        self,
        signals: Iterable[int] = _DEFAULT_SIGNALS,
        on_preempt: Optional[Callable[[], None]] = None,
    ) -> None:
        self._event = threading.Event()
        self._on_preempt = on_preempt
        self._previous = {}
        for sig in signals:
            self._previous[sig] = signal.signal(sig, self._handle)

    def _handle(self, signum, frame) -> None:
        self._event.set()
        # Unstick any KV poll loop first: a preempted worker blocked in
        # a rendezvous wait() must notice the shutdown at its next poll
        # instead of spending the grace window spinning on HTTP.
        try:
            from .runner import rendezvous as _rdv

            _rdv.request_poll_shutdown()
        except Exception:
            pass
        if self._on_preempt is not None:
            self._on_preempt()
        prev = self._previous.get(signum)
        if callable(prev):
            prev(signum, frame)

    def should_stop(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
        self._previous.clear()


class GracefulShutdown:
    """Context manager: on preemption, drain, persist the state, exit.

    ``state`` needs the DurableJaxState surface (``commit()`` +
    ``wait_until_finished()``); any object with those methods works, and
    ``state=None`` skips the durable step entirely (a serving-only
    worker has no training state — the drain hooks ARE its shutdown
    work). ``exit_code`` defaults to 143 (128+SIGTERM), which launchers
    read as "killed by infrastructure", not a software fault.

    SIGTERM ordering contract (regression-tested in
    tests/test_preemption.py): **registered drains → flight recorder →
    checkpoint** — instance hooks (:meth:`register_drain`) then module
    hooks (:func:`register_drain`), each in registration order. Drains
    run first because they hold user-visible in-flight work (the
    serving frontend finishes every accepted request here); the flight
    recorder is next because its bounded tmp+rename write cannot eat
    the grace window the checkpoint needs.

    ``state`` must be passed EXPLICITLY — ``GracefulShutdown(None)``
    declares the stateless intent; ``GracefulShutdown()`` raises, so a
    training script that forgot its state gets a loud TypeError today
    instead of a silent no-checkpoint preemption later.
    """

    _STATE_REQUIRED = object()

    def __init__(
        self,
        state=_STATE_REQUIRED,
        signals: Iterable[int] = _DEFAULT_SIGNALS,
        exit_code: int = 143,
    ) -> None:
        if state is self._STATE_REQUIRED:
            raise TypeError(
                "GracefulShutdown requires a state argument: pass the "
                "DurableJaxState to persist on SIGTERM, or an explicit "
                "None for a stateless (drain-hooks-only) shutdown"
            )
        self._state = state
        self._signals = tuple(signals)
        self._exit_code = exit_code
        self._handler: Optional[PreemptionHandler] = None
        self._drains: List[Callable[[], None]] = []

    def register_drain(self, fn: Callable[[], None]) -> Callable[[], None]:
        """Instance-scoped drain hook: runs before the module-level
        hooks, before the flight recorder, before the checkpoint."""
        if fn not in self._drains:
            self._drains.append(fn)
        return fn

    def __enter__(self) -> "GracefulShutdown":
        self._handler = PreemptionHandler(
            signals=self._signals, on_preempt=self._drain_and_exit
        )
        return self

    def _drain_and_exit(self) -> None:
        try:
            self._drain()
        finally:
            # os._exit: a signal can arrive mid-collective; running
            # normal interpreter teardown over wedged device state can
            # hang past the grace window, and the checkpoint is already
            # durable.
            os._exit(self._exit_code)

    def _drain(self) -> None:
        """The full shutdown sequence minus the exit (separable so the
        ordering is testable in-process)."""
        # Drain hooks first: in-flight user-visible work (e.g. the
        # serving plane's accepted requests) finishes while the process
        # is still fully alive. One failing hook never blocks the next
        # — nor the recorder/checkpoint behind it.
        for fn in list(self._drains) + drain_hooks():
            try:
                fn()
            except Exception:
                pass
        # Flight recorder next (common/telemetry.py): the ring dump
        # is a bounded tmp+rename write, so it cannot eat the grace
        # window the checkpoint needs — and a failed checkpoint
        # still leaves the last-N-steps post-mortem on disk.
        try:
            from .common import telemetry as _telemetry

            _telemetry.hub().dump()
        except Exception:
            pass
        # ``preemption.drain`` injection site: the deterministic
        # mid-save kill window — a chaos plan SIGKILLs here to
        # prove a kill landing between the flight-recorder dump and
        # the durable persist can never leave a truncated artifact
        # the restore path later trusts (tests/test_chaos.py).
        try:
            from .testing import chaos as _chaos

            _chaos.inject("preemption.drain")
        except Exception:
            pass  # injected transport faults don't fit this site
        if self._state is None:
            return
        # Prefer the unconditional durable path: commit() may batch
        # (save_interval) or raise HostsUpdatedInterrupt before the
        # write — either loses the grace window's whole purpose.
        persist = getattr(self._state, "persist", None)
        if persist is not None:
            persist()
        else:
            self._state.commit()
        wait = getattr(self._state, "wait_until_finished", None)
        if wait is not None:
            wait()

    def __exit__(self, *exc) -> None:
        if self._handler is not None:
            self._handler.uninstall()
            self._handler = None
