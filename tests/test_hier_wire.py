"""Two-level world (PR 10): topology detection, the hierarchical recipe
family on the flat axis, default routing through fusion / overlap /
ZeRO, hierarchical Adasum, and the straggler rebalance plane.

Bit-exactness methodology: flat psum on XLA:CPU is a left-fold while
the two-level decomposition sums intra-then-inter, so fp32 equality for
ARBITRARY data is a reassociation question, not a correctness one (see
docs/perf.md). The bit-exact assertions therefore use INTEGER-VALUED
fp32 payloads — every partial sum is exactly representable, so any
routing / permutation / scaling bug breaks equality bitwise while
legitimate reassociation cannot — plus ulp-bounded assertions on random
normal data.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.common.compat import shard_map
from horovod_tpu.common import topology as topo_mod
from horovod_tpu import analysis
from horovod_tpu.ops import overlap, traced
from horovod_tpu.ops.reduction_ops import Average, Sum

STAGES_84 = topo_mod.hierarchical_stage_groups(8, 4)
STAGES_82 = topo_mod.hierarchical_stage_groups(8, 2)


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]), ("hvd",))


def _sm(fn, mesh=None, ins=P("hvd"), outs=P("hvd")):
    return jax.jit(
        shard_map(
            fn,
            mesh=mesh or _mesh(),
            in_specs=ins,
            out_specs=outs,
            check_vma=False,
        )
    )


def _ints(rng, shape, lo=-100, hi=100):
    return rng.integers(lo, hi, size=shape).astype(np.float32)


# ------------------------------------------------- topology detection


class TestTopologyDetection:
    def test_override_env_wins(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "4")
        assert topo_mod.detect_intra_size((), 1, 1) == 1  # gcd(4, 1)
        assert topo_mod.detect_intra_size([None] * 8, 1, 1, override=4) == 4

    def test_slice_index_detection(self):
        class D:
            def __init__(self, si):
                self.slice_index = si

        devs = [D(0)] * 4 + [D(1)] * 4
        assert topo_mod.detect_intra_size(devs, 8, 1) == 4
        # uneven slices: no uniform split
        devs = [D(0)] * 5 + [D(1)] * 3
        assert topo_mod.detect_intra_size(devs, 8, 1) == 8

    def test_process_structure_detection(self):
        devs = [object()] * 8  # no slice_index attr
        assert topo_mod.detect_intra_size(devs, 2, 4) == 2
        # single process driving everything = one slice
        assert topo_mod.detect_intra_size(devs, 8, 1) == 8

    def test_gcd_degrade_survives_elastic_resize(self):
        # 8 -> 6 under HOROVOD_INTRA_SIZE=4: gcd keeps a valid split
        assert topo_mod._gcd_degrade(4, 6) == 2
        assert topo_mod._gcd_degrade(4, 8) == 4
        assert topo_mod._gcd_degrade(5, 6) == 1
        st = topo_mod.hierarchy_stages(world=6, mode="on", intra=4)
        assert st == ([[0, 1], [2, 3], [4, 5]], [[0, 2, 4], [1, 3, 5]])

    def test_mode_tri_state(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "4")
        monkeypatch.setenv("HOROVOD_HIERARCHICAL", "off")
        assert topo_mod.hierarchy_stages(world=8) is None
        monkeypatch.setenv("HOROVOD_HIERARCHICAL", "on")
        assert topo_mod.hierarchy_stages(world=8) == STAGES_84
        monkeypatch.setenv("HOROVOD_HIERARCHICAL", "auto")
        # auto + explicit override = positive evidence
        assert topo_mod.hierarchy_stages(world=8) == STAGES_84
        monkeypatch.delenv("HOROVOD_INTRA_SIZE")
        # auto with no evidence (single-slice sim): flat
        assert topo_mod.hierarchy_stages(world=8) is None

    def test_legacy_flag_reads_as_on(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "2")
        assert topo_mod.hierarchy_stages(world=8) == STAGES_82

    def test_two_level_mesh(self, hvd, monkeypatch):
        import horovod_tpu as hvd_mod

        monkeypatch.setenv("HOROVOD_INTER_AXIS", "dcn")
        from horovod_tpu.common import basics

        mesh = basics.topology().two_level_mesh(intra_size=4)
        assert mesh.axis_names == ("dcn", "intra")
        assert mesh.devices.shape == (2, 4)
        with pytest.raises(ValueError):
            basics.topology().two_level_mesh(intra_size=3)


# ------------------------------------- traced recipe family (groups)


class TestHierRecipes:
    @pytest.mark.parametrize("stages", [STAGES_84, STAGES_82])
    @pytest.mark.parametrize("op", [Sum, Average])
    def test_allreduce_groups_bitexact_integer(self, hvd, stages, op):
        rng = np.random.default_rng(0)
        x = _ints(rng, (8, 37))
        flat = _sm(lambda v: traced.allreduce(v, op=op))(x)
        hier = _sm(
            lambda v: traced.hierarchical_allreduce_groups(
                v[0], op=op, stages=stages
            )[None]
        )(x)
        np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))

    def test_allreduce_groups_ulp_bound_random(self, hvd):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(8, 513)).astype(np.float32)
        hier = np.asarray(
            _sm(
                lambda v: traced.hierarchical_allreduce_groups(
                    v[0], op=Sum, stages=STAGES_84
                )[None]
            )(x)
        )
        want = x.astype(np.float64).sum(0)
        # reassociation-only error: a few ulp of the accumulated sum
        tol = 8 * np.finfo(np.float32).eps * np.abs(want).max()
        assert np.abs(hier[0] - want).max() <= tol
        # replicas agree bitwise — it is a well-formed allreduce
        for r in range(8):
            np.testing.assert_array_equal(hier[r], hier[0])

    def test_allreduce_groups_scales(self, hvd):
        rng = np.random.default_rng(2)
        x = _ints(rng, (8, 16))
        out = np.asarray(
            _sm(
                lambda v: traced.hierarchical_allreduce_groups(
                    v[0], op=Sum, stages=STAGES_84,
                    prescale_factor=0.5, postscale_factor=2.0,
                )[None]
            )(x)
        )
        np.testing.assert_array_equal(out[0], x.sum(0))

    def test_int8_inter_within_quanta_and_consistent(self, hvd):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(8, 300)).astype(np.float32)
        out = np.asarray(
            _sm(
                lambda v: traced.hierarchical_allreduce_groups(
                    v[0], op=Sum, stages=STAGES_84, inter_wire="int8",
                    intra_wire="bf16", block_size=64, seed=7,
                )[None]
            )(x)
        )
        want = x.sum(0)
        scale = np.abs(want).max() / 127.0
        assert np.abs(out[0] - want).max() < 3.0 * scale
        for r in range(8):
            np.testing.assert_array_equal(out[r], out[0])

    def test_int8_inter_ef_residual_chains(self, hvd):
        """Two chained EF steps: the cumulative transmitted signal
        lands within one fresh step's error of 2x the target (the EF
        property, group edition of the two-axis test)."""
        rng = np.random.default_rng(4)
        x = rng.normal(size=(8, 128)).astype(np.float32)

        def step(v, c):
            o, r = traced.hierarchical_allreduce_groups(
                v[0] + c[0], op=Sum, stages=STAGES_84,
                inter_wire="int8", block_size=64, seed=11,
                return_residual=True,
            )
            return o[None], r[None]

        f = _sm(step, ins=(P("hvd"), P("hvd")), outs=(P("hvd"), P("hvd")))
        want = x.sum(0)
        scale = np.abs(want).max() / 127.0
        carry = jnp.zeros_like(jnp.asarray(x))
        outs = []
        for _ in range(2):
            o, carry = f(jnp.asarray(x), carry)
            outs.append(np.asarray(o))
        cum = np.abs(outs[0][0] + outs[1][0] - 2 * want).max()
        assert cum < 4.0 * scale
        # the carry really changed what step 2 transmitted
        assert np.abs(outs[1] - outs[0]).max() > 0.0

    @pytest.mark.parametrize("op", [Sum, Average])
    def test_reducescatter_bitexact_integer(self, hvd, op):
        rng = np.random.default_rng(5)
        panes = _ints(rng, (8, 8, 5))

        def flat(v):
            out = jax.lax.psum_scatter(
                v[0], "hvd", scatter_dimension=0, tiled=True
            )
            return out / 8 if op == Average else out

        ref = np.asarray(_sm(flat)(panes))
        got = np.asarray(
            _sm(
                lambda v: traced.hierarchical_reducescatter(
                    v[0], op=op, stages=STAGES_84
                )[None]
            )(panes)
        )
        np.testing.assert_array_equal(ref, got)

    def test_allgather_bitexact_and_int8(self, hvd):
        rng = np.random.default_rng(6)
        shards = _ints(rng, (8, 5))
        ref = np.asarray(
            _sm(lambda v: jax.lax.all_gather(v[0], "hvd")[None])(shards)
        )
        got = np.asarray(
            _sm(
                lambda v: traced.hierarchical_allgather(
                    v[0], stages=STAGES_84
                )[None]
            )(shards)
        )
        np.testing.assert_array_equal(ref, got)
        g8 = np.asarray(
            _sm(
                lambda v: traced.hierarchical_allgather(
                    v[0], stages=STAGES_84, inter_wire="int8",
                    block_size=4, seed=1,
                )[None]
            )(shards)
        )
        scale = np.abs(shards).max() / 127.0
        assert np.abs(g8 - ref).max() <= 1.5 * scale
        for r in range(8):
            np.testing.assert_array_equal(g8[r], g8[0])


class TestMaskedDegeneration:
    """psets and join masks have no uniform group shape under the
    two-level split — the routing must degenerate to the (bit-exact)
    flat masked wire, never half-apply the hierarchy."""

    def test_join_mask_bitexact_vs_flat(self, hvd):
        rng = np.random.default_rng(30)
        x = _ints(rng, (8, 48))
        mask = np.array([True] * 6 + [False] * 2)

        def body(v, stages):
            out = overlap.bucketed_allreduce(
                {"g": v[0]}, op=Average, n_buckets=2,
                min_bucket_bytes=0, mask=mask, hier_stages=stages,
            )
            return out["g"][None]

        flat = np.asarray(_sm(partial(body, stages=None))(x))
        hier = np.asarray(_sm(partial(body, stages=STAGES_84))(x))
        np.testing.assert_array_equal(flat, hier)
        np.testing.assert_array_equal(flat[0], x[:6].sum(0) / 6)

    def test_pset_bitexact_vs_flat(self, hvd):
        import horovod_tpu as hvd_mod
        from horovod_tpu.common.process_sets import ProcessSet

        ps = ProcessSet([0, 1, 2, 3])
        ps.process_set_id = 7  # proper subset (not the global set)
        rng = np.random.default_rng(31)
        x = _ints(rng, (8, 32))

        def body(v, stages):
            out = overlap.bucketed_allreduce(
                {"g": v[0]}, op=Sum, n_buckets=2, min_bucket_bytes=0,
                process_set=ps, hier_stages=stages,
            )
            return out["g"][None]

        flat = np.asarray(_sm(partial(body, stages=None))(x))
        hier = np.asarray(_sm(partial(body, stages=STAGES_84))(x))
        np.testing.assert_array_equal(flat, hier)
        np.testing.assert_array_equal(flat[0], x[:4].sum(0))
        np.testing.assert_array_equal(flat[5], x[5])  # outsider keeps input

    def test_eager_mask_keeps_flat_wire(self, monkeypatch):
        """The fused dispatcher: a join-masked batch under forced
        hierarchy still computes the exact masked result (the spec
        degenerates before the core compiles)."""
        import horovod_tpu as hvd_mod

        monkeypatch.setenv("HOROVOD_HIERARCHICAL", "on")
        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "4")
        hvd_mod.shutdown()
        hvd_mod.init()
        try:
            rng = np.random.default_rng(32)
            per = _ints(rng, (8, 40))
            x = hvd_mod.shard_from_rank_fn(
                lambda r: per[r], hvd_mod.mesh()
            )
            mask = np.array([True] * 6 + [False] * 2)
            out = np.asarray(
                jax.device_get(
                    hvd_mod.allreduce(x, op=hvd_mod.Average, mask=mask)
                )
            )
            np.testing.assert_array_equal(out[0], per[:6].sum(0) / 6)
        finally:
            hvd_mod.shutdown()


# ---------------------------------- lowered-module stage structure


# structure gates ride the shared horovod_tpu.analysis parser — no
# per-file regex over as_text()

INTRA_84 = ((0, 1, 2, 3), (4, 5, 6, 7))
INTER_84 = ((0, 4), (1, 5), (2, 6), (3, 7))


def _tree(rng, shapes):
    return {
        f"p{i}": jnp.asarray(
            np.broadcast_to(
                _ints(rng, (8,) + s, -40, 40), (8,) + s
            ).copy()
        )
        for i, s in enumerate(shapes)
    }


class TestLoweredStructure:
    def test_per_bucket_intra_rs_inter_ar_intra_ag(self, hvd):
        """With N buckets on the hierarchical wire, the lowered module
        carries exactly N intra-group reduce-scatters + N inter-group
        all-reduces + N intra-group all-gathers, and no bucket's
        collective chain depends on another's (independence — the
        overlap contract survives the two-level decomposition)."""
        rng = np.random.default_rng(7)
        t = _tree(rng, [(64,), (33,), (7,)])

        def body(tr):
            local = jax.tree_util.tree_map(lambda x: x[0], tr)
            out = overlap.bucketed_allreduce(
                local, op=Sum, n_buckets=3, min_bucket_bytes=0,
                hier_stages=STAGES_84,
            )
            return jax.tree_util.tree_map(lambda x: x[None], out)

        fn = _sm(body)
        g = analysis.parse_module(fn.lower(t))
        counts = g.counts()
        assert counts["reduce_scatter"] == counts["all_reduce"]
        assert counts["all_reduce"] == counts["all_gather"]
        assert counts["reduce_scatter"] >= 2  # 3 leaves -> >= 2 buckets
        # intra groups on RS/AG, inter groups on the AR; no bucket's
        # inter stage depends on another's
        analysis.expect(
            g,
            analysis.ReplicaGroupStructure(
                "reduce_scatter", groups=INTRA_84, require_present=True
            ),
            analysis.ReplicaGroupStructure(
                "all_gather", groups=INTRA_84, require_present=True
            ),
            analysis.ReplicaGroupStructure(
                "all_reduce", groups=INTER_84, require_present=True,
                forbid_world_spanning=True,
            ),
            analysis.NoInterCollectiveDefUse("all_reduce"),
        )
        # and the result is bit-exact vs the flat wire
        flat = jax.device_get(
            _sm(
                lambda tr: jax.tree_util.tree_map(
                    lambda x: x[None],
                    overlap.bucketed_allreduce(
                        jax.tree_util.tree_map(lambda x: x[0], tr),
                        op=Sum, n_buckets=3, min_bucket_bytes=0,
                        hier_stages=None,
                    ),
                )
            )(t)
        )
        hier = jax.device_get(fn(t))
        for k in t:
            np.testing.assert_array_equal(flat[k], hier[k])

    def test_zero_legs_hier_structure_and_parity(self, hvd):
        """The ZeRO bucket legs: hierarchical RS/AG are bit-exact vs
        flat on integer payloads, and the lowered RS leg carries
        intra-group reduce-scatters (the DCN hop sees 1/L panes)."""
        rng = np.random.default_rng(8)
        t = _tree(rng, [(64,), (33,)])

        def rs(tr, stages):
            local = jax.tree_util.tree_map(lambda x: x[0], tr)
            out = overlap.bucketed_reduce_scatter(
                local, op=Sum, n_buckets=2, min_bucket_bytes=0,
                hier_stages=stages,
            )
            return jax.tree_util.tree_map(lambda x: x[None], out)

        f_flat = _sm(partial(rs, stages=None))
        f_hier = _sm(partial(rs, stages=STAGES_84))
        a = jax.device_get(f_flat(t))
        b = jax.device_get(f_hier(t))
        for k in t:
            np.testing.assert_array_equal(a[k], b[k])
        g_hier = analysis.parse_module(f_hier.lower(t))
        # the DCN hop sees 1/L panes: the RS leg carries intra-group
        # reduce-scatters (the inter exchange rides its own groups)
        assert INTRA_84 in g_hier.replica_groups("reduce_scatter")

        def ag(tr, stages):
            local = jax.tree_util.tree_map(lambda x: x[0], tr)
            sh = overlap.bucketed_reduce_scatter(
                local, op=Sum, n_buckets=2, min_bucket_bytes=0,
                hier_stages=None,
            )
            full = overlap.bucketed_shard_all_gather(
                sh, local, n_buckets=2, min_bucket_bytes=0,
                hier_stages=stages,
            )
            return jax.tree_util.tree_map(lambda x: x[None], full)

        a = jax.device_get(_sm(partial(ag, stages=None))(t))
        b = jax.device_get(_sm(partial(ag, stages=STAGES_84))(t))
        for k in t:
            np.testing.assert_array_equal(a[k], b[k])


# ----------------------------------- default routing: fused + ZeRO


class TestDefaultRouting:
    def _reinit(self, monkeypatch, **env):
        import horovod_tpu as hvd_mod

        for k, v in env.items():
            monkeypatch.setenv(k, v)
        hvd_mod.shutdown()
        hvd_mod.init()
        return hvd_mod

    def test_fused_eager_hier_default_bitexact(self, monkeypatch):
        hvd_mod = self._reinit(
            monkeypatch,
            HOROVOD_HIERARCHICAL="on",
            HOROVOD_INTRA_SIZE="4",
        )
        try:
            rng = np.random.default_rng(9)
            per = _ints(rng, (8, 513))
            x = hvd_mod.shard_from_rank_fn(
                lambda r: per[r], hvd_mod.mesh()
            )
            out = np.asarray(
                jax.device_get(hvd_mod.allreduce(x, op=hvd_mod.Sum))
            )
            np.testing.assert_array_equal(out[0], per.sum(0))
            from horovod_tpu.common import basics

            st = basics.state().fusion.cache_stats()
            assert st["hier_dispatches"] >= 1
            assert st["wire_bytes_saved_inter"] > 0
            assert st["wire_bytes_saved_intra"] == 0  # fp32 intra
            # still one dispatch for the batch
            assert basics.state().fusion.last_cycle_dispatches == 1
        finally:
            hvd_mod.shutdown()

    def test_fused_eager_hier_off_by_default_on_single_slice(
        self, monkeypatch
    ):
        hvd_mod = self._reinit(monkeypatch)  # auto, no evidence
        try:
            rng = np.random.default_rng(10)
            per = _ints(rng, (8, 64))
            x = hvd_mod.shard_from_rank_fn(
                lambda r: per[r], hvd_mod.mesh()
            )
            np.asarray(jax.device_get(hvd_mod.allreduce(x, op=hvd_mod.Sum)))
            from horovod_tpu.common import basics

            assert (
                basics.state().fusion.cache_stats()["hier_dispatches"] == 0
            )
        finally:
            hvd_mod.shutdown()

    def test_int8_wire_places_bf16_intra_int8_inter(self, monkeypatch):
        hvd_mod = self._reinit(
            monkeypatch,
            HOROVOD_HIERARCHICAL="on",
            HOROVOD_INTRA_SIZE="4",
        )
        try:
            from horovod_tpu.ops.compression import Compression

            rng = np.random.default_rng(11)
            per = rng.normal(size=(8, 600)).astype(np.float32)
            h = hvd_mod.allreduce_async(
                hvd_mod.shard_from_rank_fn(
                    lambda r: per[r], hvd_mod.mesh()
                ),
                op=hvd_mod.Sum,
                compression=Compression.int8_block,
            )
            out = np.asarray(h.wait())
            want = per.sum(0)
            scale = np.abs(want).max() / 127.0
            assert np.abs(out[0] - want).max() < 4.0 * scale
            from horovod_tpu.common import basics
            from horovod_tpu.common.metrics import WIRE_FORMAT_CODES

            st = basics.state().fusion.cache_stats()
            assert st["wire_format_inter"] == WIRE_FORMAT_CODES["int8"]
            assert st["wire_format_intra"] == WIRE_FORMAT_CODES["bf16"]
            assert st["wire_bytes_saved_inter"] > 0
            assert st["wire_bytes_saved_intra"] > 0  # bf16 intra
        finally:
            hvd_mod.shutdown()

    def test_sharded_optimizer_hier_trajectory(self, monkeypatch):
        import optax

        monkeypatch.setenv("HOROVOD_HIERARCHICAL", "on")
        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "4")
        from horovod_tpu.sharded_optimizer import (
            ShardedDistributedOptimizer,
        )

        rng = np.random.default_rng(12)
        params = {
            "w": jnp.asarray(rng.normal(size=(33,)).astype(np.float32)),
            "v": jnp.asarray(rng.normal(size=(65,)).astype(np.float32)),
        }

        def run(hier):
            opt = ShardedDistributedOptimizer(
                optax.adam(1e-2), world=8, overlap_buckets=2,
                hierarchical=hier,
            )
            state = opt.init(params)
            p = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x, (8,) + x.shape), params
            )

            def step(p_, s_, g_):
                pl = jax.tree_util.tree_map(lambda x: x[0], p_)
                gl = jax.tree_util.tree_map(lambda x: x[0], g_)
                upd, s2 = opt.update(gl, s_, pl)
                p2 = optax.apply_updates(pl, upd)
                return (
                    jax.tree_util.tree_map(lambda x: x[None], p2),
                    s2,
                )

            f = _sm(
                step,
                ins=(P("hvd"), opt.state_spec(), P("hvd")),
                outs=(P("hvd"), opt.state_spec()),
            )
            for i in range(3):
                g = jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(
                        jnp.sin(x * (i + 1)), (8,) + x.shape
                    ),
                    params,
                )
                p, state = f(p, state, g)
            return jax.device_get(p)

        flat, hier = run(False), run(None)
        for k in flat:
            np.testing.assert_allclose(
                flat[k], hier[k], rtol=0, atol=1e-6
            )

    def test_elastic_8_to_6_reshard_on_two_level_mesh(self, monkeypatch):
        """The chaos geometry: a gang shrinks 8 -> 6 under
        HOROVOD_INTRA_SIZE=4. The split degrades to gcd=2 (stays
        two-level), the sharded state reshard carries moments, and the
        world-6 hierarchical update equals the world-6 flat one."""
        import optax

        monkeypatch.setenv("HOROVOD_HIERARCHICAL", "on")
        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "4")
        from horovod_tpu.sharded_optimizer import (
            ShardedDistributedOptimizer,
        )

        assert topo_mod.hierarchy_stages(world=6) == (
            [[0, 1], [2, 3], [4, 5]],
            [[0, 2, 4], [1, 3, 5]],
        )
        rng = np.random.default_rng(13)
        params = {
            "w": jnp.asarray(rng.normal(size=(45,)).astype(np.float32))
        }
        mesh6 = Mesh(np.asarray(jax.devices()[:6]), ("hvd",))

        def run(hier):
            opt8 = ShardedDistributedOptimizer(
                optax.adam(1e-2), world=8, overlap_buckets=2,
                hierarchical=hier,
            )
            state = opt8.init(params)
            # the reshard is the elastic resume contract: moments carry
            opt6 = ShardedDistributedOptimizer(
                optax.adam(1e-2), world=6, overlap_buckets=2,
                hierarchical=hier,
            )
            state6 = opt6.reshard_state(state, params, 6)
            p = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x, (6,) + x.shape), params
            )

            def step(p_, s_, g_):
                pl = jax.tree_util.tree_map(lambda x: x[0], p_)
                gl = jax.tree_util.tree_map(lambda x: x[0], g_)
                upd, s2 = opt6.update(gl, s_, pl)
                return (
                    jax.tree_util.tree_map(
                        lambda x: x[None],
                        optax.apply_updates(pl, upd),
                    ),
                    s2,
                )

            f = _sm(
                step,
                mesh=mesh6,
                ins=(P("hvd"), opt6.state_spec(), P("hvd")),
                outs=(P("hvd"), opt6.state_spec()),
            )
            g = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(
                    jnp.cos(x), (6,) + x.shape
                ),
                params,
            )
            p, state6 = f(p, state6, g)
            return jax.device_get(p)

        flat, hier = run(False), run(None)
        np.testing.assert_allclose(
            flat["w"], hier["w"], rtol=0, atol=1e-6
        )


# ---------------------------------------- hier_int8 (satellite fix)


class TestHierInt8TracedPath:
    def test_optimizer_path_is_two_level_and_matches_eager(
        self, monkeypatch
    ):
        """Compression.hier_int8 on the traced/optimizer path no longer
        collapses to flat single-stage int8: the lowered module carries
        the intra RS/AG legs, and the result agrees with the eager
        fused placement within the shared quantum budget."""
        import horovod_tpu as hvd_mod

        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "4")
        hvd_mod.shutdown()
        hvd_mod.init()
        try:
            from horovod_tpu.optimizer import _allreduce_grads
            from horovod_tpu.ops.compression import Compression

            rng = np.random.default_rng(14)
            g = rng.normal(size=(8, 600)).astype(np.float32)

            def body(t):
                out = _allreduce_grads(
                    {"g": t[0]}, Average, Compression.hier_int8,
                    1.0, 1.0, None, "hvd", seed=3,
                )
                return out["g"][None]

            f = _sm(body)
            # two-level signature: an intra reduce-scatter + the intra
            # all-gather around the inter int8 recipe
            analysis.expect(
                analysis.parse_module(f.lower(jnp.asarray(g))),
                analysis.CollectiveCount("reduce_scatter", 1),
                analysis.ReplicaGroupStructure(
                    "reduce_scatter", groups=INTRA_84
                ),
            )
            out = np.asarray(f(jnp.asarray(g)))
            want = g.mean(0)
            scale = np.abs(g.sum(0)).max() / 127.0 / 8
            assert np.abs(out[0] - want).max() < 4.0 * scale
            # eager placement on the same data agrees within budget
            h = hvd_mod.allreduce_async(
                hvd_mod.shard_from_rank_fn(
                    lambda r: g[r], hvd_mod.mesh()
                ),
                op=hvd_mod.Average,
                compression=Compression.hier_int8,
            )
            eager = np.asarray(h.wait())
            assert np.abs(eager[0] - out[0]).max() < 6.0 * scale
        finally:
            hvd_mod.shutdown()

    def test_bucketed_hier_int8_explicit_request(self, monkeypatch):
        """hier_int8 through the bucketed exchange resolves a split in
        auto mode from the explicit request alone."""
        monkeypatch.setenv("HOROVOD_INTRA_SIZE", "2")
        from horovod_tpu.ops.compression import Compression

        rng = np.random.default_rng(15)
        t = {"a": jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32))}

        def body(tr):
            local = jax.tree_util.tree_map(lambda x: x[0], tr)
            out = overlap.bucketed_allreduce(
                local, op=Sum, n_buckets=1, min_bucket_bytes=0,
                compression=Compression.hier_int8,
            )
            return jax.tree_util.tree_map(lambda x: x[None], out)

        f = _sm(body)
        analysis.expect(
            analysis.parse_module(f.lower(t)),
            analysis.CollectiveCount("reduce_scatter", 1),
            analysis.ReplicaGroupStructure(
                "reduce_scatter",
                groups=((0, 1), (2, 3), (4, 5), (6, 7)),
            ),
        )
        out = jax.device_get(f(t))["a"]
        want = np.asarray(t["a"]).sum(0)
        scale = np.abs(want).max() / 127.0
        assert np.abs(out[0] - want).max() < 4.0 * scale


# ------------------------------------------------ hierarchical Adasum


class TestHierAdasum:
    def _mesh2(self, L):
        return Mesh(
            np.asarray(jax.devices()[:8]).reshape(8 // L, L),
            (topo_mod.INTER_AXIS, topo_mod.INTRA_AXIS),
        )

    def _run(self, per, L, **kw):
        from horovod_tpu.ops import adasum

        spec = P((topo_mod.INTER_AXIS, topo_mod.INTRA_AXIS))
        f = jax.jit(
            shard_map(
                lambda x: adasum.adasum_allreduce(
                    x[0], hierarchical=True, **kw
                )[None],
                mesh=self._mesh2(L),
                in_specs=spec,
                out_specs=spec,
                check_vma=False,
            )
        )
        return np.asarray(f(jnp.asarray(per)))

    @pytest.mark.parametrize("L", [2, 4])
    def test_matches_host_oracle(self, hvd, L):
        """intra Sum -> Adasum across slices == adasum_vhdd_host over
        the per-slice sums (the reference's hierarchical semantics,
        adasum_gpu_operations.cc [V])."""
        from horovod_tpu.ops import adasum

        H = 8 // L
        rng = np.random.default_rng(16)
        per = rng.normal(size=(8, 97)).astype(np.float32)
        want = adasum.adasum_vhdd_host(
            [per[e * L : (e + 1) * L].sum(0) for e in range(H)]
        )
        got = self._run(per, L)
        np.testing.assert_allclose(got[0], want, rtol=1e-5, atol=1e-5)
        for r in range(8):
            np.testing.assert_array_equal(got[r], got[0])

    def test_scale_invariance(self, hvd):
        rng = np.random.default_rng(17)
        per = rng.normal(size=(8, 64)).astype(np.float32)
        a = self._run(per, 4)
        b = self._run(per * 1000.0, 4)
        np.testing.assert_allclose(
            b[0] / 1000.0, a[0], rtol=1e-4, atol=1e-5
        )

    @pytest.mark.parametrize("L", [2, 4])
    def test_int8_inter_wire_consistent_within_quanta(self, hvd, L):
        """The quantized inter wire: all replicas agree BITWISE (the
        owner-consumes-wire-value rule + piece-class keys) and the
        result stays within a few quanta of the exact composition."""
        from horovod_tpu.ops import adasum

        H = 8 // L
        rng = np.random.default_rng(18)
        per = rng.normal(size=(8, 97)).astype(np.float32)
        want = adasum.adasum_vhdd_host(
            [per[e * L : (e + 1) * L].sum(0) for e in range(H)]
        )
        got = self._run(per, L, inter_wire="int8", seed=5)
        for r in range(8):
            np.testing.assert_array_equal(got[r], got[0])
        scale = np.abs(want).max() / 127.0
        assert np.abs(got[0] - want).max() < 6.0 * scale

    def test_rejects_process_sets(self, hvd):
        from horovod_tpu.ops import adasum
        from horovod_tpu.common.process_sets import ProcessSet

        with pytest.raises(NotImplementedError):
            adasum.adasum_allreduce(
                jnp.zeros(4), hierarchical=True,
                process_set=ProcessSet([0, 1]),
            )


# ---------------------------------------------- per-hop wire tuning


class TestPerHopWire:
    def test_intra_hop_never_int8(self):
        overlap.reset_wire_tuner()
        assert (
            overlap.resolve_wire("int8", 1 << 20, hop="intra") == "fp32"
        )
        assert (
            overlap.resolve_wire("int8", 1 << 20, hop="inter") == "int8"
        )

    def test_hop_keys_are_disjoint(self):
        overlap.reset_wire_tuner()
        t = overlap.wire_tuner()
        key = ("bucket", 1 << 20)
        # teach the inter hop that int8 is great; the intra hop must
        # not inherit that observation
        for _ in range(t.trials):
            t.record(key + ("inter",), "int8", 1 << 20, 1e-3)
            t.record(key + ("inter",), "fp32", 1 << 20, 1.0)
            t.record(key + ("inter",), "bf16", 1 << 20, 1.0)
        assert (
            overlap.resolve_wire("auto", 1 << 20, key=key, hop="inter")
            == "int8"
        )
        assert (
            overlap.resolve_wire("auto", 1 << 20, key=key, hop="intra")
            != "int8"
        )
        overlap.reset_wire_tuner()


# ------------------------------------------------ straggler rebalance


class TestRebalance:
    def _driver(self, monkeypatch, enabled=True):
        import types

        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.elastic.discovery import HostDiscovery
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.rendezvous import KVStore

        class Disc(HostDiscovery):
            def find_available_hosts_and_slots(self):
                return [HostInfo("a", 4), HostInfo("b", 4)]

        if enabled:
            monkeypatch.setenv("HOROVOD_REBALANCE", "1")
        d = ElasticDriver(Disc(), ["true"], min_np=1)
        d._server = types.SimpleNamespace(store=KVStore())
        return d

    def _beat(self, d, p50s, ts):
        for r, p in p50s.items():
            d.stall_inspector.record_heartbeat(
                r, ts=ts, step=100, step_ms_p50=p
            )
        d.stall_inspector.check()

    def test_down_weights_persistent_straggler(self, monkeypatch):
        import time

        from horovod_tpu.runner.rendezvous import (
            read_rebalance_weights,
        )

        d = self._driver(monkeypatch)
        p50s = {0: 100.0, 1: 100.0, 2: 100.0, 3: 800.0}
        now = time.time()
        # streak 1 (fresh stamp): no rebalance yet
        self._beat(d, p50s, now)
        d._maybe_rebalance()
        assert read_rebalance_weights(d._server.store) == {}
        # streak 2 (second FRESH stamp): rank 3 down-weighted
        self._beat(d, p50s, now + 10)
        d._maybe_rebalance()
        w = read_rebalance_weights(d._server.store)
        assert w[3] < 1.0
        assert w[0] == w[1] == w[2] == 1.0
        assert w[3] == max(0.25, min(1.0, round(100.0 / 800.0, 2)))
        # recovery publishes the reset map
        p50s[3] = 100.0
        self._beat(d, p50s, now + 20)
        d._maybe_rebalance()
        w = read_rebalance_weights(d._server.store)
        assert all(v == 1.0 for v in w.values())

    def test_stale_stamp_does_not_advance(self, monkeypatch):
        import time

        from horovod_tpu.runner.rendezvous import (
            read_rebalance_weights,
        )

        d = self._driver(monkeypatch)
        p50s = {0: 100.0, 1: 100.0, 2: 800.0}
        now = time.time()
        self._beat(d, p50s, now)
        # the driver polls faster than workers beat: same stamp again
        self._beat(d, p50s, now)
        d._maybe_rebalance()
        assert read_rebalance_weights(d._server.store) == {}

    def test_disabled_publishes_nothing(self, monkeypatch):
        import time

        from horovod_tpu.runner.rendezvous import (
            read_rebalance_weights,
        )

        d = self._driver(monkeypatch, enabled=False)
        now = time.time()
        self._beat(d, {0: 100.0, 1: 900.0}, now)
        self._beat(d, {0: 100.0, 1: 900.0}, now + 10)
        d._maybe_rebalance()
        assert read_rebalance_weights(d._server.store) == {}

    def test_worker_read_helpers(self, monkeypatch):
        from horovod_tpu.elastic import worker as worker_mod
        from horovod_tpu.runner.rendezvous import (
            KVStore,
            put_rebalance_weights,
            read_rebalance_weights,
        )

        store = KVStore()
        put_rebalance_weights(store, {0: 1.0, 3: 0.5}, epoch=2)
        assert read_rebalance_weights(store) == {0: 1.0, 3: 0.5}
        # no rendezvous configured: helpers degrade to defaults
        monkeypatch.delenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", raising=False)
        assert worker_mod.rebalance_weights() == {}
        assert worker_mod.rebalance_weight(rank=3) == 1.0

    def test_malformed_blob_reads_empty(self):
        from horovod_tpu.runner.rendezvous import (
            KVStore,
            REBALANCE_SCOPE,
            read_rebalance_weights,
        )

        store = KVStore()
        store.put(REBALANCE_SCOPE, "weights", b"\xff not json")
        assert read_rebalance_weights(store) == {}
