"""``import horovod_tpu.torch as hvd`` — the PyTorch binding surface.

Parity with the reference's largest user-facing module
(ref: horovod/torch/__init__.py + mpi_ops.py + optimizer.py +
functions.py [V] — SURVEY.md §2.4): torch users port their scripts by
changing one import. Tensors are bridged host-side — each call views
the torch storage (``.detach().cpu().numpy()`` is zero-copy for CPU
tensors), transfers once into the eager collective path, is reduced by
XLA over the mesh, and comes back via **dlpack** when the result lives
on a CPU jax device (``torch.from_dlpack`` shares the XLA buffer — no
copy; VERDICT r3 #6, the role of the reference's zero-copy
``adapter_v2.cc`` [V]). On a TPU backend the return is one
device-to-host transfer + ``torch.from_numpy`` without an extra host
copy. Worst case one host copy each way; never the old
numpy→copy→from_numpy double round-trip.

The async handle protocol (`allreduce_async_` → `synchronize`) is kept:
handles wrap the eager path's fusion-cycle handles, so Horovod's
tensor-fusion batching applies to torch dispatches too.

Scope note: this is the compatibility layer for torch-on-CPU driving
TPU collectives (each call moves host↔device — same cost profile as
the reference's CPU-tensor path through MPI [V]). The native-speed path
for TPU training remains the JAX API.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common.basics import (  # noqa: F401
    add_process_set,
    cross_rank,
    cross_size,
    global_process_set,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    remove_process_set,
    shutdown,
    size,
)
from ..common.process_sets import (  # noqa: F401
    ProcessSet,
    warn_nonmember_controller as _warn_nonmember_controller,
)
from ..ops import eager as _eager
from ..ops.reduction_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
)


def _torch():
    import torch

    return torch


class _NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _FP16Compressor:
    """fp16 wire compression on torch tensors (ref:
    horovod/torch/compression.py [V])."""

    @staticmethod
    def compress(tensor):
        torch = _torch()
        ctx = tensor.dtype
        if tensor.is_floating_point():
            tensor = tensor.to(torch.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if tensor.dtype != ctx else tensor


class Compression:
    """hvd.Compression namespace for torch tensors [V]."""

    none = _NoneCompressor
    fp16 = _FP16Compressor


def _to_numpy(tensor) -> np.ndarray:
    return tensor.detach().cpu().numpy()


def _from_numpy(array: np.ndarray, like):
    torch = _torch()
    contig = np.ascontiguousarray(array)
    if contig.shape != array.shape:  # ascontiguousarray promotes 0-d to (1,)
        contig = contig.reshape(array.shape)
    if not contig.flags.writeable:
        # torch.from_numpy refuses read-only views (e.g. a CPU-backend
        # jax array's __array__); only then is a defensive copy needed
        contig = contig.copy()
    return torch.from_numpy(contig).to(
        dtype=like.dtype, device=like.device
    )


def _jax_to_torch(jax_row, like):
    """Result bridge with a dlpack zero-copy fast path (VERDICT r3 #6;
    the role of the reference's zero-copy adapter layer,
    horovod/torch/adapter_v2.cc [V]).

    When the collective result lives on a CPU jax device and the caller
    wants a CPU torch tensor, ``torch.from_dlpack`` shares the XLA
    buffer — no host copy at all on the way out (the buffer is a fresh
    per-call result, so aliasing it to the returned tensor is safe).
    Any failure (TPU-resident result, exotic dtype, dlpack version
    skew) falls back to the documented one-copy numpy path.
    """
    torch = _torch()
    try:
        if like.device.type == "cpu" and list(
            d.platform for d in jax_row.devices()
        ) == ["cpu"]:
            out = torch.from_dlpack(jax_row)
            return out.to(dtype=like.dtype)  # no-op when dtypes match
    except Exception:
        pass
    return _from_numpy(np.asarray(jax_row), like)


def _replicated_payload(tensor):
    """Torch calls are per-rank SPMD in the reference; under the single
    controller every rank's contribution is this process's tensor — the
    rank-major payload is the replicated stack."""
    return _eager.replicate(_to_numpy(tensor))


class _TorchHandle:
    """Async handle over the eager fusion handle (ref: handle_manager.cc
    + synchronize/poll in horovod/torch/mpi_ops.py [V])."""

    def __init__(self, inner, like, inplace_target=None, post=None):
        self._inner = inner
        self._like = like
        self._target = inplace_target
        self._post = post

    def poll(self) -> bool:
        return self._inner.poll()

    def wait(self):
        result = self._inner.wait()
        row = _eager.first(result)
        if self._post is not None:
            out = _from_numpy(self._post(np.asarray(row)), self._like)
        else:
            out = _jax_to_torch(row, self._like)
            if out.numel() == int(np.prod(self._like.shape)) and tuple(
                out.shape
            ) != tuple(self._like.shape):
                # 0-dim torch scalars round-trip as shape-(1,) payloads;
                # restore the caller's shape before any in-place copy.
                out = out.reshape(tuple(self._like.shape))
        if self._target is not None:
            self._target.copy_(out)
            return self._target
        return out


def allreduce_async(
    tensor, average=None, name=None, op=None, process_set=None,
    prescale_factor=1.0, postscale_factor=1.0,
) -> _TorchHandle:
    _warn_nonmember_controller("allreduce", process_set)
    handle = _eager.allreduce_async(
        _replicated_payload(tensor), average=average, name=name, op=op,
        process_set=process_set, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
    )
    return _TorchHandle(handle, tensor)


def allreduce(tensor, average=None, name=None, op=None, process_set=None,
              prescale_factor=1.0, postscale_factor=1.0):
    return allreduce_async(
        tensor, average=average, name=name, op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    ).wait()


def allreduce_async_(
    tensor, average=None, name=None, op=None, process_set=None,
    prescale_factor=1.0, postscale_factor=1.0,
) -> _TorchHandle:
    _warn_nonmember_controller("allreduce_", process_set)
    handle = _eager.allreduce_async(
        _replicated_payload(tensor), average=average, name=name, op=op,
        process_set=process_set, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
    )
    return _TorchHandle(handle, tensor, inplace_target=tensor)


def allreduce_(tensor, average=None, name=None, op=None, process_set=None,
               prescale_factor=1.0, postscale_factor=1.0):
    return allreduce_async_(
        tensor, average=average, name=name, op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor
    ).wait()


class _GroupedHandle:
    """One handle over a group — hvd.synchronize(handle) on the grouped
    async result must work like the reference's [V]."""

    def __init__(self, handles):
        self._handles = handles

    def poll(self) -> bool:
        return all(h.poll() for h in self._handles)

    def wait(self):
        return [h.wait() for h in self._handles]


def grouped_allreduce_async(
    tensors, average=None, name=None, op=None, process_set=None,
    prescale_factor=1.0, postscale_factor=1.0,
) -> _GroupedHandle:
    """Atomic multi-tensor allreduce (ref: hvd.grouped_allreduce /
    group_table.cc [V]): rides the eager path's begin/end_group so the
    whole list lands in ONE fusion cycle — per-tensor enqueues could be
    split across cycles by a threshold flush mid-group."""
    _warn_nonmember_controller("grouped_allreduce", process_set)
    handles = _eager.grouped_allreduce_async(
        [_replicated_payload(t) for t in tensors],
        average=average, name=name, op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    )
    return _GroupedHandle(
        [_TorchHandle(h, t) for h, t in zip(handles, tensors)]
    )


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      process_set=None, prescale_factor=1.0,
                      postscale_factor=1.0):
    return grouped_allreduce_async(
        tensors, average=average, name=name, op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    ).wait()


def _gather_post(host):
    # eager allgather returns rank-major [world, n, ...]; torch's
    # contract concatenates along dim 0 [V]
    return host.reshape((-1,) + host.shape[2:])


def grouped_allgather_async(tensors, name=None, process_set=None):
    """Atomic multi-tensor allgather (ref: hvd.grouped_allgather,
    upstream v0.28+ [V])."""
    _warn_nonmember_controller("grouped_allgather", process_set)
    handles = _eager.grouped_allgather_async(
        [_replicated_payload(t) for t in tensors], name=name,
        process_set=process_set,
    )
    return _GroupedHandle(
        [
            _TorchHandle(h, t, post=_gather_post)
            for h, t in zip(handles, tensors)
        ]
    )


def grouped_allgather(tensors, name=None, process_set=None):
    return grouped_allgather_async(
        tensors, name=name, process_set=process_set
    ).wait()


def grouped_reducescatter_async(tensors, op=None, name=None,
                                process_set=None):
    """Atomic multi-tensor reduce-scatter (ref:
    hvd.grouped_reducescatter, upstream v0.28+ [V])."""
    _warn_nonmember_controller("grouped_reducescatter", process_set)
    handles = _eager.grouped_reducescatter_async(
        [_replicated_payload(t) for t in tensors], op=op, name=name,
        process_set=process_set,
    )
    return _GroupedHandle(
        [_TorchHandle(h, t) for h, t in zip(handles, tensors)]
    )


def grouped_reducescatter(tensors, op=None, name=None, process_set=None):
    return grouped_reducescatter_async(
        tensors, op=op, name=name, process_set=process_set
    ).wait()


def allgather_async(tensor, name=None, process_set=None) -> _TorchHandle:
    _warn_nonmember_controller("allgather", process_set)
    handle = _eager.allgather_async(
        _replicated_payload(tensor), name=name, process_set=process_set
    )
    return _TorchHandle(handle, tensor, post=_gather_post)


def allgather(tensor, name=None, process_set=None):
    return allgather_async(tensor, name=name, process_set=process_set).wait()


def broadcast_async(
    tensor, root_rank, name=None, process_set=None
) -> _TorchHandle:
    _warn_nonmember_controller("broadcast", process_set)
    handle = _eager.broadcast_async(
        _replicated_payload(tensor), root_rank, name=name,
        process_set=process_set,
    )
    return _TorchHandle(handle, tensor)


def broadcast(tensor, root_rank, name=None, process_set=None):
    return broadcast_async(
        tensor, root_rank, name=name, process_set=process_set
    ).wait()


def broadcast_async_(
    tensor, root_rank, name=None, process_set=None
) -> _TorchHandle:
    _warn_nonmember_controller("broadcast_", process_set)
    handle = _eager.broadcast_async(
        _replicated_payload(tensor), root_rank, name=name,
        process_set=process_set,
    )
    return _TorchHandle(handle, tensor, inplace_target=tensor)


def broadcast_(tensor, root_rank, name=None, process_set=None):
    return broadcast_async_(
        tensor, root_rank, name=name, process_set=process_set
    ).wait()


def reducescatter_async(
    tensor, op=None, name=None, process_set=None
) -> _TorchHandle:
    """Reduce-scatter: this rank's shard of the world-reduced tensor,
    split along dim 0 (ref: hvd.reducescatter, upstream v0.27+ [V]).
    Under the single controller this process is rank 0, so the handle's
    rank-0 row IS our shard — even and uneven (v-variant) cases both."""
    _warn_nonmember_controller("reducescatter", process_set)
    handle = _eager.reducescatter_async(
        _replicated_payload(tensor), op=op, name=name,
        process_set=process_set,
    )
    return _TorchHandle(handle, tensor)


def reducescatter(tensor, op=None, name=None, process_set=None):
    return reducescatter_async(
        tensor, op=op, name=name, process_set=process_set
    ).wait()


def alltoall(tensor, splits=None, name=None, process_set=None):
    _warn_nonmember_controller("alltoall", process_set)
    if splits is not None:
        # Uneven alltoall-v: this rank's 1-D `splits` says how many dim-0
        # rows go to each peer (set members when a process set is given);
        # replicated across ranks under the single controller. Returns
        # (output, received_splits) like the reference's torch binding [V].
        torch = _torch()
        world = size()
        participants = (
            len(process_set.ranks)
            if process_set is not None and process_set.process_set_id != 0
            else world
        )
        host = _to_numpy(tensor)
        splits_1d = [int(s) for s in np.asarray(_to_numpy(splits)
                     if torch.is_tensor(splits) else splits).tolist()]
        if len(splits_1d) != participants:
            raise ValueError(
                f"splits has {len(splits_1d)} entries but the exchange "
                f"has {participants} participants"
            )
        if sum(splits_1d) != host.shape[0]:
            raise ValueError(
                f"splits sum to {sum(splits_1d)} but tensor dim0 is "
                f"{host.shape[0]}"
            )
        handle = _eager.alltoall_async(
            [host] * world, splits=[splits_1d] * world, name=name,
            process_set=process_set,
        )
        outputs, recv_splits = handle.wait()
        # single controller: this process is rank 0; with a set that
        # excludes rank 0 the exchange happened among the members and
        # rank 0's row passed through unchanged
        if (
            process_set is not None
            and process_set.process_set_id != 0
            and 0 not in process_set.ranks
        ):
            # Identity pass-through: the eager path may hand back a
            # zero-copy view of the caller's own input storage, so the
            # dlpack fast path would alias output to input (mutating
            # one would corrupt the other). Force a real copy here.
            out = _from_numpy(np.array(outputs[0], copy=True), tensor)
        else:
            out = _jax_to_torch(outputs[0], tensor)
        return out, torch.tensor(recv_splits[0], dtype=torch.int32)
    handle = _eager.alltoall_async(
        _replicated_payload(tensor), name=name, process_set=process_set
    )
    return _TorchHandle(handle, tensor).wait()


def synchronize(handle: _TorchHandle):
    return handle.wait()


def poll(handle: _TorchHandle) -> bool:
    return handle.poll()


def join(joined_ranks=None) -> int:
    return _eager.join(joined_ranks)


def barrier(process_set=None) -> None:
    """Block until all processes (or all members of ``process_set``)
    reach the barrier (ref: horovod.torch.barrier [V])."""
    _eager.barrier(process_set=process_set)


# ------------------------------------------------------- module helpers


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of an nn.Module's state_dict or named_parameters
    (ref: horovod/torch/functions.py broadcast_parameters [V])."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    for name, p in items:
        if p is None:
            continue
        broadcast_(p.data if hasattr(p, "data") else p, root_rank, name=name)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast a torch.optim state dict from root (ref:
    broadcast_optimizer_state [V]): tensor leaves ride collectives, the
    structural scalars ride broadcast_object."""
    torch = _torch()
    state_dict = optimizer.state_dict()

    from ..optimizer import broadcast_object

    meta = {
        "param_groups": state_dict["param_groups"],
        "scalar_state": {
            pid: {
                k: v
                for k, v in s.items()
                if not torch.is_tensor(v)
            }
            for pid, s in state_dict.get("state", {}).items()
        },
    }
    meta = broadcast_object(meta, root_rank=root_rank)
    state_dict["param_groups"] = meta["param_groups"]
    for pid, s in state_dict.get("state", {}).items():
        for key, value in list(s.items()):
            if torch.is_tensor(value):
                broadcast_(value, root_rank, name=f"opt.{pid}.{key}")
            else:
                s[key] = meta["scalar_state"][pid][key]
    optimizer.load_state_dict(state_dict)


def allgather_object(obj, name: Optional[str] = None):
    """Gather one picklable object per rank, rank-ordered list (ref:
    horovod/torch/functions.py allgather_object [V])."""
    from ..optimizer import allgather_object as _ao

    return _ao(obj, name=name)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    from ..optimizer import broadcast_object as _bo

    return _bo(obj, root_rank=root_rank, name=name)


class DistributedOptimizer:
    """torch.optim wrapper: allreduce grads on step() (ref:
    horovod/torch/optimizer.py _DistributedOptimizer [V]; hook-per-grad
    becomes a grouped async reduce at step time — same fusion window,
    no autograd-engine hooks needed)."""

    def __init__(
        self,
        optimizer,
        named_parameters=None,
        compression=Compression.none,
        backward_passes_per_step: int = 1,
        op=None,
    ):
        self._opt = optimizer
        self._op = op
        self._compression = compression
        self._k = max(int(backward_passes_per_step), 1)
        self._micro = 0
        self._accum = {}  # id(param) -> local gradient sum across microsteps
        if named_parameters is not None:
            self._names = {id(p): n for n, p in named_parameters}
        else:
            self._names = {}

    def __getattr__(self, name):
        return getattr(self._opt, name)

    def _grad_tensors(self):
        for group in self._opt.param_groups:
            for p in group["params"]:
                if p.grad is not None:
                    yield p

    def step(self, closure=None):
        self._micro += 1
        if self._k > 1:
            # Snapshot this microbatch's grads into our own buffers so
            # the canonical loop's zero_grad() between microbatches
            # can't discard them (ref: hook-time accumulation,
            # local_gradient_aggregation [V]).
            torch = _torch()
            for p in self._grad_tensors():
                buf = self._accum.get(id(p))
                if buf is None:
                    buf = torch.zeros_like(p.grad)
                    self._accum[id(p)] = buf
                buf.add_(p.grad)
        if self._micro < self._k:
            return None  # local aggregation window: skip comm + step
        return self._reduce_and_step(closure)

    def flush(self, closure=None):
        """Force a pending partial aggregation window to reduce + step
        now. Owners of the training loop (e.g. spark.TorchEstimator)
        call this at epoch/run boundaries so a step count that doesn't
        divide backward_passes_per_step can't silently discard the tail
        window's gradients. No-op when the window is empty."""
        if self._micro == 0:
            return None
        return self._reduce_and_step(closure)

    def _reduce_and_step(self, closure=None):
        self._micro = 0
        handles = []
        if self._k > 1:
            # Flush the UNION of accumulated params, not just those with
            # a grad on the boundary microbatch — a param whose final
            # microstep produced no grad still owes its earlier sums.
            by_id = {
                id(p): p
                for group in self._opt.param_groups
                for p in group["params"]
            }
            reduce_params = []
            for pid, buf in list(self._accum.items()):
                p = by_id.get(pid)
                # Remove the buffer either way: a param that stops
                # getting grads must not be re-reduced with zeros (and
                # stepped by stateful optimizers) in later cycles.
                del self._accum[pid]
                if p is None:
                    continue
                if p.grad is None:
                    p.grad = buf
                else:
                    p.grad.copy_(buf)
                reduce_params.append(p)
        else:
            reduce_params = list(self._grad_tensors())
        for p in reduce_params:
            name = self._names.get(id(p), f"grad.{id(p)}")
            wire, ctx = self._compression.compress(p.grad)
            handle = allreduce_async_(
                wire, op=self._op, name=name
            )
            handles.append((p, handle, ctx))
        for p, handle, ctx in handles:
            reduced = handle.wait()
            p.grad.copy_(self._compression.decompress(reduced, ctx))
        return self._opt.step(closure)

    def zero_grad(self, *args, **kwargs):
        return self._opt.zero_grad(*args, **kwargs)

    def synchronize(self):  # API parity; step() already synchronizes
        return None

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, sd):
        return self._opt.load_state_dict(sd)


def __getattr__(name):  # PEP 562 — SyncBatchNorm builds its torch base
    # class on first access, keeping this module importable without
    # torch until a torch-typed symbol is actually used.
    if name == "SyncBatchNorm":
        from . import sync_batch_norm

        return sync_batch_norm.SyncBatchNorm
    if name == "elastic":
        # hvd.elastic.run / hvd.elastic.TorchState from the shim
        # namespace, matching horovod.torch.elastic [V]
        import importlib

        return importlib.import_module(".elastic", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
