"""Serving A/B harness (horovod_tpu/serving/): scheduling + memory plane.

Measures what the continuous-batching scheduler actually buys over
classic batch-barrier inference ON THE SAME engine — the serving
analog of the Gemma-on-TPU paper's scheduling claim (PAPERS.md, arXiv
2605.25645; the pre-registered prediction table is in docs/perf.md
§"Serving: continuous vs static batching").

Two legs over the SAME toy decoder, the SAME Poisson-ish staggered
arrival trace, and the SAME per-request token budget, each appending
one JSON artifact under BENCH_ARTIFACT_DIR (default
bench_results/serve/):

* ``ab_static``     — ``ContinuousBatcher(policy="static")``: requests
  admitted only when the previous batch fully completed. A late
  arrival waits for the whole in-flight batch (head-of-line blocking);
  the batch's tail token rate decays as members finish.
* ``ab_continuous`` — the default policy: arrivals admitted into freed
  slots between decode steps, no flush, no barrier.
* ``ab_paged``      — slab vs paged memory plane at IDENTICAL traffic
  (serving/paged_kv.py): per-arm persistent-KV bytes from the donated
  cache carry's live buffers, plus a paged pool sized at a second,
  doubled max_len to show the footprint scales with PAGES, not
  max_len. Dryrun gates: identical outputs, paged-carry bytes <
  slab-carry bytes at undersubscribed pools, and byte-identical pool
  size across the two max_len values.
* ``ab_prefix``     — shared-system-prompt trace (the traffic reality
  the prefix cache exists for): every request carries the same
  system-prefix pages; the cold arm runs with the prefix cache off.
  Dryrun gates: warm arm skips ≥1 prefill chunk per follow-up request
  (``prefill_chunks_skipped``, ``prefix_hits`` > 0) with identical
  outputs; timing rows report TTFT p50/p95 warm vs cold.
* ``ab_disagg``     — unified worker vs a disaggregated prefill+decode
  pair (serving/kv_transfer.py) under long-prompt injection: short
  probe requests decode while long max_tokens=2 injector prompts keep
  arriving. On the unified worker every injector prefill interleaves
  between the probes' decode steps (the TTFT-vs-TPOT interference);
  on the disaggregated pair prefills run on the prefill worker and
  probes decode undisturbed. Probe interference is measured as
  EFFECTIVE TPOT — gen wall / (tokens-1) per request — because the
  recorder's per-step TPOT excludes the interleaved prefill time by
  construction. Each arm is measured against its OWN uninjected probe
  baseline (the arms carry different fixed per-token costs in a
  one-process simulation); the interference deltas are reported for
  the on-chip capture while the dryrun gates are structural: every
  injector prefill ran on the engine the unified probes decode on,
  the disagg decode worker ran ZERO prefills, and the int8 wire's KV
  payload is <= 1/3.5 of the fp32 payload for the same pages. Reports
  transfer bytes/pages/ms from the live metric deltas.
* ``ab_warm_cache`` — cold vs warm-disk init against one
  ``HOROVOD_EXE_CACHE`` dir (common/exe_cache.py): the cold arm pays
  and persists every prefill/decode compile, the warm arm warm-starts
  from disk. Gates (dryrun and on-chip): ZERO prefill/decode compiles
  on the warm arm for the seen keys, bit-identical tokens; dryrun
  additionally gates warm init+serve wall < cold (compiles dominate).

Each artifact records per-request TTFT and per-token TPOT p50/p95 plus
aggregate generated tokens/s. Both legs pay their compiles in an
untimed warmup (prefill buckets + the decode step), so the measured
delta is pure scheduling. BENCH_DRYRUN=1 is the CI smoke shape
(`./ci.sh bench-smoke` gates on the artifacts existing); CPU lines
carry the quarantine note — the decode step is milliseconds on CPU and
microseconds of MXU on a chip, so only an on-chip capture decides the
wall-clock claim, but the SCHEDULING effect (TTFT under load) is real
in either domain.

Env: BENCH_REQUESTS / BENCH_GEN_TOKENS / BENCH_SLOTS / BENCH_STAGGER_MS.
"""

import json
import os
import time

from _benchlib import stamp as _stamp


def _pct(vals, q):
    """Nearest-rank percentile over a sorted list (shared by every leg
    so the quantile method can never diverge between A/B arms)."""
    idx = min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)
    return vals[idx]


_SIM_NOTE = (
    "logic-validation only (CPU simulation); decode steps are ms on "
    "CPU vs us on MXU — NOT a TPU wall-clock number, but the "
    "scheduling deltas (TTFT under load) are structural"
)


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    from horovod_tpu.serving.batcher import ContinuousBatcher
    from horovod_tpu.serving.engine import InferenceEngine

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    n_requests = int(
        os.environ.get("BENCH_REQUESTS", "6" if dryrun else "32")
    )
    gen_tokens = int(
        os.environ.get("BENCH_GEN_TOKENS", "4" if dryrun else "32")
    )
    slots = int(os.environ.get("BENCH_SLOTS", "4" if dryrun else "8"))
    stagger_ms = float(
        os.environ.get("BENCH_STAGGER_MS", "5" if dryrun else "20")
    )
    platform = jax.devices()[0].platform

    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "serve")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    if dryrun:
        cfg = TransformerConfig(
            vocab_size=61, num_layers=1, d_model=16, num_heads=2,
            d_ff=32, max_len=128, causal=True, dtype=jnp.float32,
        )
    else:
        cfg = TransformerConfig(
            vocab_size=1024, num_layers=4, d_model=256, num_heads=8,
            d_ff=1024, max_len=512, causal=True, dtype=jnp.float32,
        )
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    rng = np.random.default_rng(0)
    # mixed-length arrival trace, shared by both legs
    lengths = rng.integers(4, 48 if dryrun else 128, size=n_requests)
    prompts = [
        list(rng.integers(1, cfg.vocab_size, size=int(n)))
        for n in lengths
    ]

    def run_leg(policy: str) -> dict:
        engine = InferenceEngine(
            model, params, slots=slots, max_len=cfg.max_len
        )
        batcher = ContinuousBatcher(
            engine,
            policy=policy,
            max_admit_per_step=max(slots // 2, 1),
            default_max_new_tokens=gen_tokens,
        )
        # untimed warmup: pay every prefill-bucket + decode compile the
        # trace will touch, so the timed region measures scheduling
        warm = batcher.submit(prompts[0][: max(len(prompts[0]) // 2, 1)])
        while not warm.finished():
            batcher.step()
        for _ in range(2):  # 2nd sighting spawns background promotions
            for p in prompts:
                engine._get_prefill_exe(len(p))
        engine.drain_promotions()  # join them so the timed region is
        # pure scheduling — no promotion thread stealing cycles
        batcher.start()
        t0 = time.monotonic()
        reqs = []
        for p in prompts:
            reqs.append(batcher.submit(p))
            time.sleep(stagger_ms / 1e3)
        for r in reqs:
            r.wait(timeout=600)
        wall_s = time.monotonic() - t0
        batcher.stop()
        assert all(r.status == "done" for r in reqs), [
            r.status for r in reqs
        ]
        ttfts = sorted(r.ttft_ms for r in reqs)
        slo = batcher.recorder.summaries()
        total_tokens = sum(len(r.out_tokens) for r in reqs)

        return {
            "metric": "serve_ab",
            "leg": f"ab_{policy}",
            "policy": policy,
            "platform": platform,
            "requests": n_requests,
            "slots": slots,
            "gen_tokens": gen_tokens,
            "stagger_ms": stagger_ms,
            "wall_s": round(wall_s, 4),
            "tokens_out": total_tokens,
            "tokens_per_s": round(total_tokens / wall_s, 3),
            "ttft_ms_p50": round(_pct(ttfts, 0.5), 3),
            "ttft_ms_p95": round(_pct(ttfts, 0.95), 3),
            "tpot_ms_p50": round(slo["tpot_ms"]["p50"], 4),
            "tpot_ms_p95": round(slo["tpot_ms"]["p95"], 4),
            "decode_steps": engine.stats()["decode_steps"],
            "decode_compiles": engine.stats()["decode_compiles"],
            "dryrun": dryrun,
            "note": _SIM_NOTE if platform == "cpu" else "on-chip",
        }

    for policy in ("static", "continuous"):
        line = run_leg(policy)
        path = os.path.join(artifact_dir, f"serve_ab_{policy}.json")
        with open(path, "w") as f:
            f.write(json.dumps(_stamp(line)) + "\n")
        print(json.dumps(_stamp(line)))

    # ---------------------------------------------------- memory-plane legs

    def kv_carry_bytes(engine):
        """Persistent KV residency: the donated cache carry's live
        buffers (the pool under paging, the slab otherwise) — the
        number that scales with HBM at steady state. Transient
        per-step activations are excluded by construction: only the
        carry survives between steps."""
        import jax as _jax

        return int(
            sum(
                leaf.nbytes
                for leaf in _jax.tree_util.tree_leaves(
                    engine.manager.cache
                )
            )
        )

    def drive(engine, trace, gen):
        """Run a trace through a manually-stepped batcher; returns
        per-request results + TTFTs (arrival stagger suppressed — the
        memory legs measure residency and hits, not scheduling)."""
        b = ContinuousBatcher(
            engine,
            max_admit_per_step=max(slots // 2, 1),
            default_max_new_tokens=gen,
        )
        reqs = [b.submit(p) for p in trace]
        guard = 0
        while not all(r.finished() for r in reqs):
            b.step()
            guard += 1
            assert guard < 100_000, "trace failed to complete"
        assert all(r.status == "done" for r in reqs), [
            r.status for r in reqs
        ]
        return b, reqs

    def run_paged_leg() -> dict:
        page_tokens = 16
        # undersubscribed pool: enough for the trace's tokens in
        # flight, well under slots × max_len worth of backing
        pool_pages = int(max(
            slots * ((int(max(lengths)) + gen_tokens) // page_tokens + 2),
            slots + 2,
        ))
        # the leg's claim is pool < slab, so stay strictly under full
        # backing even when env knobs (BENCH_GEN_TOKENS) inflate the
        # trace — admission simply gates concurrency to what fits
        full_backing = slots * (cfg.max_len // page_tokens)
        pool_pages = min(pool_pages, full_backing - slots)
        arms = {}
        outs = {}
        for arm in ("slab", "paged"):
            engine = InferenceEngine(
                model, params, slots=slots, max_len=cfg.max_len,
                paged=(arm == "paged"), page_tokens=page_tokens,
                pages=pool_pages, prefix_cache=False,
            )
            t0 = time.monotonic()
            _, reqs = drive(engine, prompts, gen_tokens)
            wall_s = time.monotonic() - t0
            outs[arm] = [r.out_tokens for r in reqs]
            arms[arm] = {
                "kv_carry_bytes": kv_carry_bytes(engine),
                "wall_s": round(wall_s, 4),
                "decode_compiles": engine.stats()["decode_compiles"],
                "page_allocs": (
                    engine.manager.stats().get("page_allocs", 0)
                ),
            }
        # the footprint claim: the pool's size is set by PAGES — the
        # same pool at double the max_len is byte-identical (only the
        # page-table width, a tiny int32 row, grows)
        eng2 = InferenceEngine(
            model, params, slots=slots, max_len=2 * cfg.max_len,
            paged=True, page_tokens=page_tokens, pages=pool_pages,
            prefix_cache=False,
        )
        arms["paged_2x_max_len"] = {
            "kv_carry_bytes": kv_carry_bytes(eng2)
        }
        assert outs["slab"] == outs["paged"], (
            "paged decode diverged from the slab at identical traffic"
        )
        assert (
            arms["paged"]["kv_carry_bytes"]
            == arms["paged_2x_max_len"]["kv_carry_bytes"]
        ), "pool bytes moved with max_len"
        assert (
            arms["paged"]["kv_carry_bytes"]
            < arms["slab"]["kv_carry_bytes"]
        ), "undersubscribed pool not smaller than the slab"
        return {
            "metric": "serve_ab_paged",
            "leg": "ab_paged",
            "platform": platform,
            "requests": n_requests,
            "slots": slots,
            "gen_tokens": gen_tokens,
            "page_tokens": page_tokens,
            "pool_pages": pool_pages,
            "max_len": cfg.max_len,
            "carry_bytes_ratio": round(
                arms["slab"]["kv_carry_bytes"]
                / arms["paged"]["kv_carry_bytes"],
                3,
            ),
            "arms": arms,
            "outputs_identical": True,
            "dryrun": dryrun,
            "note": _SIM_NOTE if platform == "cpu" else "on-chip",
        }

    def run_prefix_leg() -> dict:
        page_tokens = 16
        sys_prefix = list(
            rng.integers(1, cfg.vocab_size, size=2 * page_tokens)
        )  # two full shared pages per request
        tails = [
            list(rng.integers(1, cfg.vocab_size, size=int(t)))
            for t in rng.integers(3, 14, size=n_requests)
        ]
        trace = [sys_prefix + t for t in tails]
        arms = {}
        outs = {}
        for arm in ("cold", "warm"):
            engine = InferenceEngine(
                model, params, slots=slots, max_len=cfg.max_len,
                paged=True, page_tokens=page_tokens,
                prefix_cache=(arm == "warm"),
            )
            t0 = time.monotonic()
            b, reqs = drive(engine, trace, gen_tokens)
            wall_s = time.monotonic() - t0
            outs[arm] = [r.out_tokens for r in reqs]
            ttfts = sorted(r.ttft_ms for r in reqs)
            st = engine.stats()
            mstats = engine.manager.stats()
            arms[arm] = {
                "wall_s": round(wall_s, 4),
                "ttft_ms_p50": round(_pct(ttfts, 0.5), 3),
                "ttft_ms_p95": round(_pct(ttfts, 0.95), 3),
                "prefill_chunks_skipped": st["prefill_chunks_skipped"],
                "prefill_tokens_skipped": st["prefill_tokens_skipped"],
                "prefix_hits": mstats["prefix_hits"],
                "prefix_hit_rate": round(mstats["prefix_hit_rate"], 4),
            }
        assert outs["cold"] == outs["warm"], (
            "prefix-hit decode diverged from cold prefill"
        )
        warm = arms["warm"]
        assert warm["prefix_hits"] > 0, "no prefix hits on shared trace"
        # every request after the first shares 2 full pages
        assert warm["prefill_chunks_skipped"] >= 2 * (n_requests - 1), (
            warm
        )
        assert arms["cold"]["prefill_chunks_skipped"] == 0
        return {
            "metric": "serve_ab_prefix",
            "leg": "ab_prefix",
            "platform": platform,
            "requests": n_requests,
            "slots": slots,
            "gen_tokens": gen_tokens,
            "page_tokens": page_tokens,
            "shared_prefix_tokens": len(sys_prefix),
            "arms": arms,
            "outputs_identical": True,
            "dryrun": dryrun,
            "note": _SIM_NOTE if platform == "cpu" else "on-chip",
        }

    # ---------------------------------------------------- disaggregated leg

    def run_disagg_leg() -> dict:
        from horovod_tpu.common.metrics import registry as _metrics
        from horovod_tpu.serving.kv_transfer import (
            KVTransferServer,
            TransferCoordinator,
            pack_raw_pages,
        )

        page_tokens = 16
        pool_pages = 120
        n_probes = 3
        n_inject = 8 if dryrun else 16
        probe_gen = 24 if dryrun else 48
        inject_len = cfg.max_len - 8  # longest prefill bucket
        probe_prompts = [
            list(rng.integers(1, cfg.vocab_size, size=6))
            for _ in range(n_probes)
        ]
        inject_prompts = [
            list(rng.integers(1, cfg.vocab_size, size=inject_len))
            for _ in range(n_inject)
        ]

        def engine_for(role):
            return InferenceEngine(
                model, params, slots=slots, max_len=cfg.max_len,
                paged=True, page_tokens=page_tokens, pages=pool_pages,
                prefix_cache=False, role=role,
            )

        def probe_rows(reqs):
            assert all(r.status == "done" for r in reqs), [
                r.status for r in reqs
            ]
            tpots = sorted(
                r.gen_ms / max(len(r.result()["tokens"]) - 1, 1)
                for r in reqs
            )
            ttfts = sorted(r.ttft_ms for r in reqs)
            return {
                "ttft_ms_p95": round(_pct(ttfts, 0.95), 3),
                "tpot_eff_ms_p50": round(_pct(tpots, 0.5), 4),
                "tpot_eff_ms_p95": round(_pct(tpots, 0.95), 4),
            }

        def drive_trace(submit, inject=True):
            """Probes first (they keep decoding); injectors streamed in
            while the probes are mid-generation — or withheld entirely
            (``inject=False``), the per-arm interference baseline."""
            probes = [
                submit(p, max_tokens=probe_gen) for p in probe_prompts
            ]
            injectors = []
            if inject:
                for p in inject_prompts:
                    injectors.append(submit(p, max_tokens=2))
                    time.sleep(0.002)
            t0 = time.monotonic()
            for r in probes + injectors:
                r.wait(timeout=600)
            return probes, injectors, time.monotonic() - t0

        arms = {}

        # --- unified arm: one worker takes both traffic classes
        ueng = engine_for("unified")
        ubat = ContinuousBatcher(
            ueng, max_admit_per_step=2, default_max_new_tokens=probe_gen,
        )
        # untimed warmup: decode step + both prefill buckets
        warm = ubat.submit(probe_prompts[0], max_new_tokens=2)
        while not warm.finished():
            ubat.step()
        # sight each width to its promotion threshold and join the
        # background promotion threads: the timed region must contain
        # ZERO compiles — foreground or background — in either arm, so
        # the delta is pure scheduling
        for ln in (6, inject_len):
            ueng._get_prefill_exe(ln)
            ueng._get_prefill_exe(ln)
        ueng.drain_promotions()
        ubat.start()
        usubmit = (
            lambda p, max_tokens: ubat.submit(p, max_new_tokens=max_tokens)
        )
        # interference is a per-arm DELTA against an uninjected probe
        # baseline: each arm carries its own fixed per-token framework
        # cost (the disagg pair runs two engines + a real HTTP wire in
        # one process), so only the injected-minus-baseline movement
        # isolates what long-prompt prefills do to decode latency
        base_probes, _, _ = drive_trace(usubmit, inject=False)
        prefills_before = ueng.stats()["prefills"]
        probes, _, wall_s = drive_trace(usubmit)
        ubat.stop()
        ubase = probe_rows(base_probes)
        arms["unified"] = dict(
            probe_rows(probes), wall_s=round(wall_s, 4),
            tpot_baseline_ms_p95=ubase["tpot_eff_ms_p95"],
            tpot_interference_ms=round(
                probe_rows(probes)["tpot_eff_ms_p95"]
                - ubase["tpot_eff_ms_p95"], 4,
            ),
            # every injector prefill ran on the SAME engine the probes
            # were decoding on — the interference channel
            prefills_during_trace=(
                ueng.stats()["prefills"] - prefills_before
            ),
        )

        # --- disaggregated arm: prefill worker + decode worker, real
        # localhost transfer wire, int8 (the default) payload
        deng = engine_for("decode")
        dbat = ContinuousBatcher(
            deng, role="decode", max_admit_per_step=2,
            default_max_new_tokens=probe_gen,
        )
        server = KVTransferServer(dbat, port=0, addr="127.0.0.1")
        server.start()
        peng = engine_for("prefill")
        pbat = ContinuousBatcher(
            peng, role="prefill", max_admit_per_step=2,
            default_max_new_tokens=probe_gen,
        )

        class _Anns:
            def keys(self, scope):
                return ["0"]

            def get(self, scope, key):
                return json.dumps({
                    "port": 1, "addr": "127.0.0.1", "role": "decode",
                    "transfer_port": server.port,
                    "free_pages": deng.manager.admission_headroom(),
                    "ts": time.time(),
                }).encode()

        pbat.transfer = TransferCoordinator(
            peng, client=_Anns(), wire="int8"
        )
        # untimed warmup: one request through the FULL wire (compiles
        # the prefill bucket sender-side and the decode step receiver-
        # side), then the injector bucket
        dbat.start()
        pbat.start()
        warm = pbat.submit(probe_prompts[0], max_new_tokens=2)
        warm.wait(timeout=600)
        assert warm.status == "done", warm.status
        for ln in (6, inject_len):  # same zero-compile timed region
            peng._get_prefill_exe(ln)
            peng._get_prefill_exe(ln)
        peng.drain_promotions()
        psubmit = (
            lambda p, max_tokens: pbat.submit(p, max_new_tokens=max_tokens)
        )
        base_probes, _, _ = drive_trace(psubmit, inject=False)
        before = _metrics.snapshot()
        probes, _, wall_s = drive_trace(psubmit)
        after = _metrics.snapshot()
        pbat.stop()
        dbat.stop()

        def delta(key):
            return after.get(key, 0.0) - before.get(key, 0.0)

        dbase = probe_rows(base_probes)
        arms["disagg_int8"] = dict(
            probe_rows(probes),
            wall_s=round(wall_s, 4),
            tpot_baseline_ms_p95=dbase["tpot_eff_ms_p95"],
            tpot_interference_ms=round(
                probe_rows(probes)["tpot_eff_ms_p95"]
                - dbase["tpot_eff_ms_p95"], 4,
            ),
            decode_worker_prefills=deng.stats().get("prefills", 0),
            prefill_worker_prefills=peng.stats().get("prefills", 0),
            transfer_bytes=int(delta("serve.kv_transfer_bytes")),
            transfer_pages=int(delta("serve.kv_transfer_pages")),
            transfer_ms=round(delta("serve.kv_transfer_ms"), 3),
            transfers=int(delta("serve.transfers")),
            transfer_fallbacks=int(delta("serve.transfer_fallbacks")),
            decode_compiles_decode_worker=(
                deng.stats()["decode_compiles"]
            ),
            decode_compiles_prefill_worker=(
                peng.stats()["decode_compiles"]
            ),
        )

        # --- wire-payload ratio on REAL extracted pages: prefill the
        # longest injector on the (now idle) prefill engine, pack the
        # same pages both ways, compare KV payload bytes (the meta
        # header is bookkeeping, identical across wires, and noise at
        # real model sizes — the ratio claim is about KV bytes)
        slot = peng.manager.alloc("wire-probe")
        peng.prefill(slot, inject_prompts[0])
        kept, length = peng.manager.detach_keep(slot)
        raw = peng.extract_pages(kept, length)
        logical = [lp for lp, _ in kept]
        _, blob_fp32 = pack_raw_pages(
            raw, logical, length, page_tokens=page_tokens, wire="fp32"
        )
        _, blob_int8 = pack_raw_pages(
            raw, logical, length, page_tokens=page_tokens, wire="int8"
        )
        peng.manager.release_kept(kept)
        server.stop()
        byte_ratio = len(blob_fp32) / len(blob_int8)

        # The isolation gates are STRUCTURAL (which engine ran the
        # prefills), in the paged-attn leg's idiom: with the hot-path
        # promotion compile gone (the exe-cache PR's fix), the toy
        # model's prefill execution is sub-millisecond on CPU, so a
        # wall-clock TPOT ratio would gate on scheduler noise. The
        # per-arm interference deltas (injected − own uninjected
        # baseline) are reported for the on-chip capture, where a long
        # prefill occupies the MXU for real milliseconds.
        u_int = arms["unified"]["tpot_interference_ms"]
        d_int = arms["disagg_int8"]["tpot_interference_ms"]
        if dryrun:
            # every injector prefill interleaved into the engine the
            # probes were decoding on...
            assert (
                arms["unified"]["prefills_during_trace"]
                == n_probes + n_inject
            ), arms
            # ...while the disagg decode worker never ran ONE: probes
            # decode on a plane no long prompt can touch
            assert arms["disagg_int8"]["decode_worker_prefills"] == 0, arms
            assert (
                arms["disagg_int8"]["prefill_worker_prefills"]
                >= n_probes + n_inject
            ), arms
            assert byte_ratio >= 3.5, (
                f"int8 wire KV-byte drop only {byte_ratio:.2f}x vs fp32"
            )
            assert arms["disagg_int8"]["transfer_fallbacks"] == 0, arms
            assert (
                arms["disagg_int8"]["decode_compiles_decode_worker"] == 1
            ), arms
            assert (
                arms["disagg_int8"]["decode_compiles_prefill_worker"] == 0
            ), arms
        return {
            "metric": "serve_ab_disagg",
            "leg": "ab_disagg",
            "platform": platform,
            "probes": n_probes,
            "injectors": n_inject,
            "probe_gen_tokens": probe_gen,
            "inject_prompt_tokens": inject_len,
            "slots": slots,
            "page_tokens": page_tokens,
            "wire": "int8",
            "tpot_interference_unified_ms": round(u_int, 4),
            "tpot_interference_disagg_ms": round(d_int, 4),
            "kv_bytes_fp32": len(blob_fp32),
            "kv_bytes_int8": len(blob_int8),
            "kv_byte_ratio": round(byte_ratio, 3),
            "arms": arms,
            "dryrun": dryrun,
            "note": _SIM_NOTE if platform == "cpu" else "on-chip",
        }

    def run_paged_attn_leg() -> dict:
        """Tentpole A/B (paged flash-attention): the SAME paged engine
        twice — gather read (``paged_attn=off``, the transient
        contiguous view) vs fused kernel read (``paged_attn=on``,
        K/V streamed from the pool) — on a long-context, decode-heavy
        trace. Greedy outputs must be identical (the ≤1-ulp online
        softmax is absorbed by argmax), and the pre-registered decode
        HBM-byte model must hold: the kernel reads each slot's LIVE
        pages only, the gather re-reads slots × max_len every step.
        The byte model is analytic from the per-step live lengths
        (exact for both arms' reads — docs/perf.md); wall/TPOT are
        reported but gated on-chip only (CPU runs the kernel in
        interpret mode, which measures nothing about HBM)."""
        page_tokens = 16
        gen = max(gen_tokens, 8)  # decode-heavy
        long_lens = rng.integers(
            cfg.max_len // 2, cfg.max_len - gen, size=n_requests
        )
        trace = [
            list(rng.integers(1, cfg.vocab_size, size=int(n)))
            for n in long_lens
        ]
        kvh = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.d_model // cfg.num_heads
        per_tok = 2 * kvh * hd * 4 * cfg.num_layers  # k+v fp32, all layers
        arms = {}
        outs = {}
        for arm, pa in (("gather", "off"), ("kernel", "on")):
            engine = InferenceEngine(
                model, params, slots=slots, max_len=cfg.max_len,
                paged=True, page_tokens=page_tokens,
                prefix_cache=False, paged_attn=pa,
            )
            b = ContinuousBatcher(
                engine,
                max_admit_per_step=max(slots // 2, 1),
                default_max_new_tokens=gen,
            )
            reqs = [b.submit(p) for p in trace]
            kernel_bytes = 0
            gather_bytes = 0
            guard = 0
            t0 = time.monotonic()
            while not all(r.finished() for r in reqs):
                before = engine.stats()["decode_steps"]
                b.step()
                if engine.stats()["decode_steps"] > before:
                    # post-step lengths == kv_len each slot attended:
                    # the kernel DMAs exactly ceil(kv_len/pt) pages,
                    # the gather re-materializes the full table width
                    lens = engine.manager.lengths_array()
                    live_pages = sum(
                        -(-int(n) // page_tokens) for n in lens if n > 0
                    )
                    kernel_bytes += live_pages * page_tokens * per_tok
                    gather_bytes += slots * cfg.max_len * per_tok
                guard += 1
                assert guard < 100_000, "trace failed to complete"
            wall_s = time.monotonic() - t0
            assert all(r.status == "done" for r in reqs), [
                r.status for r in reqs
            ]
            outs[arm] = [r.out_tokens for r in reqs]
            st = engine.stats()
            arms[arm] = {
                "wall_s": round(wall_s, 4),
                "decode_steps": st["decode_steps"],
                "decode_compiles": st["decode_compiles"],
                "paged_attn_calls": st["paged_attn_calls"],
                "paged_attn_fallbacks": st["paged_attn_fallbacks"],
                "model_decode_read_bytes": (
                    kernel_bytes if arm == "kernel" else gather_bytes
                ),
            }
        # the acceptance gates (dryrun and on-chip alike): bit-identical
        # greedy tokens, the byte model, one executable, zero fallbacks
        assert outs["gather"] == outs["kernel"], (
            "kernel-path decode diverged from the gather oracle"
        )
        assert (
            arms["kernel"]["model_decode_read_bytes"]
            < arms["gather"]["model_decode_read_bytes"]
        ), "kernel byte model not under the gather's max_len reads"
        assert arms["kernel"]["paged_attn_calls"] > 0, arms
        assert arms["kernel"]["paged_attn_fallbacks"] == 0, arms
        assert arms["kernel"]["decode_compiles"] == 1, arms
        assert arms["gather"]["paged_attn_calls"] == 0, arms
        return {
            "metric": "serve_ab_paged_attn",
            "leg": "ab_paged_attn",
            "platform": platform,
            "requests": n_requests,
            "slots": slots,
            "gen_tokens": gen,
            "page_tokens": page_tokens,
            "max_len": cfg.max_len,
            "read_bytes_ratio": round(
                arms["kernel"]["model_decode_read_bytes"]
                / max(arms["gather"]["model_decode_read_bytes"], 1),
                4,
            ),
            "tpot_wall_ratio": round(
                arms["kernel"]["wall_s"] / max(arms["gather"]["wall_s"],
                                               1e-9),
                4,
            ),
            "arms": arms,
            "outputs_identical": True,
            "dryrun": dryrun,
            "note": (
                "byte model analytic; kernel runs in Pallas interpret "
                "mode on CPU — wall/TPOT not meaningful off-chip"
                if platform == "cpu" else "on-chip"
            ),
        }

    def run_warm_cache_leg() -> dict:
        """Tentpole A/B (persistent executable cache): the SAME engine
        + trace twice against one ``HOROVOD_EXE_CACHE`` dir — a cold
        arm that pays every prefill/decode compile and persists it,
        then a warm arm whose init warm-starts from disk and whose
        serve performs ZERO compiles for the seen keys (the gate, both
        dryrun and on-chip), with bit-identical greedy tokens. The
        init+serve wall ratio is the headline warm-restart number;
        warm < cold is asserted in DRYRUN where compiles dominate."""
        import tempfile

        from horovod_tpu.common import exe_cache

        cache_dir = tempfile.mkdtemp(prefix="bench-exe-cache-")
        trace = prompts[: min(4, len(prompts))]
        arms = {}
        outs = {}
        prev = os.environ.get("HOROVOD_EXE_CACHE")
        os.environ["HOROVOD_EXE_CACHE"] = cache_dir
        try:
            for arm in ("cold", "warm"):
                t0 = time.monotonic()
                engine = InferenceEngine(
                    model, params, slots=slots, max_len=cfg.max_len,
                    promote_after=2,
                )
                init_s = time.monotonic() - t0
                b = ContinuousBatcher(
                    engine,
                    max_admit_per_step=max(slots // 2, 1),
                    default_max_new_tokens=gen_tokens,
                )
                t0 = time.monotonic()
                reqs = [b.submit(p) for p in trace]
                guard = 0
                while not all(r.finished() for r in reqs):
                    b.step()
                    guard += 1
                    assert guard < 100_000, "trace failed to complete"
                # second sighting of each width -> background
                # promotions; join + flush so the warm arm inherits
                # the exact-tier entries too
                for p in trace:
                    engine._get_prefill_exe(len(p))
                engine.drain_promotions()
                serve_s = time.monotonic() - t0
                assert exe_cache.flush(60), "cache writes did not drain"
                st = engine.stats()
                outs[arm] = [r.out_tokens for r in reqs]
                arms[arm] = {
                    "init_s": round(init_s, 4),
                    "serve_s": round(serve_s, 4),
                    "total_s": round(init_s + serve_s, 4),
                    "prefill_compiles": st["prefill_compiles"],
                    "decode_compiles": st["decode_compiles"],
                    "prefill_disk_hits": st.get("prefill_disk_hits", 0),
                    "decode_disk_hits": st.get("decode_disk_hits", 0),
                }
        finally:
            if prev is None:
                os.environ.pop("HOROVOD_EXE_CACHE", None)
            else:
                os.environ["HOROVOD_EXE_CACHE"] = prev
        # acceptance gates: zero compiles for seen keys on the warm
        # arm, tokens bit-identical, warm restart faster than cold
        assert outs["warm"] == outs["cold"], (
            "warm-cache serve diverged from the cold-compiled arm"
        )
        assert arms["warm"]["prefill_compiles"] == 0, arms
        assert arms["warm"]["decode_compiles"] == 0, arms
        assert arms["warm"]["decode_disk_hits"] >= 1, arms
        ratio = arms["warm"]["total_s"] / max(arms["cold"]["total_s"],
                                              1e-9)
        if dryrun:
            assert ratio < 1.0, (
                f"warm init+serve not under cold: {arms}"
            )
        return {
            "metric": "serve_ab_warm_cache",
            "leg": "ab_warm_cache",
            "platform": platform,
            "requests": len(trace),
            "slots": slots,
            "gen_tokens": gen_tokens,
            "warm_total_ratio": round(ratio, 4),
            "arms": arms,
            "outputs_identical": True,
            "dryrun": dryrun,
            "note": _SIM_NOTE if platform == "cpu" else "on-chip",
        }

    def run_failover_leg() -> dict:
        """Crash-safety A/B (PR 19): the SAME burst three ways — run to
        completion (baseline), kill the worker mid-burst and REPLAY the
        journaled payloads on a warmed survivor (router durability:
        every pre-kill token is re-decoded), kill under the drain
        deadline and MIGRATE the in-flight sequences over the int8
        kv-transfer wire (export_inflight → migrate: pages + full
        generated history + armed sampling resume mid-decode, nothing
        is re-decoded). Reported: recovered-token ratio (pre-kill
        tokens NOT re-decoded after failover / pre-kill tokens) and
        time-to-first-recovered-token p50/p95 against the baseline's
        cold TTFT — the docs/perf.md prediction row. Dryrun gates are
        structural: replay output bit-identical to the baseline,
        migrated output full-length with the carried history verbatim,
        migration ratio >= 0.9 vs replay == 0, zero receiver prefills
        and ONE receiver decode executable across every resume."""
        from horovod_tpu.common.metrics import registry as _metrics
        from horovod_tpu.serving.kv_transfer import (
            KVTransferServer,
            TransferCoordinator,
        )

        page_tokens = 16
        pool_pages = 120
        n_fail = 4 if dryrun else 8
        gen_f = max(gen_tokens, 12)
        kill_at = max(gen_f // 2, 2)
        # distinct leading token per prompt: the migration TTFR poller
        # matches receiver slots back to sequences by prompt identity
        fprompts = [
            [i + 1] + list(rng.integers(1, cfg.vocab_size, size=7))
            for i in range(n_fail)
        ]

        def engine_for(role="unified"):
            return InferenceEngine(
                model, params, slots=slots, max_len=cfg.max_len,
                paged=True, page_tokens=page_tokens, pages=pool_pages,
                prefix_cache=False, role=role,
            )

        def batcher_for(engine, role="unified"):
            return ContinuousBatcher(
                engine, role=role, max_admit_per_step=slots,
                default_max_new_tokens=gen_f,
            )

        def step_until(b, reqs, n_tokens):
            guard = 0
            while not all(
                len(r.out_tokens) >= n_tokens or r.finished()
                for r in reqs
            ):
                b.step()
                guard += 1
                assert guard < 100_000, "failover trace stalled"

        def ttfr_poll(snapshot, n, t_kill):
            """First-progress wall time per recovered sequence, ms
            after the kill instant. ``snapshot()`` yields
            ``(key, current_len, baseline_len)`` rows; a sequence
            counts as recovered the first time it moves past its
            baseline (0 for replay — everything re-decodes; the
            carried history length for migration)."""
            ttfr = {}
            deadline = time.monotonic() + 600
            while len(ttfr) < n and time.monotonic() < deadline:
                for key, cur, base in snapshot():
                    if cur > base and key not in ttfr:
                        ttfr[key] = (time.monotonic() - t_kill) * 1e3
                time.sleep(0.0005)
            assert len(ttfr) == n, f"only {len(ttfr)}/{n} recovered"
            return sorted(ttfr.values())

        def warm_engine(engine):
            """Pay the prefill bucket + decode compiles untimed, the
            other legs' idiom: TTFR must measure recovery, not XLA."""
            b = batcher_for(engine)
            w = b.submit(fprompts[0], max_new_tokens=2)
            while not w.finished():
                b.step()
            for _ in range(2):
                engine._get_prefill_exe(len(fprompts[0]))
            engine.drain_promotions()

        arms = {}

        # --- baseline arm: the burst runs to completion, undisturbed
        aeng = engine_for()
        warm_engine(aeng)
        abat = batcher_for(aeng)
        t0 = time.monotonic()
        ref_reqs = [
            abat.submit(p, max_new_tokens=gen_f) for p in fprompts
        ]
        step_until(abat, ref_reqs, gen_f)
        wall_s = time.monotonic() - t0
        assert all(r.status == "done" for r in ref_reqs)
        ref_outs = [list(r.out_tokens) for r in ref_reqs]
        cold_ttfts = sorted(r.ttft_ms for r in ref_reqs)
        arms["uninterrupted"] = {
            "wall_s": round(wall_s, 4),
            "ttft_ms_p50": round(_pct(cold_ttfts, 0.5), 3),
            "ttft_ms_p95": round(_pct(cold_ttfts, 0.95), 3),
            "tokens_out": sum(len(o) for o in ref_outs),
        }

        # --- replay arm: the worker dies dark mid-burst; the router's
        # journaled payloads land on a warmed survivor and start over
        dying = engine_for()
        dbat0 = batcher_for(dying)
        surv = engine_for()
        warm_engine(surv)
        sbat2 = batcher_for(surv)
        reqs_b = [
            dbat0.submit(p, max_new_tokens=gen_f) for p in fprompts
        ]
        step_until(dbat0, reqs_b, kill_at)
        prekill_b = sum(len(r.out_tokens) for r in reqs_b)
        surv_prefills0 = surv.stats()["prefills"]
        sbat2.start()
        t_kill = time.monotonic()  # SIGKILL: pre-kill work is gone
        rep = [
            sbat2.submit(p, max_new_tokens=gen_f) for p in fprompts
        ]
        ttfr_b = ttfr_poll(
            lambda: [
                (i, len(r.out_tokens), 0) for i, r in enumerate(rep)
            ],
            n_fail, t_kill,
        )
        for r in rep:
            r.wait(timeout=600)
        sbat2.stop()
        assert all(r.status == "done" for r in rep)
        rep_outs = [list(r.out_tokens) for r in rep]
        total_b = sum(len(o) for o in rep_outs)
        redecoded_b = max(total_b - (total_b - prekill_b), 0)
        arms["kill_replay"] = {
            "ttfr_ms_p50": round(_pct(ttfr_b, 0.5), 3),
            "ttfr_ms_p95": round(_pct(ttfr_b, 0.95), 3),
            "prekill_tokens": prekill_b,
            "recovery_decoded_tokens": total_b,
            "recovered_token_ratio": round(
                1.0 - redecoded_b / max(prekill_b, 1), 4
            ),
            "survivor_prefills": (
                surv.stats()["prefills"] - surv_prefills0
            ),
            "outputs_identical": rep_outs == ref_outs,
        }

        # --- migration arm: drain deadline expires; export_inflight
        # detaches the live slots and the int8 wire carries pages +
        # history + sampling state to a decode-role receiver
        src = engine_for()
        deng = engine_for("decode")
        dbat_r = batcher_for(deng, role="decode")
        server = KVTransferServer(dbat_r, port=0, addr="127.0.0.1")
        server.start()

        class _Anns:
            def keys(self, scope):
                return ["0"]

            def get(self, scope, key):
                return json.dumps({
                    "port": 1, "addr": "127.0.0.1", "role": "decode",
                    "transfer_port": server.port,
                    "free_pages": deng.manager.admission_headroom(),
                    "ts": time.time(),
                }).encode()

        coord = TransferCoordinator(src, client=_Anns(), wire="int8")
        dbat_r.start()
        # untimed warmup through the FULL wire: source prefill + decode
        # compiles, one migrate frame, the receiver's single decode exe
        sbat0 = batcher_for(src)
        w = sbat0.submit(fprompts[0], max_new_tokens=gen_f)
        for _ in range(kill_at + 1):
            sbat0.step()
        recs = sbat0.export_inflight()
        assert len(recs) == 1 and coord.migrate(sbat0, recs[0])
        assert w.wait(timeout=600) and w.status == "done"
        for _ in range(2):
            src._get_prefill_exe(len(fprompts[0]))
        src.drain_promotions()

        sbat_c = batcher_for(src)
        reqs_c = [
            sbat_c.submit(p, max_new_tokens=gen_f) for p in fprompts
        ]
        step_until(sbat_c, reqs_c, kill_at)
        carried = {
            tuple(p): len(r.out_tokens)
            for p, r in zip(fprompts, reqs_c)
        }
        prekill_c = sum(carried.values())
        before = _metrics.snapshot()
        t_kill = time.monotonic()  # the drain deadline expires HERE
        for rec in sbat_c.export_inflight():
            assert coord.migrate(sbat_c, rec), "no migration capacity"

        def snap_receiver():
            try:
                items = list(dbat_r._slot_req.values())
            except RuntimeError:  # slot table resized mid-snapshot
                return []
            rows = []
            for r in items:
                key = tuple(int(t) for t in r.prompt)
                if key in carried:
                    rows.append((key, len(r.out_tokens), carried[key]))
            return rows

        ttfr_c = ttfr_poll(snap_receiver, n_fail, t_kill)
        for r in reqs_c:
            r.wait(timeout=600)
        dbat_r.stop()
        server.stop()
        after = _metrics.snapshot()
        assert all(r.status == "done" for r in reqs_c)
        mig_outs = [list(r.out_tokens) for r in reqs_c]
        total_c = sum(len(o) for o in mig_outs)
        # tokens decoded on the receiver = final minus carried; any
        # excess over the post-kill remainder was re-decoded history
        recovery_decoded_c = total_c - prekill_c
        redecoded_c = max(
            recovery_decoded_c - (total_c - prekill_c), 0
        )
        carried_verbatim = all(
            out[: carried[tuple(p)]]
            == ref[: carried[tuple(p)]]
            for p, out, ref in zip(fprompts, mig_outs, ref_outs)
        )
        arms["kill_migration"] = {
            "ttfr_ms_p50": round(_pct(ttfr_c, 0.5), 3),
            "ttfr_ms_p95": round(_pct(ttfr_c, 0.95), 3),
            "prekill_tokens": prekill_c,
            "recovery_decoded_tokens": recovery_decoded_c,
            "recovered_token_ratio": round(
                1.0 - redecoded_c / max(prekill_c, 1), 4
            ),
            "receiver_prefills": deng.stats()["prefills"],
            "receiver_decode_compiles": deng.stats()["decode_compiles"],
            "migrations": int(
                after.get("serve.migrations", 0.0)
                - before.get("serve.migrations", 0.0)
            ),
            "migration_ms": round(
                after.get("serve.migration_ms", 0.0)
                - before.get("serve.migration_ms", 0.0), 3,
            ),
            "carried_prefix_verbatim": carried_verbatim,
        }

        mig = arms["kill_migration"]
        if dryrun:
            # replay is correct but total loss: bit-identical output,
            # every pre-kill token decoded twice
            assert arms["kill_replay"]["outputs_identical"], (
                "replayed burst diverged from the uninterrupted run"
            )
            assert arms["kill_replay"]["recovered_token_ratio"] == 0.0
            # migration is the durability claim: full-length answers,
            # carried history verbatim (int8 wire: post-resume greedy
            # argmax is approximate, the HISTORY is exact), >= 90% of
            # pre-kill tokens never re-decoded
            assert all(len(o) == gen_f for o in mig_outs), [
                len(o) for o in mig_outs
            ]
            assert mig["carried_prefix_verbatim"], (
                "migrated history was re-decoded or corrupted"
            )
            assert mig["recovered_token_ratio"] >= 0.9, mig
            assert mig["migrations"] == n_fail, mig
            assert mig["receiver_prefills"] == 0, mig
            assert mig["receiver_decode_compiles"] == 1, mig
            assert (
                arms["kill_replay"]["survivor_prefills"] == n_fail
            ), arms["kill_replay"]
        return {
            "metric": "serve_ab_failover",
            "leg": "ab_failover",
            "platform": platform,
            "requests": n_fail,
            "slots": slots,
            "gen_tokens": gen_f,
            "kill_after_tokens": kill_at,
            "page_tokens": page_tokens,
            "wire": "int8",
            "cold_ttft_ms_p95": arms["uninterrupted"]["ttft_ms_p95"],
            "replay_ttfr_vs_cold_ttft_p95": round(
                arms["kill_replay"]["ttfr_ms_p95"]
                / max(arms["uninterrupted"]["ttft_ms_p95"], 1e-9), 4,
            ),
            "arms": arms,
            "dryrun": dryrun,
            "note": _SIM_NOTE if platform == "cpu" else "on-chip",
        }

    for leg_fn, name in ((run_paged_leg, "paged"), (run_prefix_leg, "prefix"),
                         (run_disagg_leg, "disagg"),
                         (run_paged_attn_leg, "paged_attn"),
                         (run_warm_cache_leg, "warm_cache"),
                         (run_failover_leg, "failover")):
        line = leg_fn()
        path = os.path.join(artifact_dir, f"serve_ab_{name}.json")
        with open(path, "w") as f:
            f.write(json.dumps(_stamp(line)) + "\n")
        print(json.dumps(_stamp(line)))
    print(f"bench_serve artifacts in {artifact_dir}")


if __name__ == "__main__":
    main()
