"""Persistent executable cache (common/exe_cache.py) + warm-standby
elastic: entry-key anatomy, store/load round-trip with bitwise output
parity, corruption and chaos degradation (counted cold compile, never
a failed init), cross-version/topology/donation rejection BY KEY (a
mismatched entry is never deserialized), fusion disk tier, serving
engine warm start (zero compiles for seen keys, including a fresh
disk-only subprocess), schedule sidecars, standby reservation /
swap-in / serve scale-up planning, and the restart-stamp clock."""

import glob
import json
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.common import exe_cache
from horovod_tpu.common.metrics import registry
from horovod_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


@pytest.fixture()
def cache_base(tmp_path, monkeypatch):
    base = str(tmp_path / "exe-cache")
    monkeypatch.setenv("HOROVOD_EXE_CACHE", base)
    return base


def _delta(name, before):
    return registry.snapshot().get(name, 0.0) - before.get(name, 0.0)


def _lowered(scale=2.0):
    return jax.jit(lambda x: x * scale + 1.0).lower(
        jnp.ones((8,), jnp.float32)
    )


def _rewrite_header(path, **patch):
    """Tamper one pinned header field in-place (payload untouched)."""
    with open(path, "rb") as f:
        blob = f.read()
    off = len(exe_cache.MAGIC)
    (hlen,) = struct.unpack(">I", blob[off:off + 4])
    header = json.loads(blob[off + 4:off + 4 + hlen].decode())
    header.update(patch)
    hdr = json.dumps(header, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(
            exe_cache.MAGIC + struct.pack(">I", len(hdr)) + hdr
            + blob[off + 4 + hlen:]
        )


# ------------------------------------------------------------------ keys


class TestKeys:
    def test_donation_signature(self):
        assert exe_cache.donation_signature(None) == "none"
        assert exe_cache.donation_signature(()) == "none"
        assert exe_cache.donation_signature((0, 1)) == "d0.1"
        assert exe_cache.donation_signature((3,)) == "d3"

    def test_entry_path_off_without_env(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_EXE_CACHE", raising=False)
        assert exe_cache.cache_dir() is None
        assert exe_cache.entry_path("f", "abc") is None

    def test_entry_path_key_fields(self, cache_base):
        p = exe_cache.entry_path(
            "serve/prefill", "h1", wire="int8", donation="d1",
            fingerprint="w8-l8-cpu",
        )
        name = os.path.basename(p)
        assert name.startswith("serve_prefill-w8-l8-cpu-")
        assert name.endswith(".hvdexe")
        # every key dimension lands in a DIFFERENT file: world size,
        # wire, and donation signature can never collide by path
        others = [
            exe_cache.entry_path("serve/prefill", "h1", wire="int8",
                                 donation="d1", fingerprint="w6-l6-cpu"),
            exe_cache.entry_path("serve/prefill", "h1", wire="fp32",
                                 donation="d1", fingerprint="w8-l8-cpu"),
            exe_cache.entry_path("serve/prefill", "h1", wire="int8",
                                 donation="none", fingerprint="w8-l8-cpu"),
            exe_cache.entry_path("serve/prefill", "h2", wire="int8",
                                 donation="d1", fingerprint="w8-l8-cpu"),
        ]
        assert len({p, *others}) == 5


# ------------------------------------------------------- store / load


class TestRoundTrip:
    def test_store_load_bitwise(self, cache_base):
        before = registry.snapshot()
        low = _lowered()
        fp = exe_cache.hlo_fingerprint(low)
        exe, hit = exe_cache.get_or_compile(low, "test.rt")
        assert hit is False
        assert exe_cache.flush(10)
        assert _delta("exe_cache.stores", before) == 1
        got = exe_cache.load("test.rt", fp)
        assert got is not None
        x = jnp.arange(8, dtype=jnp.float32)
        a = np.asarray(exe(x))
        b = np.asarray(got(x))
        assert a.tobytes() == b.tobytes()
        assert _delta("exe_cache.hits", before) == 1
        assert _delta("exe_cache.bytes", before) > 0
        assert _delta("exe_cache.deserialize_ms", before) >= 0

    def test_second_get_or_compile_is_a_hit(self, cache_base):
        exe_cache.get_or_compile(_lowered(), "test.hit")
        exe_cache.flush(10)
        exe, hit = exe_cache.get_or_compile(_lowered(), "test.hit")
        assert hit is True

    def test_absent_entry_counts_miss(self, cache_base):
        before = registry.snapshot()
        assert exe_cache.load("test.absent", "deadbeef") is None
        assert _delta("exe_cache.misses", before) == 1

    def test_no_tmp_leftovers(self, cache_base):
        exe_cache.get_or_compile(_lowered(), "test.tmp")
        exe_cache.flush(10)
        assert not glob.glob(os.path.join(cache_base, ".tmp-*"))


# ------------------------------------------- corruption / invalidation


class TestDegradation:
    def _seed_entry(self, family="test.corrupt"):
        low = _lowered()
        fp = exe_cache.hlo_fingerprint(low)
        path = exe_cache.store(
            low.compile(), family, fp, sync=True
        )
        assert path and os.path.exists(path)
        return fp, path

    def test_flipped_payload_byte_is_counted_corrupt(self, cache_base):
        fp, path = self._seed_entry()
        with open(path, "rb") as f:
            blob = f.read()
        blob = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        with open(path, "wb") as f:
            f.write(blob)
        before = registry.snapshot()
        assert exe_cache.load("test.corrupt", fp) is None
        assert _delta("exe_cache.corrupt", before) == 1

    def test_truncated_and_bad_magic_are_corrupt(self, cache_base):
        fp, path = self._seed_entry()
        before = registry.snapshot()
        with open(path, "wb") as f:
            f.write(b"HV")  # torn write
        assert exe_cache.load("test.corrupt", fp) is None
        with open(path, "wb") as f:
            f.write(b"NOTMAGIC" + b"\0" * 64)
        assert exe_cache.load("test.corrupt", fp) is None
        assert _delta("exe_cache.corrupt", before) == 2

    def test_chaos_bitflip_degrades_to_cold_compile(self, cache_base):
        """The ``exe_cache.load`` chaos site: a bitflipped entry falls
        back to a counted cold compile — never an aborted init."""
        low = _lowered()
        exe_cache.get_or_compile(low, "test.chaos")
        exe_cache.flush(10)
        chaos.configure("exe_cache.load@1:bitflip")
        before = registry.snapshot()
        exe, hit = exe_cache.get_or_compile(_lowered(), "test.chaos")
        assert hit is False  # corrupt read -> compiled cold
        assert exe is not None
        assert _delta("exe_cache.corrupt", before) == 1
        exe_cache.flush(10)
        # fault is one-shot: the re-persisted entry now hits clean
        exe, hit = exe_cache.get_or_compile(_lowered(), "test.chaos")
        assert hit is True

    def test_chaos_delay_still_loads(self, cache_base):
        low = _lowered()
        fp = exe_cache.hlo_fingerprint(low)
        exe_cache.store(low.compile(), "test.delay", fp, sync=True)
        chaos.configure("exe_cache.load@1:delay:ms=10")
        assert exe_cache.load("test.delay", fp) is not None

    def test_mismatched_entries_rejected_never_deserialized(
        self, cache_base, monkeypatch
    ):
        """Cross-version/topology safety: entries whose header pins a
        different JAX/jaxlib version, platform, or format are rejected
        by the invalidation rules BEFORE deserialization; a different
        world size or donation signature never even resolves to the
        same file."""
        from jax.experimental import serialize_executable as se

        fp, path = self._seed_entry("test.rej")

        def _boom(*a, **kw):  # proves the payload is never loaded
            raise AssertionError("deserialized a mismatched entry")

        monkeypatch.setattr(se, "deserialize_and_load", _boom)
        before = registry.snapshot()
        for patch in (
            {"jax": "0.0.1"},
            {"jaxlib": "0.0.1"},
            {"platform": "tpu"},
            {"format": exe_cache.FORMAT_VERSION + 1},
        ):
            self._seed_entry("test.rej")  # restore a clean entry
            _rewrite_header(path, **patch)
            assert exe_cache.load("test.rej", fp) is None
        assert _delta("exe_cache.rejected", before) == 4
        # different topology fingerprint / donation: a DIFFERENT key,
        # so the reader misses on the absent file — by construction the
        # 8-world entry cannot load into a 6-world reader
        before = registry.snapshot()
        assert exe_cache.load("test.rej", fp,
                              fingerprint="w6-l6-cpu") is None
        assert exe_cache.load("test.rej", fp, donation="d1") is None
        assert _delta("exe_cache.misses", before) == 2
        assert _delta("exe_cache.rejected", before) == 0


# ------------------------------------------------------ scan / preload


class TestScanPreload:
    def test_scan_filters_family_and_topology(self, cache_base):
        low = _lowered()
        fp = exe_cache.hlo_fingerprint(low)
        exe = low.compile()
        exe_cache.store(exe, "fam.a", fp, meta={"width": 8}, sync=True)
        exe_cache.store(exe, "fam.b", fp, sync=True)
        headers = exe_cache.scan("fam.a")
        assert len(headers) == 1
        h = headers[0]
        assert h["family"] == "fam.a"
        assert h["meta"] == {"width": 8}
        assert os.path.exists(h["path"])
        assert exe_cache.scan("fam.a", fingerprint="w999-l1-cpu") == []

    def test_preload_deserializes_everything(self, cache_base):
        low = _lowered()
        fp = exe_cache.hlo_fingerprint(low)
        exe_cache.store(low.compile(), "fam.pre", fp, sync=True)
        loaded, nbytes = exe_cache.preload("fam.pre")
        assert loaded == 1 and nbytes > 0
        # corrupt entries are skipped, not raised (standby staging must
        # survive a torn cache)
        path = exe_cache.scan("fam.pre")[0]["path"]
        with open(path, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            f.write(b"\0")
        loaded, _ = exe_cache.preload("fam.pre")
        assert loaded == 0


# ----------------------------------------------------------- sidecars


class TestSidecars:
    def test_merge_on_persist(self, cache_base):
        exe_cache.persist_json("sc", {"a": 1})
        exe_cache.persist_json("sc", {"b": 2})
        assert exe_cache.load_json("sc") == {"a": 1, "b": 2}

    def test_corrupt_sidecar_reads_empty(self, cache_base):
        path = exe_cache.persist_json("sc2", {"a": 1})
        with open(path, "w") as f:
            f.write("{not json")
        before = registry.snapshot()
        assert exe_cache.load_json("sc2") == {}
        assert _delta("exe_cache.corrupt", before) == 1

    def test_overlap_schedule_persists_and_reloads(self, cache_base):
        from horovod_tpu.ops import overlap

        overlap.reset_schedule_cache()
        tree = {"a": jnp.ones((64, 8)), "b": jnp.ones((16,))}
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        s1 = overlap.schedule_for(leaves, treedef, n_buckets=2,
                                  min_bucket_bytes=1)
        assert exe_cache.load_json("overlap_schedule")  # persisted
        # a fresh in-memory cache (restarted worker) reconstructs the
        # SAME partition from the sidecar instead of re-deriving it
        overlap.reset_schedule_cache()
        s2 = overlap.schedule_for(leaves, treedef, n_buckets=2,
                                  min_bucket_bytes=1)
        assert s2 == s1
        assert overlap.schedule_cache_stats()["disk_hits"] == 1
        overlap.reset_schedule_cache()


# ------------------------------------------------------- fusion tier


class TestFusionDiskTier:
    def _drill(self):
        """test_fusion_injit's promotion pattern: exact compile for the
        first composition, core compile + two sightings for the second
        (the second sighting promotes)."""
        import horovod_tpu as hvd

        def batch(sizes, tag):
            hs = [
                hvd.allreduce_async(
                    np.stack([
                        (r + 1.0) * np.arange(1, n + 1, dtype=np.float32)
                        for r in range(hvd.size())
                    ]),
                    name=f"{tag}{i}",
                )
                for i, n in enumerate(sizes)
            ]
            return [np.asarray(h.wait()) for h in hs]

        batch([6, 2], "x")
        batch([3, 5], "y")
        batch([3, 5], "y")
        return batch([3, 5], "y")

    def test_disk_tier_round_trip_bitwise(self, cache_base):
        import horovod_tpu as hvd

        hvd.init()
        try:
            f = hvd.common.basics.state().fusion
            f.cycle_time_ms = 1e6  # eager-flush only via wait()
            out1 = self._drill()
            s = f.cache_stats()
            assert s["promotions"] == 1
            assert s["disk_misses"] == 3  # exact + core + promoted
            assert s["disk_hits"] == 0
        finally:
            hvd.shutdown()
        assert exe_cache.flush(10)
        hvd.init()
        try:
            f = hvd.common.basics.state().fusion
            f.cycle_time_ms = 1e6
            out2 = self._drill()
            s = f.cache_stats()
            # zero fused-dispatch compiles for seen keys: every build —
            # including the bucket->exact promotion — resolves from disk
            assert s["disk_hits"] == 3
            assert s["disk_misses"] == 0
            assert s["promotions"] == 1
            for a, b in zip(out1, out2):
                assert a.tobytes() == b.tobytes()
        finally:
            hvd.shutdown()


# --------------------------------------------------- serving warm start


def _toy_engine(tmp_base, **kw):
    from horovod_tpu.models.transformer import Transformer, TransformerConfig
    from horovod_tpu.serving.engine import InferenceEngine

    cfg = TransformerConfig(
        vocab_size=61, num_layers=1, d_model=16, num_heads=2, d_ff=32,
        max_len=64, causal=True, dtype=jnp.float32,
    )
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("min_bucket", 4)
    return InferenceEngine(model, params, **kw)


def _serve_round(eng, prompt, n):
    slot = eng.manager.alloc("r")
    out = [eng.prefill(slot, prompt)]
    for _ in range(n - 1):
        toks = np.zeros(eng.slots, np.int32)
        toks[slot] = out[-1]
        nxt = eng.decode_step(toks)
        eng.manager.advance(slot)
        out.append(int(nxt[slot]))
    return out


class TestServeWarmStart:
    def test_fresh_engine_serves_with_zero_compiles(self, cache_base):
        eng = _toy_engine(cache_base, promote_after=2)
        prompt = [5, 7, 11, 2, 9]
        cold = _serve_round(eng, prompt, 4)
        _serve_round(eng, prompt, 1)  # second sighting -> promotion
        assert eng.drain_promotions()
        exe_cache.flush(10)
        warm = _toy_engine(cache_base, promote_after=2)
        s = warm.stats()
        assert s.get("prefill_disk_hits", 0) >= 1
        assert s.get("decode_disk_hits", 0) == 1
        out = _serve_round(warm, prompt, 4)
        s = warm.stats()
        assert s["prefill_compiles"] == 0
        assert s["decode_compiles"] == 0
        assert out == cold

    def test_decode_role_loads_only_decode_entries(self, cache_base):
        eng = _toy_engine(cache_base, promote_after=2)
        _serve_round(eng, [1, 2, 3, 4, 5], 3)
        exe_cache.flush(10)
        dec = _toy_engine(cache_base, role="decode")
        s = dec.stats()
        assert s.get("decode_disk_hits", 0) == 1
        assert s.get("prefill_disk_hits", 0) == 0

    @pytest.mark.slow
    def test_disk_only_subprocess_is_bitwise_identical(
        self, cache_base, tmp_path
    ):
        """The acceptance drill: a SECOND PROCESS against the populated
        cache performs zero prefill/decode compiles for seen keys and
        produces bitwise-identical tokens."""
        eng = _toy_engine(cache_base, promote_after=2)
        prompt = [5, 7, 11]
        cold = _serve_round(eng, prompt, 5)
        _serve_round(eng, prompt, 1)
        assert eng.drain_promotions()
        exe_cache.flush(10)
        script = tmp_path / "warm_proc.py"
        script.write_text(
            "import os, json\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import sys; sys.path.insert(0, %r)\n"
            "sys.path.insert(0, %r)\n"
            "from test_exe_cache import _toy_engine, _serve_round\n"
            "eng = _toy_engine(os.environ['HOROVOD_EXE_CACHE'],"
            " promote_after=2)\n"
            "out = _serve_round(eng, %r, 5)\n"
            "print('RESULT', json.dumps({'out': out,"
            " 'stats': eng.stats()}))\n"
            % (os.path.dirname(__file__), "/root/repo", list(prompt))
        )
        env = dict(os.environ, HOROVOD_EXE_CACHE=cache_base)
        r = subprocess.run(
            [sys.executable, str(script)], env=env,
            capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        line = [l for l in r.stdout.splitlines()
                if l.startswith("RESULT ")][0]
        got = json.loads(line[len("RESULT "):])
        assert got["stats"]["prefill_compiles"] == 0
        assert got["stats"]["decode_compiles"] == 0
        assert got["out"] == cold


# ------------------------------------------------------- warm standby


class TestWarmStandby:
    def _driver(self, hosts, **kw):
        from horovod_tpu.elastic.discovery import HostDiscovery
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo

        class FakeDiscovery(HostDiscovery):
            def __init__(self, hosts):
                self.hosts = [HostInfo(h, s) for h, s in hosts]

            def find_available_hosts_and_slots(self):
                return list(self.hosts)

        kw.setdefault("min_np", 2)
        d = ElasticDriver(FakeDiscovery(hosts), ["true"], **kw)
        d.host_manager.refresh()
        return d

    def test_reservation_holds_excess_host(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_WARM_STANDBY", "1")
        d = self._driver([("a", 2), ("b", 2)])
        a = d.compute_assignment()
        assert a.world_size == 2 and a.hostnames == ["a"]
        assert d._standby_current == {"b"}

    def test_tight_capacity_reserves_nothing(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_WARM_STANDBY", "1")
        d = self._driver([("a", 2)])
        a = d.compute_assignment()
        assert a.hostnames == ["a"]
        assert d._standby_current == set()

    def test_host_failure_swaps_standby_in(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_WARM_STANDBY", "1")
        d = self._driver([("a", 2), ("b", 2)])
        d.compute_assignment()
        d._standby_warmers["b"] = None  # a tracked (fake) warmer
        d.handle_host_failure("a")
        assert "b" in d._standby_released
        a = d.compute_assignment()
        assert a.hostnames == ["b"] and a.world_size == 2
        assert d._standby_swapins == 1

    def test_released_standby_is_never_rereserved(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_WARM_STANDBY", "1")
        d = self._driver([("a", 2), ("b", 2)])
        d.compute_assignment()
        d._standby_warmers["b"] = None
        d._release_standby("test")
        a = d.compute_assignment()
        # the released host joins the gang; the pool may backfill a
        # DIFFERENT host as the next standby, but never "b" again
        assert "b" not in d._standby_current
        assert "b" in a.hostnames

    def test_serve_saturation_releases_standby(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_WARM_STANDBY", "1")
        d = self._driver([("a", 2), ("b", 2)])
        d.compute_assignment()
        d._standby_warmers["b"] = None
        # headroom left: no scale-up
        d._maybe_scale_up(
            {"decode": {"workers": 2, "free_slots": 3, "free_pages": 8}}
        )
        assert d._scaleup_reason is None
        # zero admission headroom on a live role: release + grow
        d._maybe_scale_up(
            {"decode": {"workers": 2, "free_slots": 0, "free_pages": 0}}
        )
        assert d._scaleup_reason is not None
        assert "scaleup" in d._scaleup_reason
        assert "b" in d._standby_released

    def test_standby_lifecycle_announce_stage_release(self, tmp_path):
        from horovod_tpu.elastic.standby import StandbyWarmer
        from horovod_tpu.runner.rendezvous import (
            KVStore, STANDBY_SCOPE, read_standbys,
        )

        base = str(tmp_path / "cache")
        low = _lowered()
        exe_cache.store(
            low.compile(), "fam.sb", exe_cache.hlo_fingerprint(low),
            sync=True, base=base,
        )
        store = KVStore()
        w = StandbyWarmer(store, "standby-1", exe_cache_base=base)
        w._announce("announce")
        detail = w.stage()
        assert detail["exes"] == 1 and detail["exe_bytes"] > 0
        w._announce("armed", detail)
        st = read_standbys(store)
        assert st["standby-1"]["state"] == "armed"
        assert st["standby-1"]["exes"] == 1
        assert not w._released()
        store.put(STANDBY_SCOPE, "release.standby-1", b"1")
        assert w._released()


# ----------------------------------------------------- restart clock


class TestRestartStamp:
    def test_stamp_round_trip(self):
        from horovod_tpu.runner.rendezvous import (
            KVStore, put_restart_stamp, read_restart_stamp,
        )

        store = KVStore()
        assert read_restart_stamp(store) is None
        put_restart_stamp(store, epoch=3, reason="host a failed",
                          warm=True, kind="scaleup")
        stamp = read_restart_stamp(store)
        assert stamp["epoch"] == 3
        assert stamp["warm"] is True
        assert stamp["kind"] == "scaleup"
        assert stamp["ts"] > 0

    def test_worker_publishes_restart_ms(self):
        from horovod_tpu.elastic.worker import WorkerNotificationManager
        from horovod_tpu.runner.rendezvous import (
            KVStore, put_restart_stamp,
        )

        store = KVStore()
        put_restart_stamp(store, epoch=2, reason="quarantine",
                          warm=True, kind="scaleup")
        mgr = WorkerNotificationManager.__new__(WorkerNotificationManager)
        before = dict(registry.snapshot())
        registry.gauge("elastic.restart_ms", -1.0)
        mgr._publish_restart_ms(store, "1")  # stale epoch: no-op
        assert registry.snapshot()["elastic.restart_ms"] == -1.0
        mgr._publish_restart_ms(store, "2")
        snap = registry.snapshot()
        assert snap["elastic.restart_ms"] >= 0.0
        assert snap["elastic.restart_warm"] == 1.0
        assert snap["serve.scaleup_ms"] == snap["elastic.restart_ms"]
