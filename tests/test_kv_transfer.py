"""Disaggregated prefill/decode tests (serving/kv_transfer.py): wire
codec round-trips (fp32 bit-exact, int8 bounded, pad exclusion),
fp32-wire bit-parity of transferred decode vs a unified worker (incl.
RoPE/GQA and staggered multi-slot), the int8 divergence/greedy-match
gate, role-gated compile counts (decode_compiles==1 on decode workers
across streamed admissions, 0 on pure-prefill), chaos-injected
mid-transfer resets absorbed by the RetryPolicy, exhaustion falling
back to local decode with zero client-visible 500s, and the Router's
role split including the mixed-version (missing ``role``) regression.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.common.metrics import registry as _metrics
from horovod_tpu.common.retry import RetryPolicy, _reset_breakers
from horovod_tpu.testing import chaos


def _cfg(**kw):
    from horovod_tpu.models.transformer import TransformerConfig

    base = dict(
        vocab_size=61,
        num_layers=1,
        d_model=16,
        num_heads=2,
        d_ff=32,
        max_len=64,
        causal=True,
        dtype=jnp.float32,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _toy(**cfg_kw):
    from horovod_tpu.models.transformer import Transformer

    model = Transformer(_cfg(**cfg_kw))
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    return model, params


@pytest.fixture(scope="module")
def toy():
    return _toy()


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    chaos.reset()
    _reset_breakers()
    yield
    chaos.reset()
    _reset_breakers()


def _engine(model, params, role="unified", **kw):
    from horovod_tpu.serving.engine import InferenceEngine

    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("paged", True)
    return InferenceEngine(model, params, role=role, **kw)


def _batcher(engine, role="unified", **kw):
    from horovod_tpu.serving.batcher import ContinuousBatcher

    kw.setdefault("default_max_new_tokens", 8)
    return ContinuousBatcher(engine, role=role, **kw)


def _unified_tokens(model, params, prompt, n, submit_kw=None, **engine_kw):
    """Reference: the same prompt decoded end-to-end on one worker."""
    bat = _batcher(_engine(model, params, **engine_kw))
    req = bat.submit(prompt, max_new_tokens=n, **(submit_kw or {}))
    while not req.finished():
        bat.step()
    assert req.status == "done"
    return req.result()["tokens"]


class _FakeAnnounceClient:
    """Serve-scope announcement reader over a dict — what the
    TransferCoordinator sees instead of a live rendezvous KV."""

    def __init__(self, anns):
        self.anns = dict(anns)

    def keys(self, scope):
        return [str(r) for r in self.anns]

    def get(self, scope, key):
        return json.dumps(self.anns[int(key)]).encode()


def _decode_ann(rank, transfer_port, free_pages=100, **extra):
    ann = {
        "port": 1,
        "addr": "127.0.0.1",
        "role": "decode",
        "transfer_port": transfer_port,
        "free_pages": free_pages,
        "free_slots": 4,
        "ts": time.time(),
    }
    ann.update(extra)
    return ann


def _fleet(model, params, wire="fp32", retry=None, decode_kw=None,
           prefill_kw=None):
    """One prefill + one decode worker wired through a real
    KVTransferServer on localhost. Returns (pbat, dbat, server,
    coordinator); caller stops server/dbat."""
    from horovod_tpu.serving.kv_transfer import (
        KVTransferServer,
        TransferCoordinator,
    )

    deng = _engine(model, params, role="decode", **(decode_kw or {}))
    dbat = _batcher(deng, role="decode")
    server = KVTransferServer(dbat, port=0, addr="127.0.0.1")
    server.start()
    peng = _engine(model, params, role="prefill", **(prefill_kw or {}))
    pbat = _batcher(peng, role="prefill")
    coord = TransferCoordinator(
        peng,
        client=_FakeAnnounceClient({0: _decode_ann(0, server.port)}),
        wire=wire,
        retry=retry,
    )
    pbat.transfer = coord
    dbat.start()
    return pbat, dbat, server, coord


def _pump(pbat, reqs, timeout=30.0):
    deadline = time.monotonic() + timeout
    while (
        not all(r.finished() for r in reqs)
        and time.monotonic() < deadline
    ):
        pbat.step()
        time.sleep(0.005)
    assert all(r.finished() for r in reqs), "transfer never completed"


# ---------------------------------------------------------------- codec


def test_fp32_wire_roundtrip_bit_exact():
    from horovod_tpu.serving.kv_transfer import (
        frame,
        pack_raw_pages,
        unframe,
        unpack_pages,
    )

    rng = np.random.default_rng(0)
    raw = [
        rng.standard_normal((3, 8, 2, 4)).astype(np.float32)
        for _ in range(2)
    ]
    meta, blob = pack_raw_pages(
        raw, [0, 1, 2], length=20, page_tokens=8, wire="fp32"
    )
    meta2, blob2 = unframe(frame(meta, blob))
    assert meta2 == meta
    out = unpack_pages(meta2, blob2)
    for a, b in zip(raw, out):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_int8_wire_bounded_error_and_pad_exclusion():
    from horovod_tpu.serving.kv_transfer import (
        pack_raw_pages,
        unpack_pages,
        wire_block_size,
    )

    rng = np.random.default_rng(1)
    page = rng.standard_normal((4, 8, 2, 4)).astype(np.float32)
    # tail page: only the first 3 token rows valid, rest zero (pad) —
    # plus a huge valid value so a pad-inclusive scale would be obvious
    page[-1, 3:] = 0.0
    page[0, 0, 0, 0] = 50.0
    meta, blob = pack_raw_pages(
        [page], [0, 1, 2, 3], length=27, page_tokens=8, wire="int8"
    )
    (out,) = unpack_pages(meta, blob)
    block = wire_block_size(int(np.prod(page.shape[1:])))
    # per-block bound: |err| <= scale/2 + stochastic rounding, scale =
    # blockmax/127 — check against the loose 2*blockmax/127 envelope
    flat_in = page.reshape(page.shape[0], -1)
    flat_out = out.reshape(out.shape[0], -1)
    for p in range(page.shape[0]):
        for b0 in range(0, flat_in.shape[1], block):
            seg_in = flat_in[p, b0:b0 + block]
            seg_out = flat_out[p, b0:b0 + block]
            bound = 2.0 * np.abs(seg_in).max() / 127.0 + 1e-6
            assert np.abs(seg_in - seg_out).max() <= bound
    # pad rows are exact zeros on the far side — zero never raises a
    # block absmax, so pads are excluded from scales by construction
    np.testing.assert_array_equal(out[-1, 3:], 0.0)


def test_int8_wire_is_smaller_than_fp32():
    from horovod_tpu.serving.kv_transfer import frame, pack_raw_pages

    rng = np.random.default_rng(2)
    # realistic page volume (the toy tests above keep pages tiny, but
    # the byte-ratio claim is about real payloads where the JSON meta
    # is noise): 8 KiB of fp32 per page per leaf
    raw = [
        rng.standard_normal((6, 8, 8, 32)).astype(np.float32)
        for _ in range(2)
    ]
    sizes = {}
    for wire in ("fp32", "int8"):
        meta, blob = pack_raw_pages(
            raw, list(range(6)), length=48, page_tokens=8, wire=wire
        )
        sizes[wire] = len(frame(meta, blob))
    assert sizes["fp32"] / sizes["int8"] >= 3.5


def test_bf16_wire_roundtrip():
    from horovod_tpu.serving.kv_transfer import (
        pack_raw_pages,
        unpack_pages,
    )

    raw = [np.linspace(-2, 2, 64, dtype=np.float32).reshape(1, 8, 2, 4)]
    meta, blob = pack_raw_pages(
        raw, [0], length=8, page_tokens=8, wire="bf16"
    )
    (out,) = unpack_pages(meta, blob)
    assert out.dtype == np.float32
    assert np.abs(out - raw[0]).max() <= 0.02  # bf16 mantissa


def test_wire_block_size_never_straddles_pages():
    from horovod_tpu.serving.kv_transfer import wire_block_size

    for elems in (64, 500, 512, 513, 1024, 4096):
        b = wire_block_size(elems)
        assert elems % b == 0
        assert b <= max(512, 1)


# ------------------------------------------------------------ bit parity


def test_fp32_transfer_bit_parity_with_unified(toy):
    model, params = toy
    prompt = list(range(1, 11))
    ref = _unified_tokens(model, params, prompt, 8)
    pbat, dbat, server, _ = _fleet(model, params, wire="fp32")
    try:
        req = pbat.submit(prompt, max_new_tokens=8)
        _pump(pbat, [req])
        assert req.status == "done"
        assert req.result()["tokens"] == ref
    finally:
        dbat.stop()
        server.stop()


def test_fp32_transfer_bit_parity_rope_gqa():
    """The parity gate on the attention variants most sensitive to KV
    placement: rotary embeddings + grouped-query heads."""
    model, params = _toy(rope=True, num_kv_heads=1)
    prompt = list(range(2, 14))
    ref = _unified_tokens(model, params, prompt, 6)
    pbat, dbat, server, _ = _fleet(model, params, wire="fp32")
    try:
        req = pbat.submit(prompt, max_new_tokens=6)
        _pump(pbat, [req])
        assert req.result()["tokens"] == ref
    finally:
        dbat.stop()
        server.stop()


def test_fp32_transfer_bit_parity_staggered_multislot(toy):
    """Three prompts streamed at staggered times share the decode
    worker's slots; every one must still match its unified reference
    bit for bit — cross-slot KV isolation survives the wire."""
    model, params = toy
    prompts = [list(range(1, 8)), list(range(3, 15)), [7, 5, 3, 2, 9]]
    refs = [_unified_tokens(model, params, p, 6) for p in prompts]
    pbat, dbat, server, _ = _fleet(model, params, wire="fp32")
    try:
        reqs = []
        for p in prompts:
            reqs.append(pbat.submit(p, max_new_tokens=6))
            for _ in range(3):  # stagger: admissions land mid-decode
                pbat.step()
                time.sleep(0.002)
        _pump(pbat, reqs)
        for req, ref in zip(reqs, refs):
            assert req.status == "done"
            assert req.result()["tokens"] == ref
    finally:
        dbat.stop()
        server.stop()


def test_int8_transfer_bounded_divergence_and_greedy_match(toy):
    """The lossy-wire gate: transferred-int8 decode must greedy-match
    the unified reference on nearly every step of a batch of prompts
    (logit perturbations are bounded by the per-block quantization
    error, so argmax flips only near ties)."""
    model, params = toy
    prompts = [list(range(1, 10)), list(range(5, 17)), [9, 1, 4, 4, 8]]
    refs = [_unified_tokens(model, params, p, 8) for p in prompts]
    pbat, dbat, server, _ = _fleet(model, params, wire="int8")
    try:
        reqs = [pbat.submit(p, max_new_tokens=8) for p in prompts]
        _pump(pbat, reqs)
        total = matched = 0
        for req, ref in zip(reqs, refs):
            assert req.status == "done"
            got = req.result()["tokens"]
            assert len(got) == len(ref)
            total += len(ref)
            matched += sum(g == r for g, r in zip(got, ref))
        assert matched / total >= 0.9, (matched, total)
    finally:
        dbat.stop()
        server.stop()


# ------------------------------------------------- role-gated executables


def test_decode_role_rejects_prompts_and_prefill_raises(toy):
    model, params = toy
    from horovod_tpu.serving.batcher import Rejected

    eng = _engine(model, params, role="decode")
    bat = _batcher(eng, role="decode")
    with pytest.raises(Rejected):
        bat.submit([1, 2, 3])
    with pytest.raises(RuntimeError, match="decode-role"):
        eng.prefill(eng.manager.alloc(), [1, 2, 3])


def test_roles_require_paged_plane(toy):
    model, params = toy
    eng = _engine(model, params, paged=False)
    with pytest.raises(ValueError, match="paged"):
        _batcher(eng, role="prefill")


def test_decode_compiles_once_across_streamed_admissions(toy):
    """The zero-retrace invariant on the transfer path: >=3 streamed
    admissions on a decode worker leave decode_compiles == 1 (ingest
    changes data, never shapes), and the pure-prefill worker that fed
    it never compiled a decode step at all."""
    model, params = toy
    pbat, dbat, server, _ = _fleet(model, params, wire="fp32")
    try:
        reqs = [
            pbat.submit(list(range(1, 6 + i)), max_new_tokens=6)
            for i in range(3)
        ]
        _pump(pbat, reqs)
        assert all(r.status == "done" for r in reqs)
        assert dbat.engine.stats()["decode_compiles"] == 1
        assert dbat.engine.stats()["transfer_ingests"] >= 3
        assert pbat.engine.stats()["decode_compiles"] == 0
    finally:
        dbat.stop()
        server.stop()


# ------------------------------------------------------ chaos + fallback


def test_mid_transfer_reset_is_retried(toy):
    """Satellite: one injected connection reset mid-stream; the
    RetryPolicy absorbs it and the request completes remotely."""
    model, params = toy
    chaos.configure("serve.kv_transfer@1:reset")
    before = _metrics.snapshot().get("serve.transfer_fallbacks", 0)
    retry = RetryPolicy(
        "serve.kv_transfer", attempts=3, backoff_ms=1.0,
        attempt_timeout_s=10.0,
    )
    pbat, dbat, server, _ = _fleet(model, params, wire="fp32",
                                   retry=retry)
    try:
        req = pbat.submit(list(range(1, 9)), max_new_tokens=5)
        _pump(pbat, [req])
        assert req.status == "done"
        snap = _metrics.snapshot()
        assert snap.get("chaos.serve.kv_transfer.reset", 0) >= 1
        # absorbed, not fallen back
        assert snap.get("serve.transfer_fallbacks", 0) == before
        assert dbat.engine.stats()["transfer_ingests"] >= 1
    finally:
        dbat.stop()
        server.stop()


def test_transfer_exhaustion_falls_back_to_local_decode(toy):
    """Satellite: every stream attempt dies mid-transfer (chaos resets
    past the retry budget) AFTER the reservation and prefill; the
    request comes home — completes locally, counted in
    serve.transfer_fallbacks, and the waiter sees a normal result (the
    zero-500s contract is asserted end-to-end below)."""
    model, params = toy
    ref = _unified_tokens(model, params, list(range(1, 9)), 5)
    chaos.configure("serve.kv_transfer:p=1:reset")
    retry = RetryPolicy(
        "serve.kv_transfer", attempts=2, backoff_ms=1.0,
        deadline_s=5.0, attempt_timeout_s=0.5,
    )
    pbat, dbat, server, _ = _fleet(model, params, wire="fp32",
                                   retry=retry)
    before = _metrics.snapshot().get("serve.transfer_fallbacks", 0)
    try:
        req = pbat.submit(list(range(1, 9)), max_new_tokens=5)
        _pump(pbat, [req], timeout=60.0)
        assert req.status == "done"
        assert req.result()["tokens"] == ref  # local decode, same model
        snap = _metrics.snapshot()
        assert snap.get("serve.transfer_fallbacks", 0) == before + 1
        # the ingest never landed on the decode worker
        assert dbat.engine.stats()["transfer_ingests"] == 0
        # the prefill worker compiled its decode table lazily, only now
        assert pbat.engine.stats()["decode_compiles"] == 1
    finally:
        dbat.stop()
        server.stop()


def test_no_decode_capacity_takes_local_path_without_prefill_waste(toy):
    """Reservation BEFORE prefill: with no decode workers announced the
    request never detours through the transfer plane at all."""
    from horovod_tpu.serving.kv_transfer import TransferCoordinator

    model, params = toy
    peng = _engine(model, params, role="prefill")
    pbat = _batcher(peng, role="prefill")
    pbat.transfer = TransferCoordinator(
        peng, client=_FakeAnnounceClient({}), wire="fp32"
    )
    before = _metrics.snapshot().get("serve.transfer_local", 0)
    req = pbat.submit(list(range(1, 7)), max_new_tokens=4)
    while not req.finished():
        pbat.step()
    assert req.status == "done"
    assert (
        _metrics.snapshot().get("serve.transfer_local", 0) == before + 1
    )


def test_generate_zero_500s_under_transfer_outage(toy):
    """The client-facing contract: a prefill worker whose transfer
    plane is down still answers POST /generate with HTTP 200."""
    from horovod_tpu.serving.frontend import ServeFrontend
    from horovod_tpu.serving.kv_transfer import TransferCoordinator

    model, params = toy
    peng = _engine(model, params, role="prefill")
    pbat = _batcher(peng, role="prefill")
    # dead target on a port nothing listens on
    pbat.transfer = TransferCoordinator(
        peng,
        client=_FakeAnnounceClient({0: _decode_ann(0, 1)}),
        wire="fp32",
        retry=RetryPolicy(
            "serve.kv_transfer", attempts=1, backoff_ms=1.0,
            deadline_s=2.0, attempt_timeout_s=0.3,
        ),
        reserve_timeout_s=0.3,
    )
    fe = ServeFrontend(pbat, port=0, addr="127.0.0.1")
    pbat.start()
    fe.start()
    try:
        body = json.dumps(
            {"tokens": list(range(1, 8)), "max_tokens": 4}
        ).encode()
        http = urllib.request.Request(
            f"http://127.0.0.1:{fe.port}/generate", data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(http, timeout=60) as resp:
            assert resp.status == 200
            out = json.loads(resp.read().decode())
        assert out["status"] == "done"
        assert len(out["tokens"]) == 4
    finally:
        fe.stop()
        pbat.stop()


# --------------------------------------------------------------- routing


class _DictStore:
    def __init__(self, anns):
        self.anns = {
            str(r): json.dumps(a).encode() for r, a in anns.items()
        }

    def keys(self, scope):
        return list(self.anns) if scope == "serve" else []

    def get(self, scope, key):
        return self.anns.get(key)


def _ann(rank, role=None, **extra):
    ann = {
        "port": 9000 + rank,
        "addr": "127.0.0.1",
        "free_slots": 4,
        "free_pages": 50,
        "queue_depth": 0,
        "ts": time.time(),
    }
    if role is not None:
        ann["role"] = role
    ann.update(extra)
    return ann


def test_router_mixed_version_blobs_missing_role_stay_routable():
    """Satellite regression: old workers announce without any ``role``
    field — they must parse as unified and keep taking traffic."""
    from horovod_tpu.serving.frontend import Router

    router = Router(_DictStore({0: _ann(0), 1: _ann(1)}))
    picked = router.pick()
    assert picked is not None and picked["rank"] in (0, 1)


def test_router_excludes_decode_and_prefers_prefill():
    from horovod_tpu.serving.frontend import Router

    # decode-only fleet: nothing to route prompts to
    router = Router(_DictStore({0: _ann(0, "decode")}))
    assert router.pick() is None

    # mixed fleet: decode never picked; prefill outranks unified (and
    # the roleless legacy blob counts as unified)
    store = _DictStore({
        0: _ann(0, "decode", free_pages=500),
        1: _ann(1),  # legacy, no role field
        2: _ann(2, "prefill", free_slots=1, free_pages=1),
        3: _ann(3, "unified", free_slots=9, free_pages=90),
    })
    router = Router(store)
    for _ in range(4):
        picked = router.pick()
        assert picked["rank"] == 2  # prefill wins even when less free
        router.credit(2)


def test_capacity_blob_carries_role_and_transfer_port(toy):
    model, params = toy
    from horovod_tpu.serving.frontend import ServeFrontend
    from horovod_tpu.serving.kv_transfer import KVTransferServer

    deng = _engine(model, params, role="decode")
    dbat = _batcher(deng, role="decode")
    server = KVTransferServer(dbat, port=0, addr="127.0.0.1")
    server.start()
    fe = ServeFrontend(dbat, port=0, addr="127.0.0.1",
                       transfer_server=server)
    try:
        cap = fe.capacity()
        assert cap["role"] == "decode"
        assert cap["transfer_port"] == server.port
        free_before = cap["free_pages"]
        # a reservation debits the announced headroom
        body = json.dumps({"pages": 3}).encode()
        http = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/kv/reserve", data=body,
            method="POST",
        )
        with urllib.request.urlopen(http, timeout=10) as resp:
            assert resp.status == 200
        assert fe.capacity()["free_pages"] == free_before - 3
    finally:
        fe.stop()
        server.stop()


def test_reserve_denied_when_draining_or_over_headroom(toy):
    model, params = toy
    import urllib.error

    from horovod_tpu.serving.kv_transfer import KVTransferServer

    deng = _engine(model, params, role="decode")
    dbat = _batcher(deng, role="decode")
    server = KVTransferServer(dbat, port=0, addr="127.0.0.1")
    server.start()
    try:
        headroom = deng.manager.admission_headroom()
        body = json.dumps({"pages": headroom + 1}).encode()
        http = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/kv/reserve", data=body,
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(http, timeout=10)
        assert ei.value.code == 503
    finally:
        server.stop()


def test_reservation_failover_to_second_decode_worker(toy):
    """A denied/unreachable first target is skipped in-call — the
    coordinator reserves on the next candidate."""
    from horovod_tpu.serving.kv_transfer import (
        KVTransferServer,
        TransferCoordinator,
    )

    model, params = toy
    deng = _engine(model, params, role="decode")
    dbat = _batcher(deng, role="decode")
    server = KVTransferServer(dbat, port=0, addr="127.0.0.1")
    server.start()
    peng = _engine(model, params, role="prefill")
    coord = TransferCoordinator(
        peng,
        client=_FakeAnnounceClient({
            # rank 5 looks best (more free pages) but nothing listens
            5: _decode_ann(5, 1, free_pages=500),
            0: _decode_ann(0, server.port, free_pages=10),
        }),
        wire="fp32",
        reserve_timeout_s=0.3,
    )
    try:
        res = coord.reserve(2)
        assert res is not None and res["rank"] == 0
    finally:
        server.stop()


def test_driver_per_role_capacity_gauges():
    """elastic/driver.py satellite wiring: per-role worker counts and
    headroom land as driver.serve.<role>.* gauges, with the missing-
    role blob counted as unified."""
    import types

    from horovod_tpu.elastic.driver import ElasticDriver

    store = _DictStore({
        0: _ann(0, "prefill"),
        1: _ann(1, "decode", free_pages=7, free_slots=2),
        2: _ann(2),  # legacy blob -> unified
    })
    fake = types.SimpleNamespace(
        _server=types.SimpleNamespace(store=store),
        _serve_cap_seen={},
        # PR 18: the capacity poll feeds the standby scale-up check;
        # its behavior is covered in test_exe_cache.py
        _maybe_scale_up=lambda per_role: None,
    )
    ElasticDriver._poll_serve_capacity(fake)
    snap = _metrics.snapshot()
    assert snap.get("driver.serve.prefill.workers") == 1.0
    assert snap.get("driver.serve.decode.workers") == 1.0
    assert snap.get("driver.serve.unified.workers") == 1.0
    assert snap.get("driver.serve.decode.free_pages") == 7.0


# -------------------------------------------------------- live migration


def _migration_receiver(model, params):
    """Decode-role worker behind a real KVTransferServer, scheduler
    running — where migrated sequences land."""
    from horovod_tpu.serving.kv_transfer import KVTransferServer

    deng = _engine(model, params, role="decode")
    dbat = _batcher(deng, role="decode")
    server = KVTransferServer(dbat, port=0, addr="127.0.0.1")
    server.start()
    dbat.start()
    return dbat, server


def _source_mid_decode(model, params, prompt, n, coord_client, wire="fp32",
                       submit_kw=None, retry=None):
    """Unified source worker stepped a few decode rounds in: returns
    (batcher, coordinator, request) with the request mid-decode."""
    from horovod_tpu.serving.kv_transfer import TransferCoordinator

    seng = _engine(model, params)
    sbat = _batcher(seng)
    coord = TransferCoordinator(
        seng, client=coord_client, wire=wire, retry=retry
    )
    req = sbat.submit(prompt, max_new_tokens=n, **(submit_kw or {}))
    for _ in range(4):
        sbat.step()
    assert req.status == "running"
    assert 2 <= len(req.out_tokens) < n
    return sbat, coord, req


def test_live_migration_mid_decode_bit_parity(toy):
    """Tentpole: a sequence detached MID-decode resumes on a decode
    peer bit-identically — the full generated history crosses the wire
    (no token re-decoded, no re-prefill) and the receiver's single
    decode executable absorbs the resume without a retrace."""
    model, params = toy
    prompt = list(range(1, 9))
    ref = _unified_tokens(model, params, prompt, 10)
    dbat, server = _migration_receiver(model, params)
    before = _metrics.snapshot()
    try:
        sbat, coord, req = _source_mid_decode(
            model, params, prompt, 10,
            _FakeAnnounceClient({0: _decode_ann(0, server.port)}),
        )
        records = sbat.export_inflight()
        assert len(records) == 1
        assert coord.migrate(sbat, records[0])
        assert req.wait(timeout=30), "migrated request never completed"
        assert req.status == "done"
        assert req.result()["tokens"] == ref
        snap = _metrics.snapshot()
        assert snap.get("serve.migrations", 0) == before.get(
            "serve.migrations", 0) + 1
        assert snap.get("serve.migrations_in", 0) == before.get(
            "serve.migrations_in", 0) + 1
        # the receiver resumed mid-decode: one decode exe, NO prefill
        assert dbat.engine.stats()["decode_compiles"] == 1
        assert dbat.engine.stats()["prefill_compiles"] == 0
    finally:
        dbat.stop()
        server.stop()


def test_live_migration_preserves_sampling_stream(toy):
    """The armed sampling snapshot carries the RAW mid-stream PRNG key
    (split once per decode step), not the seed: a migrated sampled
    sequence must continue exactly where it left off — re-seeding on
    the receiver would fork the stream and this assert would catch it."""
    model, params = toy
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    kw = dict(temperature=0.7, top_k=7, seed=42)
    ref = _unified_tokens(model, params, prompt, 10, submit_kw=kw)
    dbat, server = _migration_receiver(model, params)
    try:
        sbat, coord, req = _source_mid_decode(
            model, params, prompt, 10,
            _FakeAnnounceClient({0: _decode_ann(0, server.port)}),
            submit_kw=kw,
        )
        records = sbat.export_inflight()
        assert coord.migrate(sbat, records[0])
        assert req.wait(timeout=30)
        assert req.status == "done"
        assert req.result()["tokens"] == ref
    finally:
        dbat.stop()
        server.stop()


def test_frontend_drain_deadline_migrates_inflight(toy):
    """The SIGTERM path end to end: past the drain deadline the
    frontend exports every in-flight slot and streams it out; the
    drain still returns True and the accepted request completes
    remotely with the uninterrupted answer."""
    from horovod_tpu.serving.frontend import ServeFrontend

    model, params = toy
    prompt = list(range(2, 10))
    ref = _unified_tokens(model, params, prompt, 10)
    dbat, server = _migration_receiver(model, params)
    try:
        sbat, coord, req = _source_mid_decode(
            model, params, prompt, 10,
            _FakeAnnounceClient({0: _decode_ann(0, server.port)}),
        )
        sbat.transfer = coord
        fe = ServeFrontend(sbat, port=0, addr="127.0.0.1")
        try:
            assert fe.drain(timeout=30.0, migrate_after=0.0)
        finally:
            fe.stop()
        assert req.finished() and req.status == "done"
        assert req.result()["tokens"] == ref
        assert _metrics.snapshot().get("serve.migrations", 0) >= 1
    finally:
        dbat.stop()
        server.stop()


def test_migration_retried_reset_admits_exactly_once(toy):
    """Chaos at the serve.migrate site: the first stream attempt dies
    mid-flight, the retry re-POSTs the SAME frame, and the receiver's
    idempotency ledger admits it exactly once — still bit-parity."""
    model, params = toy
    prompt = list(range(1, 8))
    ref = _unified_tokens(model, params, prompt, 9)
    chaos.configure("seed=7;serve.migrate@1:reset")
    retry = RetryPolicy(
        "serve.kv_transfer", attempts=3, backoff_ms=1.0,
        attempt_timeout_s=10.0,
    )
    dbat, server = _migration_receiver(model, params)
    before = _metrics.snapshot()
    try:
        sbat, coord, req = _source_mid_decode(
            model, params, prompt, 9,
            _FakeAnnounceClient({0: _decode_ann(0, server.port)}),
            retry=retry,
        )
        records = sbat.export_inflight()
        assert coord.migrate(sbat, records[0])
        assert req.wait(timeout=30)
        assert req.status == "done"
        assert req.result()["tokens"] == ref
        snap = _metrics.snapshot()
        assert snap.get("chaos.serve.migrate.reset", 0) >= 1
        assert snap.get("serve.migrations_in", 0) == before.get(
            "serve.migrations_in", 0) + 1
    finally:
        dbat.stop()
        server.stop()


def test_migration_no_capacity_falls_back_to_local_decode(toy):
    """No peer has room: the exported record comes home — requeued
    paused on its own pages and finished locally by the same drain,
    zero client-visible failures."""
    model, params = toy
    prompt = list(range(4, 12))
    ref = _unified_tokens(model, params, prompt, 8)
    sbat, coord, req = _source_mid_decode(
        model, params, prompt, 8, _FakeAnnounceClient({})
    )
    before = _metrics.snapshot().get("serve.transfer_fallbacks", 0)

    def on_deadline(records):
        for rec in records:
            assert not coord.migrate(sbat, rec)

    assert sbat.drain(timeout=30.0, migrate_after=0.0,
                      on_deadline=on_deadline)
    assert req.status == "done"
    assert req.result()["tokens"] == ref
    assert (
        _metrics.snapshot().get("serve.transfer_fallbacks", 0)
        == before + 1
    )
