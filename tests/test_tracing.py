"""Trace-plane tests: common/tracing.py + analysis/trace_merge.py.

Covers the ISSUE 20 acceptance surfaces that don't need a serving
fleet: context minting/adoption (W3C traceparent round-trip, malformed
headers, sampling), the span ring bound under concurrent emitters
(property test), the NTP offset estimator on synthetic two-host stamp
pairs — including the asymmetric-RTT error bound — multi-hop offset
composition, and skew-corrected assembly ordering.
"""

import json
import os
import threading

import pytest

from horovod_tpu.analysis import trace_merge
from horovod_tpu.common import tracing


@pytest.fixture
def traced(monkeypatch):
    """Tracing ON at sample rate 1.0, fresh recorder + settings."""
    monkeypatch.setenv("HOROVOD_TRACE", "1")
    monkeypatch.setenv("HOROVOD_TRACE_SAMPLE", "1.0")
    tracing._reset()
    yield
    tracing._reset()


# --------------------------------------------------------------- context


class TestContext:
    def test_traceparent_round_trip(self):
        ctx = tracing.TraceContext("ab" * 16, "cd" * 8, True)
        parsed = tracing.parse_traceparent(ctx.to_traceparent())
        assert parsed.trace_id == ctx.trace_id
        assert parsed.span_id == ctx.span_id
        assert parsed.sampled

    @pytest.mark.parametrize(
        "header",
        [
            None,
            "",
            "garbage",
            "00-short-cd" + "cd" * 7 + "-01",
            "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",  # non-hex
            "00-" + "00" * 16 + "-" + "cd" * 8 + "-01",  # zero trace
            "00-" + "ab" * 16 + "-" + "00" * 8 + "-01",  # zero span
        ],
    )
    def test_malformed_headers_rejected(self, header):
        assert tracing.parse_traceparent(header) is None

    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_TRACE", raising=False)
        tracing._reset()
        assert not tracing.enabled()
        assert tracing.mint() is None
        assert tracing.adopt("00-" + "ab" * 16 + "-" + "cd" * 8 + "-01") \
            is None
        # None propagates: no span, no cost
        assert tracing.start_span("x", None) is None
        tracing._reset()

    def test_mint_and_children(self, traced):
        ctx = tracing.mint()
        assert ctx is not None and ctx.sampled
        child = tracing.start_span("op", ctx, k=1)
        assert child.ctx.trace_id == ctx.trace_id
        assert child.ctx.span_id != ctx.span_id
        assert child.parent_id == ctx.span_id

    def test_adopt_keeps_caller_decision(self, traced):
        hdr = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        ctx = tracing.adopt(hdr)
        assert ctx.trace_id == "ab" * 16
        # explicit sampled=0 stays untraced even with tracing on
        assert tracing.adopt(hdr[:-2] + "00") is None

    def test_sample_zero_mints_nothing(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TRACE", "1")
        monkeypatch.setenv("HOROVOD_TRACE_SAMPLE", "0.0")
        tracing._reset()
        assert all(tracing.mint() is None for _ in range(20))
        tracing._reset()

    def test_wire_dict_round_trip(self, traced):
        ctx = tracing.mint()
        back = tracing.TraceContext.from_dict(
            json.loads(json.dumps(ctx.to_dict()))
        )
        assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
        assert tracing.TraceContext.from_dict(None) is None
        assert tracing.TraceContext.from_dict({"trace_id": ""}) is None


# ----------------------------------------------------------------- spans


class TestSpans:
    def test_span_records_into_ring(self, traced):
        ctx = tracing.mint()
        span = tracing.start_span("op", ctx, slot=3)
        span.end(outcome="ok")
        span.end(outcome="twice")  # idempotent: second end is a no-op
        recs = tracing.recorder().spans()
        assert len(recs) == 1
        rec = recs[0]
        assert rec["name"] == "op"
        assert rec["trace_id"] == ctx.trace_id
        assert rec["tags"]["outcome"] == "ok"
        assert rec["host"] and rec["pid"] == os.getpid()
        assert rec["dur_ms"] >= 0

    def test_retry_annotation_lands_on_active_span(self, traced):
        span = tracing.root_span("hop", tracing.mint())
        with span:
            tracing.annotate("retry:site#1@40ms")
        rec = tracing.recorder().spans()[-1]
        assert rec["tags"]["notes"] == ["retry:site#1@40ms"]

    def test_active_adopts_span_across_threads(self, traced):
        span = tracing.root_span("handoff", tracing.mint())
        seen = []

        def worker():
            with tracing.active(span):
                seen.append(tracing.current())

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert seen == [span]
        assert tracing.current() is None

    def test_ring_bound_under_concurrent_emitters(self, traced):
        """Property: whatever N threads emit, the ring NEVER exceeds
        its bound and every surviving record is intact."""
        rec = tracing.recorder()
        rec.configure(capacity=64)
        ctx = tracing.mint()
        stop = threading.Event()
        errors = []

        def emitter(tid):
            try:
                for i in range(500):
                    s = tracing.start_span("burst", ctx, tid=tid, i=i)
                    s.end()
            except Exception as e:  # pragma: no cover - the failure
                errors.append(e)

        def watcher():
            while not stop.is_set():
                assert len(rec) <= 64
                for r in rec.spans():
                    assert r["name"] == "burst"

        threads = [
            threading.Thread(target=emitter, args=(t,)) for t in range(8)
        ]
        w = threading.Thread(target=watcher)
        w.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        w.join()
        assert not errors
        assert len(rec) <= 64
        assert len(rec.spans()) <= 64

    def test_dump_json_lines(self, traced, tmp_path):
        ctx = tracing.mint()
        for i in range(3):
            tracing.start_span("s", ctx, i=i).end()
        path = str(tmp_path / "ring.spans")
        assert tracing.recorder().dump(path) == path
        lines = [json.loads(x) for x in open(path)]
        assert [r["tags"]["i"] for r in lines] == [0, 1, 2]


# -------------------------------------------------------- offset estimation


class TestNtpOffset:
    def test_symmetric_delay_exact(self):
        # host B runs 250 ms ahead; 10 ms each way
        true_off, d = 0.250, 0.010
        t_send = 100.0
        peer_recv = t_send + d + true_off
        peer_send = peer_recv + 0.002
        t_recv = peer_send - true_off + d
        off, err = trace_merge.ntp_offset(
            t_send, peer_recv, peer_send, t_recv
        )
        assert off == pytest.approx(true_off, abs=1e-9)
        assert err == pytest.approx(d, abs=1e-9)

    def test_asymmetric_rtt_error_bound(self):
        """Asymmetric delay skews the estimate but the TRUE offset
        always stays within ±err (half-RTT) of it — the NTP bound the
        assembler's weighting relies on."""
        true_off = -0.120
        for d_fwd, d_back in [(0.001, 0.030), (0.040, 0.002),
                              (0.0, 0.050), (0.025, 0.025)]:
            t_send = 500.0
            peer_recv = t_send + d_fwd + true_off
            peer_send = peer_recv + 0.001
            t_recv = peer_send - true_off + d_back
            off, err = trace_merge.ntp_offset(
                t_send, peer_recv, peer_send, t_recv
            )
            assert abs(off - true_off) <= err + 1e-12, (d_fwd, d_back)
            # and the skew is exactly half the asymmetry
            assert off - true_off == pytest.approx(
                (d_fwd - d_back) / 2, abs=1e-9
            )

    def test_offsets_compose_across_hops(self):
        """router→prefill→decode: decode never talked to the router,
        yet lands on its clock through the prefill edge."""
        edges = [
            {"a": ("router", 1), "b": ("prefill", 2),
             "offset": 0.100, "err": 0.002},
            {"a": ("prefill", 2), "b": ("decode", 3),
             "offset": -0.040, "err": 0.003},
        ]
        offs = trace_merge.host_offsets(
            edges, reference=("router", 1)
        )
        assert offs[("router", 1)] == 0.0
        assert offs[("prefill", 2)] == pytest.approx(0.100)
        assert offs[("decode", 3)] == pytest.approx(0.060)

    def test_parallel_edges_weighted_by_error(self):
        """A tight edge dominates a sloppy (retried) one between the
        same pair — inverse-error fusion."""
        edges = [
            {"a": ("a", 1), "b": ("b", 2), "offset": 0.100,
             "err": 0.001},
            {"a": ("a", 1), "b": ("b", 2), "offset": 0.900,
             "err": 1.000},
        ]
        offs = trace_merge.host_offsets(edges, reference=("a", 1))
        assert abs(offs[("b", 2)] - 0.100) < 0.005

    def test_dijkstra_prefers_tight_path(self):
        """Two routes to the same host: the low-error relay path must
        beat the direct-but-sloppy edge."""
        edges = [
            {"a": ("a", 1), "b": ("c", 3), "offset": 5.0, "err": 2.0},
            {"a": ("a", 1), "b": ("b", 2), "offset": 1.0,
             "err": 0.001},
            {"a": ("b", 2), "b": ("c", 3), "offset": 1.0,
             "err": 0.001},
        ]
        offs = trace_merge.host_offsets(edges, reference=("a", 1))
        # relay path says 2.0; direct sloppy edge said 5.0 but only
        # perturbs the fused direct estimate, it can't win the path
        assert abs(offs[("c", 3)] - 2.0) < 0.1


# --------------------------------------------------------------- assembly


def _span(host, pid, role, name, ts, dur_ms=1.0, trace_id="t" * 32,
          **tags):
    return {
        "trace_id": trace_id, "span_id": os.urandom(8).hex(),
        "parent_id": None, "name": name, "ts": ts, "dur_ms": dur_ms,
        "tags": tags, "host": host, "pid": pid, "role": role,
    }


class TestAssembly:
    def test_skew_corrected_monotonic_order(self):
        """A decode host 10 s behind makes raw timestamps lie; the
        assembled order must still read router → prefill → decode."""
        skew = -10.0  # decode clock = true - 10s
        spans = [
            _span("h1", 1, "router", "route", 100.0, dur_ms=50.0),
            _span("h1", 2, "prefill", "serve.prefill", 100.010),
            # the hop span carries the NTP stamps for the skewed host
            _span(
                "h1", 2, "prefill", "kv.stream", 100.020,
                t_send=100.020, t_recv=100.024,
                peer_recv=100.021 + skew, peer_send=100.023 + skew,
                peer="h1:3",
            ),
            _span("h1", 3, "decode", "serve.decode", 100.030 + skew),
        ]
        corrected, offsets = trace_merge.assemble(spans)
        assert offsets[("h1", 3)] == pytest.approx(skew, abs=0.003)
        names = [r["name"] for r in corrected]
        assert names == [
            "route", "serve.prefill", "kv.stream", "serve.decode"
        ]
        ts = [r["ts_corrected"] for r in corrected]
        assert ts == sorted(ts)

    def test_to_chrome_one_row_per_host_role(self):
        spans = [
            _span("h1", 1, "router", "route", 1.0),
            _span("h1", 2, "prefill", "serve.prefill", 1.1),
            _span("h2", 3, "decode", "serve.decode", 1.2),
        ]
        corrected, offsets = trace_merge.assemble(spans)
        chrome = trace_merge.to_chrome(corrected, offsets)
        meta = [
            e for e in chrome["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        ]
        assert sorted(m["args"]["name"] for m in meta) == [
            "h1 [prefill]", "h1 [router]", "h2 [decode]"
        ]
        events = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
        assert len(events) == 3
        assert all(e["ts"] >= 0 for e in events)
        assert all(e["args"]["trace_id"] == "t" * 32 for e in events)

    def test_traces_in_and_filter(self):
        spans = [
            _span("h", 1, "r", "a", 1.0, trace_id="x" * 32),
            _span("h", 1, "r", "b", 2.0, trace_id="x" * 32),
            _span("h", 1, "r", "c", 3.0, trace_id="y" * 32),
        ]
        assert trace_merge.traces_in(spans) == {
            "x" * 32: 2, "y" * 32: 1
        }
        assert len(trace_merge.filter_trace(spans, "y" * 32)) == 1


# -------------------------------------------------------------- exemplars


class TestExemplars:
    def test_p95_exemplar_witness(self):
        from horovod_tpu.serving.slo import LatencyRecorder

        rec = LatencyRecorder(capacity=128)
        for i in range(100):
            rec.record_ttft(float(i), trace_id=f"trace-{i}")
        s = rec.summaries()["ttft_ms"]
        # nearest-rank p95 witness over 0..99 is sample 94
        assert s["p95_exemplar"] == "trace-94"
        text = "\n".join(rec.render_prometheus_summaries())
        assert '# {trace_id="trace-94"}' in text
        assert 'serve_ttft_p95_exemplar{trace_id="trace-94"} 1' in text

    def test_untraced_samples_leave_no_exemplar(self):
        from horovod_tpu.serving.slo import LatencyRecorder

        rec = LatencyRecorder(capacity=16)
        rec.record_tpot(5.0)
        s = rec.summaries()["tpot_ms"]
        assert s["p95_exemplar"] == ""
        text = "\n".join(rec.render_prometheus_summaries())
        assert "tpot_p95_exemplar" not in text
