"""Ulysses all-to-all sequence parallelism (parallel/ulysses.py): the
sharded computation must match dense full-sequence attention exactly,
including gradients (the second SP strategy next to ring_attention —
SURVEY.md §5.7)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_pkg
from horovod_tpu.parallel.ulysses import _dense_attention, ulysses_attention
from tests.conftest import dense_attention_oracle

B, T, H, D = 2, 64, 8, 16


def _qkv(seed):
    rng = np.random.default_rng(seed)
    return [
        jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
        for _ in range(3)
    ]


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_matches_dense_oracle(hvd, causal):
    mesh = hvd_pkg.mesh()
    q, k, v = _qkv(0)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(None, hvd_pkg.WORLD_AXIS), P(None, hvd_pkg.WORLD_AXIS),
                  P(None, hvd_pkg.WORLD_AXIS)),
        out_specs=P(None, hvd_pkg.WORLD_AXIS),
        check_vma=False,
    )
    def sharded(q, k, v):
        return ulysses_attention(
            q, k, v, axis_name=hvd_pkg.WORLD_AXIS, causal=causal
        )

    got = np.asarray(jax.jit(sharded)(q, k, v))
    # INDEPENDENT oracle (conftest) — not ulysses' own _dense_attention,
    # so a shared attention-math bug cannot cancel out
    want = np.asarray(dense_attention_oracle(q, k, v, causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(_dense_attention(q, k, v, causal)), want,
        rtol=2e-5, atol=2e-5,
    )


def test_gradients_match_dense(hvd):
    mesh = hvd_pkg.mesh()
    q, k, v = _qkv(1)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(None, hvd_pkg.WORLD_AXIS),) * 3,
        out_specs=P(),
        check_vma=False,
    )
    def sharded_loss(q, k, v):
        out = ulysses_attention(
            q, k, v, axis_name=hvd_pkg.WORLD_AXIS, causal=True
        )
        return jax.lax.psum(
            jnp.sum(out.astype(jnp.float32) ** 2), hvd_pkg.WORLD_AXIS
        )

    def dense_loss(q, k, v):
        out = _dense_attention(q, k, v, True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_sharded = jax.jit(jax.grad(sharded_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gs, gd in zip(g_sharded, g_dense):
        np.testing.assert_allclose(
            np.asarray(gs), np.asarray(gd), rtol=5e-4, atol=5e-4
        )


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_flash_inner_matches_dense_oracle(hvd, causal):
    """attn_fn=flash_attention (the TPU 'auto' choice, interpret-mode
    kernels here) must agree with the dense oracle through the
    all-to-all exchanges."""
    from horovod_tpu.ops.flash_attention import flash_attention

    mesh = hvd_pkg.mesh()
    q, k, v = _qkv(3)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(None, hvd_pkg.WORLD_AXIS), P(None, hvd_pkg.WORLD_AXIS),
                  P(None, hvd_pkg.WORLD_AXIS)),
        out_specs=P(None, hvd_pkg.WORLD_AXIS),
        check_vma=False,
    )
    def sharded(q, k, v):
        return ulysses_attention(
            q, k, v, axis_name=hvd_pkg.WORLD_AXIS, causal=causal,
            attn_fn=flash_attention,
        )

    got = np.asarray(jax.jit(sharded)(q, k, v))
    want = np.asarray(dense_attention_oracle(q, k, v, causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_head_poor_model_rejected(hvd):
    mesh = hvd_pkg.mesh()
    q = k = v = jnp.zeros((1, 8, 4, 8), jnp.float32)  # 4 heads < sp=8

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(None, hvd_pkg.WORLD_AXIS),) * 3,
        out_specs=P(None, hvd_pkg.WORLD_AXIS),
        check_vma=False,
    )
    def sharded(q, k, v):
        return ulysses_attention(q, k, v, axis_name=hvd_pkg.WORLD_AXIS)

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(sharded)(q, k, v)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_gqa_matches_repeat_heads_oracle(hvd, causal):
    """Grouped-query inputs through the exchanges (sp=2 mesh so a REAL
    head grouping passes the kv%sp rule: h=8, kv=4, rep=2): kv heads
    split over sp like q heads, whole q-head groups per rank, so the
    inner attention's contiguous group rule stays exact."""
    from jax.sharding import Mesh

    from horovod_tpu.ops.flash_attention import flash_attention

    g = 4  # kv heads: h=8 -> two q heads share each kv head
    q, k, v = _qkv(4)
    kg = k[:, :, :g]
    vg = v[:, :, :g]
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))

    for attn_fn in (None, flash_attention):
        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        def sharded(q, k, v):
            return ulysses_attention(
                q, k, v, axis_name="sp", causal=causal,
                attn_fn=attn_fn,
            )

        got = np.asarray(jax.jit(sharded)(q, kg, vg))
        rep = q.shape[2] // g
        want = np.asarray(dense_attention_oracle(
            q, jnp.repeat(kg, rep, 2), jnp.repeat(vg, rep, 2), causal
        ))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    # head-poor GQA (kv heads not divisible by sp) is rejected loudly
    mesh8 = hvd_pkg.mesh()

    @partial(
        jax.shard_map, mesh=mesh8,
        in_specs=(P(None, hvd_pkg.WORLD_AXIS),) * 3,
        out_specs=P(None, hvd_pkg.WORLD_AXIS),
        check_vma=False,
    )
    def sharded8(q, k, v):
        return ulysses_attention(q, k, v, axis_name=hvd_pkg.WORLD_AXIS)

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(sharded8)(q, kg, vg)
