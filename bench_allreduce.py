"""Allreduce microbenchmark — bandwidth across message sizes AND world
sizes, with scaling efficiency vs perfect-linear.

The harness behind the reference's headline claim (scaling efficiency of
allreduce-dominated training, docs/benchmarks.rst + the Horovod paper
fig. 5-6 [V]; BASELINE.md north star: allreduce scaling efficiency on an
8→256-chip sweep). The sweep is world-size-parameterized: on a pod
slice it walks 8→256 unchanged; on the 8-device CPU simulation it walks
1/2/4/8 (validating the sweep logic with real XLA collectives); on the
1-chip dev box it measures single-device round-trip overhead.

Per (world, size) it prints one JSON line:
  {"metric": "allreduce_busbw", "bytes": N, "world": W,
   "value": GB/s, "unit": "GB/s", "lat_us": ...}
and per world a summary with efficiency vs the base world:
  {"metric": "allreduce_scaling", "world": W, "base_world": B,
   "value": eff, "unit": "ratio", "busbw_gbs": ...}

Bus bandwidth uses the standard ring-allreduce convention:
  busbw = bytes * 2*(W-1)/W / time
(equals algobw for W=1). Ring busbw is world-size-invariant under
perfect scaling, so efficiency(W) = busbw(W) / busbw(base).

Env: BENCH_PLATFORM=cpu for the simulated mesh, BENCH_SIZES (bytes,
comma-sep), BENCH_ITERS, BENCH_WORLDS to override the world sweep.
"""

import json
import os
import time

from _benchlib import stamp as _stamp
from functools import partial

# Quarantine (VERDICT r3 weak #8): a host-simulation number measures
# XLA-on-CPU emulation overhead, not ICI bandwidth/scaling — it must
# never be quotable near BASELINE.md's 90% north star. The note rides
# EVERY non-TPU line (busbw and scaling); save such outputs under a
# sim_ filename prefix (bench.py's stale-artifact fallback skips both).
_SIM_NOTE = (
    "logic-validation only (CPU simulation); NOT a TPU "
    "scaling/efficiency number"
)


def sweep_worlds(n_devices: int):
    """World sizes to sweep given the visible device count: powers of
    two up to n (plus n itself when not a power of two). Large slices
    (>=64 devices) start at 8 — the north star's 8→256 window."""
    worlds = []
    w = 1
    while w <= n_devices:
        worlds.append(w)
        w *= 2
    if worlds[-1] != n_devices:
        worlds.append(n_devices)
    if n_devices >= 64:
        worlds = [w for w in worlds if w >= 8]
    return worlds


def ring_factor(world: int) -> float:
    return 2.0 * (world - 1) / world if world > 1 else 1.0


def scaling_efficiency(busbw_by_world):
    """Efficiency vs perfect-linear: ring busbw is flat across worlds,
    so eff(w) = busbw(w)/busbw(base). Returns (base_world, {w: eff})."""
    if not busbw_by_world:
        return None, {}
    base = min(busbw_by_world)
    base_bw = busbw_by_world[base]
    return base, {
        w: (bw / base_bw if base_bw > 0 else 0.0)
        for w, bw in sorted(busbw_by_world.items())
    }


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as np

    from horovod_tpu.common.topology import WORLD_AXIS
    from horovod_tpu.ops import traced
    from horovod_tpu.ops.reduction_ops import Average

    devices = jax.devices()
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    sizes_env = os.environ.get("BENCH_SIZES")
    if sizes_env:
        sizes = [int(s) for s in sizes_env.split(",")]
    else:
        sizes = [1 << p for p in range(10, 28, 2)]  # 1 KB .. 128 MB
    worlds_env = os.environ.get("BENCH_WORLDS")
    if worlds_env:
        worlds = [int(w) for w in worlds_env.split(",")]
    else:
        worlds = sweep_worlds(len(devices))

    # Representative size for the scaling figure: the largest swept
    # (bandwidth-bound, like gradient buckets after fusion).
    scale_size = max(sizes)
    busbw_at_scale_size = {}

    for world in worlds:
        mesh = Mesh(np.array(devices[:world]), (WORLD_AXIS,))
        for nbytes in sizes:
            n = max(nbytes // 4, 1)  # float32 elements

            # Average (same wire bytes as Sum) keeps the chained values
            # stationary at 1.0: the timed loop feeds each reduce the
            # previous output, so every iteration data-depends on the
            # last — independent same-input calls would let the final
            # sync cover only one of them (and block_until_ready is
            # advisory on the axon tunnel anyway; see _benchlib.sync).
            @partial(
                jax.shard_map,
                mesh=mesh,
                in_specs=P(WORLD_AXIS),
                out_specs=P(WORLD_AXIS),
                check_vma=False,
            )
            def reduce(x):
                return traced.allreduce(x[0], op=Average)[None]

            from _benchlib import sync as _sync

            step = jax.jit(reduce)
            x = jnp.ones((world, n), jnp.float32)
            out = step(x)  # compile + warm
            # one chained call before timing: step(out) sees a committed
            # sharded input — a different jit cache key than the fresh
            # jnp.ones — and must be compiled OUTSIDE the timed region
            out = step(out)
            _sync(out)  # scalar host transfer = trustworthy sync
            t0 = time.perf_counter()
            for _ in range(iters):
                out = step(out)
            _sync(out)
            dt = (time.perf_counter() - t0) / iters
            busbw = nbytes * ring_factor(world) / dt / 1e9
            if nbytes == scale_size:
                busbw_at_scale_size[world] = busbw
            line = {
                "metric": "allreduce_busbw",
                "bytes": nbytes,
                "world": world,
                "value": round(busbw, 3),
                "unit": "GB/s",
                "lat_us": round(dt * 1e6, 1),
                "platform": devices[0].platform,
            }
            if devices[0].platform != "tpu":
                line["note"] = _SIM_NOTE
            print(json.dumps(_stamp(line)), flush=True)

    base, eff = scaling_efficiency(busbw_at_scale_size)
    for world, e in eff.items():
        line = {
            "metric": "allreduce_scaling",
            "world": world,
            "base_world": base,
            "bytes": scale_size,
            "value": round(e, 4),
            "unit": "ratio",
            "busbw_gbs": round(busbw_at_scale_size[world], 3),
            "platform": devices[0].platform,
        }
        if devices[0].platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)


if __name__ == "__main__":
    main()
