"""PyTorch-shim MNIST — the reference's canonical torch example, ported
by changing one import (ref: examples/pytorch/pytorch_mnist.py [V]:
init → DistributedOptimizer → broadcast_parameters → train loop).

The model swaps BatchNorm for hvd.SyncBatchNorm to exercise the
cross-rank statistics path. Synthetic MNIST-shaped data keeps the
example hermetic (no downloads — the sandbox has no egress).

Run (CPU simulation): JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/torch_mnist.py --epochs 1
"""

import argparse
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )

import numpy as np
import torch
import torch.nn as tnn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(tnn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = tnn.Conv2d(1, 8, 3, padding=1)
        self.bn = hvd.SyncBatchNorm(8)
        self.conv2 = tnn.Conv2d(8, 16, 3, padding=1)
        self.fc = tnn.Linear(16 * 7 * 7, 10)

    def forward(self, x):
        x = F.relu(self.bn(self.conv1(x)))
        x = F.max_pool2d(x, 2)
        x = F.relu(self.conv2(x))
        x = F.max_pool2d(x, 2)
        return self.fc(x.flatten(1))


def synthetic_mnist(n=512, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 1, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, size=(n,))
    # plant a learnable signal: mean intensity encodes the label
    x += y[:, None, None, None] * 0.1
    return torch.tensor(x), torch.tensor(y)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(0)

    model = Net()
    # Scale LR by world size; wrap the optimizer; broadcast initial
    # state — the reference's three-line recipe [V].
    optimizer = torch.optim.SGD(
        model.parameters(), lr=args.lr * hvd.size(), momentum=0.9
    )
    optimizer = hvd.DistributedOptimizer(optimizer)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    x, y = synthetic_mnist()
    n = x.shape[0]
    model.train()
    for epoch in range(args.epochs):
        perm = torch.randperm(n)
        losses = []
        for i in range(0, n, args.batch_size):
            idx = perm[i : i + args.batch_size]
            optimizer.zero_grad()
            loss = F.cross_entropy(model(x[idx]), y[idx])
            loss.backward()
            optimizer.step()
            losses.append(float(loss))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")
    print("torch shim example done")


if __name__ == "__main__":
    main()
