"""Backward-interleaved bucketed gradient exchange (ops/overlap.py).

The acceptance contract of the bucketed layer:

* numeric parity with the monolithic path — BIT-exact for op=Sum fp32
  (psum over a concat is elementwise identical to per-leaf psum),
  within the documented quantum/cast bounds for Average / compressed
  wires, including process-set and join cases;
* compiled-program evidence of independence — the lowered step for
  ``overlap_buckets=N`` carries N separate collective ops with no
  def-use path from one bucket's collective to another's operands;
* schedule/compile stability — one schedule build and one trace per
  bucket config across steps (cache stats + trace counter);
* per-bucket preservation of the PR-2 wire machinery — EF residuals,
  the prescale fold, and Compression.int8_block granularity.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu import analysis
from horovod_tpu.ops import overlap, traced
from horovod_tpu.ops.compression import Compression

WORLD = 8


def _shmap(mesh, fn, in_specs=(P(),), out_specs=P()):
    return jax.jit(
        partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )(fn)
    )


def _tree(rng, sizes, dtype=np.float32):
    return {
        f"p{i:02d}": jnp.asarray(rng.normal(size=s), dtype)
        for i, s in enumerate(sizes)
    }


# --------------------------------------------------------- the schedule


class TestBucketSchedule:
    def test_reverse_order_and_balance(self):
        leaves = [np.zeros((64,), np.float32) for _ in range(8)]
        s = overlap.build_bucket_schedule(leaves, 4)
        assert s.n_buckets == 4
        # reverse flatten order: the LAST leaves (produced first in
        # backprop) fill bucket 0
        assert s.buckets == ((7, 6), (5, 4), (3, 2), (1, 0))
        assert set(s.bucket_bytes) == {512}
        assert s.total_bytes == 8 * 64 * 4

    def test_dtype_boundary_forces_split(self):
        leaves = [
            np.zeros((16,), np.float32),
            np.zeros((16,), np.float16),
            np.zeros((16,), np.float16),
        ]
        s = overlap.build_bucket_schedule(leaves, 1)
        # one bucket requested, but fp16 and fp32 cannot share a concat
        assert s.n_buckets == 2
        assert s.buckets == ((2, 1), (0,))

    def test_min_bytes_merges_small_buckets(self):
        leaves = [np.zeros((64,), np.float32) for _ in range(8)]
        s = overlap.build_bucket_schedule(
            leaves, 8, min_bucket_bytes=512
        )
        assert s.n_buckets == 4
        assert all(b >= 512 for b in s.bucket_bytes)

    def test_float0_leaves_pass_through(self):
        leaves = [
            np.zeros((8,), np.float32),
            np.zeros((4,), jax.dtypes.float0),
        ]
        s = overlap.build_bucket_schedule(leaves, 2)
        assert s.passthrough == (1,)
        assert s.buckets == ((0,),)

    def test_schedule_cache_no_churn(self):
        overlap.reset_schedule_cache()
        rng = np.random.default_rng(0)
        t = _tree(rng, [(32,), (16,), (8, 4)])
        leaves, treedef = jax.tree_util.tree_flatten(t)
        for _ in range(5):
            overlap.schedule_for(leaves, treedef, 2)
        stats = overlap.schedule_cache_stats()
        assert stats["misses"] == 1
        assert stats["hits"] == 4


# --------------------------------------------- numeric parity (traced)


class TestParity:
    def test_sum_fp32_bitexact(self, hvd):
        mesh = hvd_mod.mesh()
        rng = np.random.default_rng(1)
        t = _tree(rng, [(33, 7), (129,), (64,), (5, 5, 5), (3,)])
        mono = _shmap(
            mesh,
            lambda p: jax.tree_util.tree_map(
                lambda g: traced.allreduce(g, op=hvd_mod.Sum), p
            ),
        )
        for n in (1, 2, 3, 5):
            buck = _shmap(
                mesh,
                lambda p, n=n: overlap.bucketed_allreduce(
                    p, op=hvd_mod.Sum, n_buckets=n,
                    min_bucket_bytes=0,
                ),
            )
            a, b = mono(t), buck(t)
            for k in t:
                assert (np.asarray(a[k]) == np.asarray(b[k])).all(), (
                    k,
                    n,
                )

    def test_average_parity(self, hvd):
        mesh = hvd_mod.mesh()
        rng = np.random.default_rng(2)
        t = _tree(rng, [(40,), (30,), (20,)])
        mono = _shmap(
            mesh,
            lambda p: jax.tree_util.tree_map(
                lambda g: traced.allreduce(g, op=hvd_mod.Average), p
            ),
        )
        buck = _shmap(
            mesh,
            lambda p: overlap.bucketed_allreduce(
                p, op=hvd_mod.Average, n_buckets=2, min_bucket_bytes=0
            ),
        )
        a, b = mono(t), buck(t)
        for k in t:
            np.testing.assert_allclose(
                np.asarray(a[k]), np.asarray(b[k]), rtol=1e-7
            )

    def test_bf16_wire_tolerance(self, hvd):
        mesh = hvd_mod.mesh()
        rng = np.random.default_rng(3)
        t = _tree(rng, [(50,), (60,)])
        buck = _shmap(
            mesh,
            lambda p: overlap.bucketed_allreduce(
                p,
                op=hvd_mod.Sum,
                n_buckets=2,
                compression=Compression.bf16,
                min_bucket_bytes=0,
            ),
        )
        out = buck(t)
        for k in t:
            exact = np.asarray(t[k]) * WORLD
            # one bf16 cast each way: ~2^-8 relative
            np.testing.assert_allclose(
                np.asarray(out[k]), exact, rtol=2e-2, atol=1e-2
            )

    def test_process_set_bitexact(self, hvd):
        ps = hvd.add_process_set([1, 3, 5])
        mesh = hvd_mod.mesh()
        t = {
            "a": jnp.arange(24.0, dtype=jnp.float32).reshape(4, 6),
            "b": jnp.arange(10.0, dtype=jnp.float32),
        }

        def body(p, x):
            # rank-dependent payload: rank r contributes p * (r + 1)
            r = (traced.rank() + 1).astype(jnp.float32)
            scaled = jax.tree_util.tree_map(lambda g: g * r, p)
            mono = jax.tree_util.tree_map(
                lambda g: traced.allreduce(
                    g, op=hvd_mod.Sum, process_set=ps
                ),
                scaled,
            )
            buck = overlap.bucketed_allreduce(
                scaled, op=hvd_mod.Sum, n_buckets=2, process_set=ps,
                min_bucket_bytes=0,
            )
            return mono, buck

        # out_specs with world axis needs a leading axis: wrap leaves
        run = _shmap(
            mesh,
            lambda p: jax.tree_util.tree_map(
                lambda x: x[None], body(p, None)
            ),
            in_specs=(P(),),
            out_specs=(
                P(hvd_mod.WORLD_AXIS),
                P(hvd_mod.WORLD_AXIS),
            ),
        )
        mono, buck = run(t)
        for k in t:
            assert (
                np.asarray(mono[k]) == np.asarray(buck[k])
            ).all(), k
        # members hold the member-sum, non-members their own input
        member_sum = {
            k: np.asarray(t[k]) * (2 + 4 + 6) for k in t
        }
        np.testing.assert_allclose(
            np.asarray(buck["a"])[3], member_sum["a"]
        )
        np.testing.assert_allclose(
            np.asarray(buck["a"])[0], np.asarray(t["a"]) * 1
        )

    def test_join_mask_parity(self, hvd):
        """The traced join mask: joined ranks drop out, Average divides
        by the live count — identical monolithic vs bucketed."""
        mesh = hvd_mod.mesh()
        mask = np.ones(WORLD, dtype=bool)
        mask[2] = False
        mask[5] = False
        t = {"a": jnp.ones((12,), jnp.float32), "b": jnp.ones((7,))}

        def body(p):
            r = (traced.rank() + 1).astype(jnp.float32)
            scaled = jax.tree_util.tree_map(lambda g: g * r, p)
            mono = jax.tree_util.tree_map(
                lambda g: traced.allreduce(
                    g, op=hvd_mod.Average, mask=mask
                ),
                scaled,
            )
            buck = overlap.bucketed_allreduce(
                scaled, op=hvd_mod.Average, n_buckets=2, mask=mask,
                min_bucket_bytes=0,
            )
            return jax.tree_util.tree_map(
                lambda x: x[None], (mono, buck)
            )

        mono, buck = _shmap(
            mesh,
            body,
            in_specs=(P(),),
            out_specs=(P(hvd_mod.WORLD_AXIS), P(hvd_mod.WORLD_AXIS)),
        )(t)
        live = [r + 1 for r in range(WORLD) if mask[r]]
        expected = np.mean(live)
        for k in t:
            assert (
                np.asarray(mono[k]) == np.asarray(buck[k])
            ).all(), k
            np.testing.assert_allclose(
                np.asarray(buck[k])[0],
                np.asarray(t[k]) * expected,
                rtol=1e-6,
            )


# ------------------------------------ compiled-program independence
# (shared parser: horovod_tpu.analysis — the per-file regex these
# tests used to carry lives there now, typed and rule-checked)


class TestCompiledIndependence:
    def test_n_buckets_n_collectives_no_serial_dep(self, hvd):
        """The lowered module for overlap_buckets=N holds exactly N
        all_reduce ops, and no all_reduce's operands transitively
        reach another all_reduce's result — there is no artificial
        serialization between buckets."""
        mesh = hvd_mod.mesh()
        rng = np.random.default_rng(4)
        t = _tree(rng, [(64,)] * 6)
        n = 3
        fn = _shmap(
            mesh,
            lambda p: overlap.bucketed_allreduce(
                p, op=hvd_mod.Sum, n_buckets=n, min_bucket_bytes=0
            ),
        )
        g = analysis.parse_module(fn.lower(t))
        analysis.expect(
            g,
            analysis.CollectiveCount("all_reduce", n),
            analysis.NoInterCollectiveDefUse("all_reduce"),
        )

    def test_in_backprop_boundary_emits_n_collectives(self, hvd):
        mesh = hvd_mod.mesh()
        rng = np.random.default_rng(5)
        params = _tree(rng, [(16, 16)] * 6)
        n = 3

        def loss(p, x):
            p = overlap.overlap_boundary(
                p, op=hvd_mod.Sum, n_buckets=n, min_bucket_bytes=0
            )
            h = x
            for k in sorted(p):
                h = jnp.tanh(h @ p[k])
            return jnp.sum(h * h)

        fn = _shmap(
            mesh,
            lambda p, x: jax.grad(loss)(p, x[0]),
            in_specs=(P(), P(hvd_mod.WORLD_AXIS)),
        )
        x = jnp.asarray(
            rng.normal(size=(WORLD, 4, 16)), jnp.float32
        )
        g = analysis.parse_module(fn.lower(params, x))
        analysis.expect(g, analysis.CollectiveCount("all_reduce", n))

    def test_no_retrace_and_one_schedule_across_steps(self, hvd):
        """Per-bucket-config compile happens once: 4 steps of the same
        jitted bucketed step trace once and build one schedule."""
        overlap.reset_schedule_cache()
        mesh = hvd_mod.mesh()
        rng = np.random.default_rng(6)
        t = _tree(rng, [(32,), (48,), (16,)])
        traces = {"n": 0}

        def body(p):
            traces["n"] += 1
            return overlap.bucketed_allreduce(
                p, op=hvd_mod.Sum, n_buckets=2, min_bucket_bytes=0
            )

        fn = _shmap(mesh, body)
        out = t
        for _ in range(4):
            out = fn(out)
        assert traces["n"] == 1, "bucketed step retraced"
        stats = overlap.schedule_cache_stats()
        assert stats["misses"] == 1, stats


# ------------------------------------------------ quantized per bucket


def _quantum_bound_bucket(rows):
    """Two-stage quantum bound for one bucket buffer (the
    test_fusion_quantized bound, bucket edition)."""
    q1 = sum(np.abs(np.asarray(r)).max() for r in rows) / 127.0
    total = np.sum(np.stack(rows), axis=0)
    q2 = np.abs(total).max() / 127.0
    return q1 + q2


class TestQuantizedBuckets:
    def _run(self, hvd, fn, t, n_out=1):
        mesh = hvd_mod.mesh()
        out_specs = (
            P() if n_out == 1 else tuple(P() for _ in range(n_out))
        )
        return _shmap(mesh, fn, out_specs=out_specs)(t)

    def test_parity_vs_monolithic_quantized(self, hvd):
        """Bucketed int8_block lands within the summed quantum bounds
        of the PR-2 monolithic (per-leaf) quantized path."""
        rng = np.random.default_rng(7)
        sizes = [(700,), (260,), (300,)]
        t = _tree(rng, sizes)
        mono = self._run(
            hvd,
            lambda p: jax.tree_util.tree_map(
                lambda g: traced.quantized_allreduce(
                    g, op=hvd_mod.Sum, block_size=512
                ),
                p,
            ),
            t,
        )
        buck = self._run(
            hvd,
            lambda p: overlap.bucketed_allreduce(
                p,
                op=hvd_mod.Sum,
                n_buckets=2,
                compression=Compression.int8_block,
                seed=3,
                min_bucket_bytes=0,
            ),
            t,
        )
        # every rank contributes the same row here, so exact = 8x
        for k in t:
            exact = np.asarray(t[k]) * WORLD
            rows = [np.asarray(t[k]).ravel()] * WORLD
            bound = _quantum_bound_bucket(rows)
            # bucket buffers concat several leaves: the bucket bound is
            # conservative (absmax over the shared blocks); both paths
            # must sit within their bound, and within the sum of each
            # other's
            assert (
                np.abs(np.asarray(mono[k]).ravel() - exact.ravel()).max()
                <= bound * 3
            )
            assert (
                np.abs(np.asarray(buck[k]).ravel() - exact.ravel()).max()
                <= bound * 3
            )

    def test_ef_residual_sliced_per_bucket_bitexact(self, hvd):
        """EF residuals are SLICED from the bucket buffer, not
        recomputed per leaf: for each bucket, calling the monolithic
        `quantized_allreduce(return_residual=True)` on the hand-built
        concat of that bucket's members (same seed stride, same block
        size) reproduces the bucketed outputs AND residuals bit-for-bit
        after splitting."""
        rng = np.random.default_rng(8)
        t = _tree(rng, [(256,), (128,), (64,)])
        leaves, treedef = jax.tree_util.tree_flatten(t)
        sched = overlap.build_bucket_schedule(leaves, 2)
        seed = 11

        def bucketed(p):
            res0 = jax.tree_util.tree_map(jnp.zeros_like, p)
            return overlap.bucketed_allreduce(
                p,
                op=hvd_mod.Sum,
                n_buckets=2,
                compression=Compression.int8_block,
                residuals=res0,
                seed=seed,
                min_bucket_bytes=0,
            )

        out, res = self._run(hvd, bucketed, t, n_out=2)

        def oracle(p):
            lv = jax.tree_util.tree_flatten(p)[0]
            outs, ress = [], []
            for b, idxs in enumerate(sched.buckets):
                buf = jnp.concatenate(
                    [lv[i].reshape(-1) for i in idxs]
                )
                o, r = traced.quantized_allreduce(
                    buf,
                    op=hvd_mod.Sum,
                    seed=seed * sched.n_buckets + b,
                    return_residual=True,
                    block_size=Compression.int8_block.block_size,
                )
                outs.append(o)
                ress.append(r)
            return tuple(outs), tuple(ress)

        o_outs, o_ress = self._run(hvd, oracle, t, n_out=2)
        flat_keys = sorted(t)
        for b, idxs in enumerate(sched.buckets):
            off = 0
            for i in idxs:
                k = flat_keys[i]
                sz = np.asarray(t[k]).size
                np.testing.assert_array_equal(
                    np.asarray(out[k]),
                    np.asarray(o_outs[b])[off : off + sz],
                )
                np.testing.assert_array_equal(
                    np.asarray(res[k]),
                    np.asarray(o_ress[b])[off : off + sz],
                )
                off += sz

    def test_ef_converges_across_steps(self, hvd):
        """EF-SGD property through the BUCKETED wire: with a constant
        gradient, the running mean of reduced outputs converges to the
        exact sum (the carry keeps the quantizer honest)."""
        rng = np.random.default_rng(9)
        t = _tree(rng, [(200,), (100,)])
        mesh = hvd_mod.mesh()

        def step(p, r, s):
            return overlap.bucketed_allreduce(
                p,
                op=hvd_mod.Sum,
                n_buckets=2,
                compression=Compression.int8_block,
                residuals=r,
                seed=s,
                min_bucket_bytes=0,
            )

        fn = jax.jit(
            partial(
                jax.shard_map,
                mesh=mesh,
                in_specs=(P(), P(), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )(step),
            static_argnums=(),
        )
        res = jax.tree_util.tree_map(jnp.zeros_like, t)
        acc = {k: 0.0 for k in t}
        steps = 12
        for s in range(steps):
            out, res = fn(t, res, jnp.asarray(s))
            for k in t:
                acc[k] = acc[k] + np.asarray(out[k])
        for k in t:
            exact = np.asarray(t[k]) * WORLD
            mean_err = np.abs(acc[k] / steps - exact).max()
            one_shot = np.abs(np.asarray(out[k]) - exact).max()
            assert mean_err <= max(one_shot, 1e-6) * 1.05, (
                k,
                mean_err,
                one_shot,
            )

    def test_prescale_fold_parity(self, hvd):
        """The prescale fold survives bucketing: folded prescale ==
        two-pass (pre-multiplied tensor) bit-exactly for positive
        factors, per bucket."""
        rng = np.random.default_rng(10)
        t = _tree(rng, [(300,), (212,)])
        f = 0.37
        folded = self._run(
            hvd,
            lambda p: overlap.bucketed_allreduce(
                p,
                op=hvd_mod.Sum,
                n_buckets=2,
                compression=Compression.int8_block,
                prescale_factor=f,
                seed=5,
                min_bucket_bytes=0,
            ),
            t,
        )
        twopass = self._run(
            hvd,
            lambda p: overlap.bucketed_allreduce(
                jax.tree_util.tree_map(lambda g: g * f, p),
                op=hvd_mod.Sum,
                n_buckets=2,
                compression=Compression.int8_block,
                seed=5,
                min_bucket_bytes=0,
            ),
            t,
        )
        for k in t:
            np.testing.assert_allclose(
                np.asarray(folded[k]),
                np.asarray(twopass[k]),
                rtol=1e-6,
                atol=1e-7,
            )

    def test_block_granularity_honored(self, hvd):
        """A custom block_size (Compression.int8_block.with_block_size)
        reaches the bucket wire: an outlier leaf sharing a bucket with
        a small-magnitude leaf must not destroy the latter's precision
        when blocks are fine enough to separate them."""
        fine = Compression.int8_block.with_block_size(128)
        small = np.full(512, 1e-3, np.float32)
        outlier = np.full(512, 1e3, np.float32)
        t = {
            "small": jnp.asarray(small),
            "outlier": jnp.asarray(outlier),
        }
        out = self._run(
            hvd,
            lambda p: overlap.bucketed_allreduce(
                p, op=hvd_mod.Sum, n_buckets=1, compression=fine,
                seed=2, min_bucket_bytes=0,
            ),
            t,
        )
        exact_small = small * WORLD
        # fine blocks: the small leaf's blocks own their scales, so its
        # relative error stays at the int8 quantum, not the outlier's
        err = np.abs(np.asarray(out["small"]) - exact_small).max()
        assert err <= (1e-3 * WORLD) / 127.0 * 3 + (1e-3 / 127.0) * 8


# --------------------------------------- end-to-end optimizer parity


class TestOptimizerIntegration:
    def _problem(self, rng):
        params = _tree(rng, [(24, 8), (8,), (8, 8), (8,)])
        x = jnp.asarray(
            rng.normal(size=(WORLD, 6, 24)), jnp.float32
        )
        y = jnp.asarray(rng.normal(size=(WORLD, 6, 8)), jnp.float32)
        return params, x, y

    @staticmethod
    def _loss(p, xb, yb):
        h = jnp.tanh(xb @ p["p00"] + p["p01"])
        h = h @ p["p02"] + p["p03"]
        return jnp.mean((h - yb) ** 2)

    def _make_step(self, opt, vg):
        mesh = hvd_mod.mesh()

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P(hvd_mod.WORLD_AXIS),
                      P(hvd_mod.WORLD_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        def step(p, st, xb, yb):
            loss, g = vg(p, xb[0], yb[0])
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st, jax.lax.pmean(
                loss, hvd_mod.WORLD_AXIS
            )

        return jax.jit(step)

    def test_distributed_optimizer_overlap_bitexact(self, hvd):
        """DistributedOptimizer(overlap_buckets=N) reproduces the
        monolithic trajectory bit-for-bit (op=Sum, fp32)."""
        rng = np.random.default_rng(11)
        params, x, y = self._problem(rng)
        vg = jax.value_and_grad(self._loss)
        o1 = hvd_mod.DistributedOptimizer(
            optax.adam(1e-2), op=hvd_mod.Sum
        )
        o2 = hvd_mod.DistributedOptimizer(
            optax.adam(1e-2), op=hvd_mod.Sum, overlap_buckets=2,
            overlap_min_bytes=0,
        )
        s1, s2 = o1.init(params), o2.init(params)
        st1, st2 = self._make_step(o1, vg), self._make_step(o2, vg)
        p1 = p2 = params
        for _ in range(3):
            p1, s1, l1 = st1(p1, s1, x, y)
            p2, s2, l2 = st2(p2, s2, x, y)
        for k in params:
            assert (np.asarray(p1[k]) == np.asarray(p2[k])).all(), k
        assert float(l1) == float(l2)

    def test_value_and_grad_in_backprop_parity(self, hvd):
        """hvd.value_and_grad(overlap_buckets=N) — the custom_vjp
        boundary — returns the same reduced gradients as the post-hoc
        exchange (within float tolerance; the exchange runs at a
        different point of the backward)."""
        rng = np.random.default_rng(12)
        params, x, y = self._problem(rng)
        vg_mono = hvd_mod.value_and_grad(self._loss, op=hvd_mod.Sum)
        vg_over = hvd_mod.value_and_grad(
            self._loss, op=hvd_mod.Sum, overlap_buckets=2,
            overlap_min_bytes=0,
        )
        mesh = hvd_mod.mesh()

        def run(vg):
            return _shmap(
                mesh,
                lambda p, xb, yb: vg(p, xb[0], yb[0]),
                in_specs=(P(), P(hvd_mod.WORLD_AXIS),
                          P(hvd_mod.WORLD_AXIS)),
                out_specs=(P(), P()),
            )(params, x, y)

        (l1, g1), (l2, g2) = run(vg_mono), run(vg_over)
        assert float(l1) == float(l2)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(g1[k]), np.asarray(g2[k]),
                rtol=1e-6, atol=1e-7,
            )

    def test_value_and_grad_overlap_rejects_tuple_argnums(self, hvd):
        with pytest.raises(ValueError, match="argnums"):
            hvd_mod.value_and_grad(
                self._loss, argnums=(0, 1), overlap_buckets=2
            )

    def test_overlap_rejects_adasum(self, hvd):
        with pytest.raises(ValueError, match="Adasum"):
            hvd_mod.DistributedOptimizer(
                optax.sgd(1e-2), op=hvd_mod.Adasum, overlap_buckets=2
            )

    def test_env_default_falls_back_for_unsupported_ops(
        self, hvd, monkeypatch
    ):
        """HOROVOD_OVERLAP=1 is a fleet-wide default: a job whose op
        the bucketed layer can't carry (Min/Max/Adasum) silently keeps
        the monolithic path — only an EXPLICIT overlap_buckets= with a
        bad op is a construction error."""
        monkeypatch.setenv("HOROVOD_OVERLAP", "1")
        monkeypatch.setenv("HOROVOD_OVERLAP_BUCKETS", "4")
        # constructs fine (falls back), and the wrapped update traces
        # through the monolithic per-leaf path
        opt = hvd_mod.DistributedOptimizer(
            optax.sgd(1e-2), op=hvd_mod.Min
        )
        rng = np.random.default_rng(20)
        params = _tree(rng, [(8,), (4,)])
        mesh = hvd_mod.mesh()
        st = opt.init(params)
        upd = _shmap(
            mesh,
            lambda p: opt.update(p, st, p)[0],
        )(params)
        for k in params:
            assert np.isfinite(np.asarray(upd[k])).all()
        # the tape API falls back the same way
        hvd_mod.value_and_grad(self._loss, op=hvd_mod.Min)
        # explicit request still raises loudly
        with pytest.raises(ValueError, match="Sum/Average"):
            hvd_mod.DistributedOptimizer(
                optax.sgd(1e-2), op=hvd_mod.Min, overlap_buckets=4
            )
        with pytest.raises(ValueError, match="Sum/Average"):
            hvd_mod.value_and_grad(
                self._loss, op=hvd_mod.Min, overlap_buckets=4
            )

    def test_sharded_optimizer_bucketed_bitexact_and_hlo(self, hvd):
        """ZeRO-1 with overlap_buckets: bit-exact trajectory vs the
        per-leaf exchange, and the lowered step carries N independent
        reduce_scatter + N all_gather ops."""
        rng = np.random.default_rng(13)
        params, x, y = self._problem(rng)
        o1 = hvd_mod.ShardedDistributedOptimizer(optax.adam(1e-2))
        o2 = hvd_mod.ShardedDistributedOptimizer(
            optax.adam(1e-2), overlap_buckets=2, overlap_min_bytes=0
        )
        mesh = hvd_mod.mesh()

        def make(opt):
            @partial(
                jax.shard_map,
                mesh=mesh,
                in_specs=(P(), opt.state_spec(),
                          P(hvd_mod.WORLD_AXIS),
                          P(hvd_mod.WORLD_AXIS)),
                out_specs=(P(), opt.state_spec(), P()),
                check_vma=False,
            )
            def step(p, st, xb, yb):
                loss, g = jax.value_and_grad(self._loss)(
                    p, xb[0], yb[0]
                )
                u, st = opt.update(g, st, p)
                return optax.apply_updates(p, u), st, jax.lax.pmean(
                    loss, hvd_mod.WORLD_AXIS
                )

            return jax.jit(step)

        s1, s2 = o1.init(params), o2.init(params)
        st1, st2 = make(o1), make(o2)
        g = analysis.parse_module(st2.lower(params, s2, x, y))
        analysis.expect(
            g,
            analysis.CollectiveCount("reduce_scatter", 2),
            analysis.CollectiveCount("all_gather", 2),
            analysis.NoInterCollectiveDefUse("reduce_scatter"),
        )
        p1, p2 = params, params
        for _ in range(3):
            p1, s1, l1 = st1(p1, s1, x, y)
            p2, s2, l2 = st2(p2, s2, x, y)
        for k in params:
            assert (np.asarray(p1[k]) == np.asarray(p2[k])).all(), k


# ------------------------------------------------- tuner + config


class TestOverlapTuner:
    def test_explore_then_exploit(self):
        from horovod_tpu.common.autotune import OverlapTuner

        t = OverlapTuner(min_bucket_bytes=0, trials=2)
        key = "step"
        total = 1 << 22
        seen = []
        # feed synthetic observations: n=4 has the best goodput
        for _ in range(2 * len(t.candidates) + 4):
            n = t.choose(key, total)
            seen.append(n)
            secs = {1: 1.0, 2: 0.8, 4: 0.5, 8: 0.7, 16: 0.9}[n]
            t.record(key, n, total, secs)
        # exploration visited every candidate `trials` times...
        for c in t.candidates:
            assert seen.count(c) >= 2 or seen[-1] == 4
        # ...then settled on the argmax
        assert seen[-1] == 4
        assert t.choose(key, total) == 4

    def test_min_bytes_floor_prunes_candidates(self):
        from horovod_tpu.common.autotune import OverlapTuner

        t = OverlapTuner(min_bucket_bytes=1 << 20, trials=1)
        # 2 MiB total: 4/8/16 buckets would be under the 1 MiB floor
        assert t.viable(2 << 20) == (1, 2)
        # tiny totals leave only the monolithic schedule — chosen
        # without any trial bookkeeping
        assert t.choose("k", 1 << 10) == 1

    def test_config_env(self, monkeypatch):
        from horovod_tpu.common.config import Config

        monkeypatch.setenv("HOROVOD_OVERLAP", "1")
        monkeypatch.setenv("HOROVOD_OVERLAP_BUCKETS", "7")
        monkeypatch.setenv("HOROVOD_OVERLAP_MIN_BYTES", "4096")
        cfg = Config.from_env()
        assert cfg.overlap is True
        assert cfg.overlap_buckets == 7
        assert cfg.overlap_min_bytes == 4096
        assert overlap.default_buckets() in (7, 0)  # init state free

    def test_default_buckets_off_by_default(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_OVERLAP", raising=False)
        assert overlap.default_buckets() == 0


# ----------------------------------------- metrics + timeline estimate


class TestObservability:
    def test_schedule_publishes_metrics(self, hvd):
        from horovod_tpu.common.metrics import registry

        registry.reset()
        mesh = hvd_mod.mesh()
        rng = np.random.default_rng(14)
        t = _tree(rng, [(64,), (32,), (16,)])
        _shmap(
            mesh,
            lambda p: overlap.bucketed_allreduce(
                p, op=hvd_mod.Sum, n_buckets=2, min_bucket_bytes=0
            ),
        )(t)
        snap = registry.snapshot()
        assert snap["overlap.buckets"] == 2
        assert snap["overlap.bucket_bytes_total"] == (64 + 32 + 16) * 4
        assert snap["overlap.bucket_bytes_max"] >= snap[
            "overlap.bucket_bytes_min"
        ]

    def test_collective_overlap_stats_synthetic(self):
        """Exposed vs hidden on a hand-built trace: a 100us collective
        with 60us of concurrent compute on the same device pid is 60
        hidden / 40 exposed; a second, fully-exposed collective adds
        its whole duration to exposed."""
        from horovod_tpu.common.traced_timeline import (
            collective_overlap_stats,
        )

        events = [
            # device pid 7: collective [0, 100)
            {"ph": "X", "pid": 7, "tid": 1, "ts": 0, "dur": 100,
             "name": "all-reduce.1"},
            # concurrent compute [20, 80) on another row of pid 7
            {"ph": "X", "pid": 7, "tid": 2, "ts": 20, "dur": 60,
             "name": "fusion.42"},
            # fully exposed collective [200, 250)
            {"ph": "X", "pid": 7, "tid": 1, "ts": 200, "dur": 50,
             "name": "all-gather.3"},
            # async start half must be ignored
            {"ph": "X", "pid": 7, "tid": 1, "ts": 300, "dur": 10,
             "name": "all-reduce-start.9"},
        ]
        s = collective_overlap_stats(events)
        assert s["spans"] == 2
        assert s["collective_us"] == 150
        assert s["hidden_us"] == 60
        assert s["exposed_us"] == 90

    def test_container_rows_do_not_count_as_hiding_compute(self):
        """Profiler annotation rows ('Steps', 'XLA Modules', name
        scopes) span the whole step on the device pid; counting them
        as compute would report every collective 100% hidden for any
        schedule. They are excluded via thread_name metadata; real op
        rows still hide."""
        from horovod_tpu.common.traced_timeline import (
            collective_overlap_stats,
        )

        events = [
            {"ph": "M", "pid": 7, "tid": 9, "name": "thread_name",
             "args": {"name": "Steps"}},
            {"ph": "M", "pid": 7, "tid": 8, "name": "thread_name",
             "args": {"name": "XLA Modules"}},
            {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            # whole-step container spans blanket the timeline
            {"ph": "X", "pid": 7, "tid": 9, "ts": 0, "dur": 1000,
             "name": "train 0"},
            {"ph": "X", "pid": 7, "tid": 8, "ts": 0, "dur": 1000,
             "name": "jit_step"},
            # the collective, with 30us of REAL op compute concurrent
            {"ph": "X", "pid": 7, "tid": 2, "ts": 100, "dur": 100,
             "name": "all-reduce.5"},
            {"ph": "X", "pid": 7, "tid": 2, "ts": 150, "dur": 30,
             "name": "fusion.9"},
        ]
        s = collective_overlap_stats(events)
        assert s["spans"] == 1
        assert s["collective_us"] == 100
        assert s["hidden_us"] == 30  # only the real op row hides
        assert s["exposed_us"] == 70

    def test_traced_timeline_exports_overlap_counters(self, hvd,
                                                      tmp_path):
        """The chrome-trace export computes the exposed/hidden split,
        publishes overlap.* metrics, and appends counter events."""
        import gzip
        import json as _json
        import os

        from horovod_tpu.common.metrics import registry
        from horovod_tpu.common.traced_timeline import TracedTimeline

        registry.reset()
        tl = TracedTimeline(str(tmp_path / "tl.json"))
        # fabricate a profiler output instead of running one: the
        # export path only reads the trace.json.gz files
        d = os.path.join(
            tl.logdir, "plugins", "profile", "run1"
        )
        os.makedirs(d)
        trace = {
            "traceEvents": [
                {"ph": "X", "pid": 3, "tid": 1, "ts": 0, "dur": 100,
                 "name": "all-reduce.7"},
                {"ph": "X", "pid": 3, "tid": 2, "ts": 50, "dur": 100,
                 "name": "fusion.1"},
            ]
        }
        with gzip.open(
            os.path.join(d, "host.trace.json.gz"), "wt"
        ) as f:
            _json.dump(trace, f)
        tl._export_chrome_trace()
        snap = registry.snapshot()
        assert snap["overlap.collective_ms"] == pytest.approx(0.1)
        assert snap["overlap.hidden_collective_ms"] == pytest.approx(
            0.05
        )
        assert snap["overlap.exposed_collective_ms"] == pytest.approx(
            0.05
        )
        out = _json.load(open(tmp_path / "tl.json"))
        names = [e.get("name") for e in out["traceEvents"]]
        assert "hvd.exposed_collective_ms" in names
        assert "hvd.hidden_collective_ms" in names
