#!/usr/bin/env bash
# Round-4 chip work, part b. chipwork_r04.sh's lse smoke used the CPU
# test tolerance (2e-3) against an fp32 dense oracle; on the chip the
# MXU's default-precision matmul carries bf16-epsilon (~7.8e-3) input
# rounding, so BOTH layouts "failed" with identical ~6.6e-3 maxerr —
# i.e. they agree with each other exactly and differ from the oracle by
# rounding. That misread exported BENCH_FLASH=0 and would have run every
# LM bench with dense attention. This part re-validates with an
# on-chip-calibrated gate (cross-layout agreement tight at 1e-5, oracle
# agreement at 2e-2 like tests/test_flash_attention.py:85's bf16 case)
# and then runs the remaining captures from the r04 plan.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

# 0. let the in-flight resnet50 capture (launched by part a) finish,
#    then finalize its artifact the way cap() would have
while pgrep -f "python bench.py" >/dev/null 2>&1; do sleep 30; done
if [ -f bench_results/resnet50_${R}.json.tmp ]; then
  if grep -qE '^\{' bench_results/resnet50_${R}.json.tmp; then
    grep -E '^\{' bench_results/resnet50_${R}.json.tmp > bench_results/resnet50_${R}.json
    rm -f bench_results/resnet50_${R}.json.tmp bench_results/resnet50_${R}.err
    echo "=== finalized resnet50 from part a:" >&2
    cat bench_results/resnet50_${R}.json >&2
  fi
fi

cap() {   # cap <name> <cmd...>  -> bench_results/<name>_r04.json
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  for attempt in 1 2; do
    echo "=== $name (attempt $attempt) $(date -u +%H:%M)" >&2
    "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
    if grep -qE '^\{' "$out.tmp"; then
      grep -E '^\{' "$out.tmp" > "$out"
      rm -f "$out.tmp" "bench_results/${name}_${R}.err"
      cat "$out" >&2
      return 0
    fi
    rm -f "$out.tmp"
    sleep 120
  done
  echo "FAILED $name (see bench_results/${name}_${R}.err)" >&2
  return 1
}

# 1. flash lse re-validation with the calibrated gate
python - > bench_results/flash_lse_smoke2_${R}.txt 2>&1 <<'EOF'
import os
import numpy as np
import jax, jax.numpy as jnp

def dense(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)

rng = np.random.default_rng(0)
b, t, h, d = 2, 256, 4, 64
q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32) for _ in range(3))

from horovod_tpu.ops import flash_attention as fa

rq, rk, rv = jax.grad(
    lambda q, k, v: dense(q, k, v, True).astype(jnp.float32).sum(),
    argnums=(0, 1, 2))(q, k, v)

grads = {}
ok_oracle = {}
for layout, env in (("broadcast", "1"), ("compact", "")):
    os.environ["HOROVOD_FLASH_LSE_BROADCAST"] = env
    try:
        def loss(q, k, v):
            return fa.flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        grads[layout] = (np.asarray(gq), np.asarray(gk), np.asarray(gv))
        ok = True
        for name, a, bb in (("dq", gq, rq), ("dk", gk, rk), ("dv", gv, rv)):
            err = float(jnp.max(jnp.abs(a - bb)))
            print(layout, name, "maxerr-vs-fp32-oracle", err)
            ok = ok and err < 2e-2   # bf16-epsilon MXU rounding allowance
    except Exception as e:
        print(layout, "EXCEPTION", repr(e)[:300])
        ok = False
    ok_oracle[layout] = ok

# the real layout gate: both interchange layouts must agree tightly
agree = False
if "compact" in grads and "broadcast" in grads:
    errs = [float(np.abs(a - b).max())
            for a, b in zip(grads["compact"], grads["broadcast"])]
    print("cross-layout maxerr dq/dk/dv:", errs)
    agree = max(errs) < 1e-5

print("RESULT compact=%s broadcast=%s agree=%s" % (
    "PASS" if ok_oracle.get("compact") else "FAIL",
    "PASS" if ok_oracle.get("broadcast") else "FAIL",
    "PASS" if agree else "FAIL"))
if ok_oracle.get("compact") and agree:
    print("FLASH LSE LAYOUTS PASS ON TPU")
EOF
tail -3 bench_results/flash_lse_smoke2_${R}.txt >&2
if ! grep -q "FLASH LSE LAYOUTS PASS ON TPU" bench_results/flash_lse_smoke2_${R}.txt; then
  if grep -q "broadcast=PASS" bench_results/flash_lse_smoke2_${R}.txt; then
    echo "compact lse FAILED calibrated gate; pinning broadcast" >&2
    export HOROVOD_FLASH_LSE_BROADCAST=1
  else
    echo "flash failed calibrated gate — LM benches fall back to dense" >&2
    export BENCH_FLASH=0
  fi
fi

# 2. space_to_depth stem A/B (resnet50 default landed in part a)
cap resnet50_s2d       env BENCH_INNER=1 BENCH_STEM=space_to_depth python bench.py

# 3. GPT-2 medium: fresh default; flash block sweep; no-remat big batch
cap gpt2_medium        env BENCH_MODEL=gpt2_medium python bench_lm.py
for blk in 64 256 512; do
  cap gpt2_blk${blk}   env BENCH_MODEL=gpt2_medium BENCH_FLASH_BLOCK=${blk} python bench_lm.py
done
cap gpt2_noremat_b16   env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
cap gpt2_seq1024       env BENCH_MODEL=gpt2_medium BENCH_BATCH=4 BENCH_SEQ=1024 python bench_lm.py

# 4. BERT-large: fresh default + no-remat big batch
cap bert_large         env BENCH_MODEL=bert_large python bench_lm.py
cap bert_noremat_b16   env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py

# 5. ViT-B/16 (config #5 — round-3 capture died in the outage)
cap vit_b16            env BENCH_INNER=1 BENCH_MODEL=vit_b16 python bench.py

# 6. allreduce busbw on the real chip (world=1: single-device round trip)
cap allreduce          python bench_allreduce.py

# 7. batch-512 confirm (HBM-bound => flat) for the roofline note
cap resnet50_b512      env BENCH_INNER=1 BENCH_BATCH=512 python bench.py

echo "=== chipwork_r04b complete $(date -u +%H:%M)" >&2
