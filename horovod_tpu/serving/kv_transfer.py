"""Streamed KV-page transfer: the disaggregated fleet's inter-slice wire.

Role-split serving (docs/serving.md "disaggregated fleet"): a PREFILL
worker runs the chunked bucket-cached prefill into its local page pool,
then ships the finished pages to a DECODE worker, where the request
enters continuous batching with its page table rebuilt by pointer
(`paged_kv.py` ingest-attach). The long-prompt admission therefore
never runs on the worker holding in-flight decode streams — the
TTFT-vs-TPOT interference the Gemma-on-TPU serving comparison removes
by construction (PAPERS.md arXiv 2605.25645).

Wire format (``HOROVOD_SERVE_KV_WIRE``): pages travel as block-scaled
int8 by default — the PR 2 ``int8_block`` kernels, EQuARX-style
placement (PAPERS.md arXiv 2506.17615) — at ~¼ the bytes of the pool
dtype; ``fp32`` is the lossless pool-dtype passthrough (bit-identical
decode to a unified worker — the parity gate tests/test_kv_transfer.py
holds), ``bf16`` the middle ground. Quantization blocks never straddle
a page (the block size divides the per-page element count), and the
tail page's pad rows are zeroed BEFORE quantization — zeros never
raise a block's absmax, so pad positions are excluded from the scales
by construction.

Transport: stdlib HTTP in the MetricsServer mold (no new
dependencies). The decode worker runs a :class:`KVTransferServer` on
``HOROVOD_SERVE_TRANSFER_PORT`` (announced through the capacity
blobs):

* ``POST /kv/reserve`` — capacity reservation BEFORE the sender spends
  a prefill: pages are promised against the decode worker's admission
  headroom with a TTL, so a crashed sender cannot leak them.
* ``POST /kv/ingest`` — the framed page payload; admits the request
  into the decode batcher and replies its id immediately (idempotent
  by sender request id, so a retried stream cannot double-admit).
* ``GET /kv/result`` — long-poll for the finished decode.

The sender side (:class:`TransferCoordinator`, driven by the prefill
batcher) picks the least-loaded announced decode worker, reserves,
streams under a ``RetryPolicy`` with the ``serve.kv_transfer`` chaos
site fired on every attempt, and on exhaustion FALLS BACK to decoding
locally in unified mode (``serve.transfer_fallbacks``) — a transfer
outage degrades to PR 11 behavior, it never errors the request.
"""

from __future__ import annotations

import functools
import json
import struct
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common import tracing as _tracing
from ..common.logging import get_logger
from ..common.metrics import registry as _metrics
from ..common.retry import RetryPolicy
from ..testing import chaos as _chaos

_log = get_logger("serve.kv_transfer")

CHAOS_SITE = "serve.kv_transfer"
MIGRATE_CHAOS_SITE = "serve.migrate"
WIRE_FORMATS = ("fp32", "bf16", "int8")
# int8 block granularity cap: clamped DOWN to the per-page element
# count so a scale never spans two pages (the per-page quantize
# contract); pages bigger than this use the largest divisor <= cap.
DEFAULT_WIRE_BLOCK = 512
DEFAULT_RESERVATION_TTL_S = 30.0
DEFAULT_RESULT_TIMEOUT_S = 300.0


def wire_block_size(page_elems: int, cap: int = DEFAULT_WIRE_BLOCK) -> int:
    """Largest block size <= ``cap`` that divides the per-page element
    count — blocks tile pages exactly, so no scale mixes two pages'
    dynamic ranges (and none mixes k with v or layer with layer: each
    leaf is quantized separately)."""
    if page_elems <= cap:
        return page_elems
    for b in range(cap, 0, -1):
        if page_elems % b == 0:
            return b
    return 1


def worker_role(ann: dict) -> str:
    """The role a capacity announcement claims. Blobs from OLD workers
    (rolling upgrade) carry no ``role`` field at all — they are unified
    workers and MUST stay routable, so missing or unrecognized values
    parse as ``"unified"`` (the Router regression test)."""
    role = ann.get("role", "unified")
    return role if role in ("prefill", "decode", "unified") else "unified"


# ------------------------------------------------------------ pack/unpack


def pack_pages(
    engine, kept, length: int, *, wire: str = "int8", seed: int = 0,
) -> Tuple[dict, bytes]:
    """Serialize a detached slot's pages for the wire. Returns
    ``(meta, blob)``: ``meta`` is the JSON-able frame header (wire
    format, page geometry, per-leaf segment table), ``blob`` the
    concatenated per-leaf payloads (int8 values + float32 block scales,
    or raw bf16/pool-dtype bytes).

    The device gather (``engine.extract_pages``) must already have
    happened on the scheduler thread when this runs off-thread — pass
    its result via ``raw=``; quantization itself is thread-safe (fresh
    host arrays through jitted kernels)."""
    return pack_raw_pages(
        engine.extract_pages(kept, length),
        [lp for lp, _ in kept], length,
        page_tokens=engine.manager.page_tokens, wire=wire, seed=seed,
    )


def pack_raw_pages(
    raw: List[np.ndarray], logical: List[int], length: int, *,
    page_tokens: int, wire: str = "int8", seed: int = 0,
) -> Tuple[dict, bytes]:
    if wire not in WIRE_FORMATS:
        raise ValueError(f"wire must be one of {WIRE_FORMATS}, got {wire!r}")
    segments = []
    parts: List[bytes] = []
    for arr in raw:
        seg: Dict[str, object] = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
        if wire == "int8":
            from ..ops.pallas_kernels import int8_block_quantize

            page_elems = int(np.prod(arr.shape[1:]))
            block = wire_block_size(page_elems)
            vals, scales = int8_block_quantize(
                arr.astype(np.float32), block_size=block, seed=seed
            )
            vals = np.asarray(vals)
            scales = np.asarray(scales, np.float32)
            seg["block"] = block
            seg["nscales"] = int(scales.size)
            parts.append(vals.tobytes())
            parts.append(scales.tobytes())
        elif wire == "bf16":
            import ml_dtypes

            parts.append(arr.astype(ml_dtypes.bfloat16).tobytes())
        else:  # fp32: lossless pool-dtype passthrough
            parts.append(arr.tobytes())
        segments.append(seg)
    meta = {
        "wire": wire,
        "length": int(length),
        "page_tokens": int(page_tokens),
        "pages": [int(lp) for lp in logical],
        "segments": segments,
    }
    return meta, b"".join(parts)


def unpack_pages(meta: dict, blob: bytes) -> List[np.ndarray]:
    """Inverse of :func:`pack_raw_pages`: per-leaf page payloads in the
    pool dtype, pad rows exact zeros (zeros quantize and dequantize to
    zeros — the pad-exclusion contract round-trips)."""
    wire = meta["wire"]
    out: List[np.ndarray] = []
    off = 0
    for seg in meta["segments"]:
        shape = tuple(seg["shape"])
        dtype = np.dtype(seg["dtype"])
        n = int(np.prod(shape))
        if wire == "int8":
            from ..ops.pallas_kernels import int8_block_dequantize

            vals = np.frombuffer(
                blob, np.int8, count=n, offset=off
            ).reshape(shape)
            off += n
            nscales = int(seg["nscales"])
            scales = np.frombuffer(blob, np.float32, count=nscales,
                                   offset=off)
            off += nscales * 4
            arr = np.asarray(int8_block_dequantize(
                vals, scales, block_size=int(seg["block"]),
            )).astype(dtype)
        elif wire == "bf16":
            import ml_dtypes

            arr = np.frombuffer(
                blob, ml_dtypes.bfloat16, count=n, offset=off
            ).reshape(shape).astype(dtype)
            off += 2 * n
        else:
            arr = np.frombuffer(
                blob, dtype, count=n, offset=off
            ).reshape(shape)
            off += n * dtype.itemsize
        out.append(arr)
    return out


def frame(meta: dict, blob: bytes) -> bytes:
    """One HTTP body: 4-byte big-endian header length + JSON header +
    raw payload."""
    head = json.dumps(meta).encode()
    return struct.pack(">I", len(head)) + head + blob


def unframe(body: bytes) -> Tuple[dict, bytes]:
    if len(body) < 4:
        raise ValueError("transfer frame too short")
    (hlen,) = struct.unpack(">I", body[:4])
    if len(body) < 4 + hlen:
        raise ValueError("transfer frame truncated")
    meta = json.loads(body[4:4 + hlen].decode())
    return meta, body[4 + hlen:]


# -------------------------------------------------------- receiver (decode)


class KVTransferServer:
    """Decode-worker ingest endpoint: stdlib ThreadingHTTPServer (the
    MetricsServer mold — no new dependencies) owning the reservation
    ledger and the rid → request table. The HTTP threads only parse,
    dequantize and enqueue — every device write happens on the
    batcher's scheduler thread (ingest admission), preserving the
    single-consumer contract the donated carry depends on."""

    def __init__(
        self,
        batcher,
        port: int = 0,
        addr: str = "0.0.0.0",
        reservation_ttl_s: float = DEFAULT_RESERVATION_TTL_S,
    ) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.batcher = batcher
        self._ttl = float(reservation_ttl_s)
        self._lock = threading.Lock()
        self._reservations: Dict[str, Tuple[int, float]] = {}
        self._by_request: Dict[str, str] = {}  # sender request id -> rid
        self._results: Dict[str, object] = {}  # rid -> batcher Request
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                _log.debug("kv_transfer http " + fmt, *args)

            def _json(self, code, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                recv_ts = time.time()
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                path = self.path.split("?", 1)[0]
                if path == "/kv/reserve":
                    code, obj = outer._handle_reserve(body)
                elif path == "/kv/ingest":
                    code, obj = outer._handle_ingest(body)
                elif path == "/kv/migrate":
                    code, obj = outer._handle_migrate(body)
                else:
                    code, obj = 404, {"error": "not found"}
                if isinstance(obj, dict):
                    # clock-stamp echo: every kv reply is an NTP edge
                    # for the trace assembler (tracing.tag_hop_fields)
                    obj.update(_tracing.json_stamps(recv_ts))
                return self._json(code, obj)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/kv/result":
                    params = dict(
                        kv.split("=", 1)
                        for kv in query.split("&") if "=" in kv
                    )
                    return self._json(*outer._handle_result(params))
                return self._json(404, {"error": "not found"})

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((addr, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="hvd-kv-transfer", daemon=True,
            )
            self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------ reservations

    def reserved_pages(self) -> int:
        """Unexpired reserved pages — debited from the announced
        capacity so two senders can't both be promised the same
        headroom between announce refreshes."""
        now = time.monotonic()
        with self._lock:
            for rid in [
                r for r, (_, exp) in self._reservations.items()
                if exp < now
            ]:
                del self._reservations[rid]
            return sum(p for p, _ in self._reservations.values())

    def _handle_reserve(self, body: bytes):
        try:
            payload = json.loads(body or b"{}")
            pages = int(payload["pages"])
        except (ValueError, KeyError):
            return 400, {"error": "bad reserve request"}
        span = _tracing.start_span(
            "kv.reserve",
            _tracing.TraceContext.from_dict(payload.get("trace")),
            pages=pages,
        )
        if self.batcher.draining:
            if span is not None:
                span.end(outcome="draining")
            return 503, {"error": "draining"}
        mgr = self.batcher.engine.manager
        headroom = mgr.admission_headroom() - self.reserved_pages()
        if pages > headroom:
            _metrics.counter("serve.transfer_reserve_denied")
            if span is not None:
                span.end(outcome="denied", free=headroom)
            return 503, {"error": "no decode capacity", "free": headroom}
        rid = uuid.uuid4().hex
        with self._lock:
            self._reservations[rid] = (
                pages, time.monotonic() + self._ttl
            )
        _metrics.counter("serve.transfer_reservations")
        if span is not None:
            span.end(outcome="ok")
        return 200, {"reservation": rid, "pages": pages}

    # ----------------------------------------------------------------- ingest

    def _handle_ingest(self, body: bytes):
        try:
            meta, blob = unframe(body)
        except (ValueError, json.JSONDecodeError) as e:
            return 400, {"error": f"bad transfer frame: {e}"}
        request_id = str(meta.get("request_id", ""))
        tctx = _tracing.TraceContext.from_dict(meta.get("trace"))
        span = _tracing.start_span(
            "kv.ingest", tctx, pages=len(meta.get("pages", ())),
        )
        with self._lock:
            rid = self._by_request.get(request_id)
            if rid is not None:
                # retried stream after a mid-flight reset: the first
                # frame already admitted — idempotent, never twice
                if span is not None:
                    span.end(outcome="duplicate")
                return 200, {"rid": rid, "duplicate": True}
            if meta.get("reservation"):
                self._reservations.pop(meta["reservation"], None)
        if self.batcher.draining:
            if span is not None:
                span.end(outcome="draining")
            return 503, {"error": "draining"}
        try:
            arrays = unpack_pages(meta, blob)
            req = self.batcher.submit_ingested(
                prompt=meta.get("prompt", ()),
                first_token=int(meta["first_token"]),
                max_new_tokens=int(meta["max_new_tokens"]),
                deadline_ms=meta.get("deadline_ms"),
                logical=meta["pages"],
                arrays=arrays,
                length=int(meta["length"]),
                hashes=[bytes.fromhex(h) for h in meta.get("hashes", ())],
                temperature=float(meta.get("temperature", 0.0)),
                top_k=int(meta.get("top_k", 0)),
                seed=meta.get("seed"),
                trace=span.ctx if span is not None else tctx,
            )
        except Exception as e:  # Rejected, malformed frames
            _log.warning("kv transfer ingest rejected: %s", e)
            if span is not None:
                span.end(outcome="error", error=str(e))
            return 503, {"error": str(e)}
        rid = uuid.uuid4().hex
        with self._lock:
            if request_id:
                self._by_request[request_id] = rid
            self._results[rid] = req
        _metrics.counter("serve.kv_transfer_bytes_in", len(body))
        _metrics.counter("serve.kv_transfer_pages_in", len(meta["pages"]))
        if span is not None:
            span.end(outcome="ok", bytes=len(body))
        return 200, {"rid": rid}

    def _handle_migrate(self, body: bytes):
        """The ``migrate`` frame beside ``ingest``: a live-migrated
        in-flight sequence — pages AND its full generated-token history
        AND armed sampling state — resuming mid-decode with no
        re-prefill. Same idempotency ledger as ingest (a retried stream
        after a mid-flight reset admits exactly once)."""
        try:
            meta, blob = unframe(body)
        except (ValueError, json.JSONDecodeError) as e:
            return 400, {"error": f"bad migrate frame: {e}"}
        request_id = str(meta.get("request_id", ""))
        tctx = _tracing.TraceContext.from_dict(meta.get("trace"))
        span = _tracing.start_span(
            "kv.migrate", tctx, pages=len(meta.get("pages", ())),
        )
        with self._lock:
            rid = self._by_request.get(request_id)
            if rid is not None:
                if span is not None:
                    span.end(outcome="duplicate")
                return 200, {"rid": rid, "duplicate": True}
            if meta.get("reservation"):
                self._reservations.pop(meta["reservation"], None)
        if self.batcher.draining:
            if span is not None:
                span.end(outcome="draining")
            return 503, {"error": "draining"}
        try:
            arrays = unpack_pages(meta, blob)
            req = self.batcher.submit_migrated(
                prompt=meta.get("prompt", ()),
                tokens=meta["tokens"],
                max_new_tokens=int(meta["max_new_tokens"]),
                deadline_ms=meta.get("deadline_ms"),
                logical=meta["pages"],
                arrays=arrays,
                length=int(meta["length"]),
                sample=meta.get("sample"),
                trace=span.ctx if span is not None else tctx,
            )
        except Exception as e:  # Rejected, malformed frames
            _log.warning("kv migrate rejected: %s", e)
            if span is not None:
                span.end(outcome="error", error=str(e))
            return 503, {"error": str(e)}
        rid = uuid.uuid4().hex
        with self._lock:
            if request_id:
                self._by_request[request_id] = rid
            self._results[rid] = req
        _metrics.counter("serve.kv_transfer_bytes_in", len(body))
        _metrics.counter("serve.kv_transfer_pages_in", len(meta["pages"]))
        _metrics.counter("serve.migrations_in")
        if span is not None:
            span.end(outcome="ok", bytes=len(body))
        return 200, {"rid": rid}

    def _handle_result(self, params: dict):
        rid = params.get("rid", "")
        with self._lock:
            req = self._results.get(rid)
        if req is None:
            return 404, {"error": f"unknown rid {rid!r}"}
        timeout = float(params.get("timeout", 30.0))
        if not req.wait(timeout=timeout):
            return 202, {"done": False}
        with self._lock:
            self._results.pop(rid, None)
            for k, v in list(self._by_request.items()):
                if v == rid:
                    del self._by_request[k]
        return 200, dict(req.result(), done=True)


# --------------------------------------------------------- sender (prefill)


class TransferCoordinator:
    """Prefill-worker side: decode-target selection, capacity
    reservation BEFORE the prefill runs, and the retried page stream.
    Driven by the batcher's scheduler thread (reserve, page extraction)
    plus one short-lived handoff thread per streamed request (the
    quantize + HTTP leg — no device state crosses the boundary)."""

    def __init__(
        self,
        engine,
        *,
        client=None,
        client_factory=None,
        wire: Optional[str] = None,
        retry: Optional[RetryPolicy] = None,
        reserve_timeout_s: float = 5.0,
        result_timeout_s: float = DEFAULT_RESULT_TIMEOUT_S,
    ) -> None:
        from ..common import basics

        cfg = basics.live_config()
        self.engine = engine
        self.wire = cfg.serve_kv_wire if wire is None else str(wire)
        if self.wire not in WIRE_FORMATS:
            raise ValueError(
                f"kv wire must be one of {WIRE_FORMATS}, got {self.wire!r}"
            )
        self._client = client
        self._client_factory = client_factory
        self._retry = retry or RetryPolicy.from_env(CHAOS_SITE)
        self._reserve_timeout = float(reserve_timeout_s)
        self._result_timeout = float(result_timeout_s)
        self._lock = threading.Lock()
        # local in-flight debits per decode rank (reserved pages not
        # yet reflected in the target's announcements) — the Router's
        # debit idea applied to the transfer plane
        self._debits: Dict[int, int] = {}

    # ------------------------------------------------------------- targets

    def _resolve_client(self):
        if self._client is None and self._client_factory is not None:
            self._client = self._client_factory()
        return self._client

    def decode_targets(self, exclude=(), roles=("decode",)) -> List[dict]:
        """Announced transfer-capable workers of the wanted ``roles``,
        least-loaded first (announced page headroom minus local
        reservation debits). Prefill handoffs want pure decode workers;
        live migration also accepts paged unified workers (they run a
        transfer server too) — a single-role fleet can still evacuate."""
        from .frontend import read_announcements

        client = self._resolve_client()
        if client is None:
            return []
        try:
            anns = read_announcements(client)
        except (OSError, RuntimeError):
            return []
        with self._lock:
            debits = dict(self._debits)

        def load(item):
            rank, ann = item
            free = int(ann.get("free_pages", ann.get("free_slots", 0)))
            return (-(free - debits.get(rank, 0)), rank)

        return [
            dict(ann, rank=rank)
            for rank, ann in sorted(anns.items(), key=load)
            if worker_role(ann) in roles
            and not ann.get("draining")
            and ann.get("transfer_port")
            and rank not in exclude
        ]

    # ------------------------------------------------------------- reserve

    def reserve(
        self, pages: int, roles=("decode",), trace=None,
    ) -> Optional[dict]:
        """Reserve ``pages`` on the best decode worker, failing over
        across candidates in-call; None when NO decode capacity exists
        anywhere — the sender's cue to take the unified/local path.
        ``trace`` (an ``Optional[TraceContext]``) rides the reserve
        body so the receiver's admission decision lands in the same
        trace, and the reply's clock-stamp echo becomes an NTP edge."""
        import urllib.error
        import urllib.request

        span = _tracing.start_span("kv.reserve", trace, pages=int(pages))
        failed: set = set()
        for _ in range(4):
            targets = self.decode_targets(exclude=failed, roles=roles)
            if not targets:
                if span is not None:
                    span.end(outcome="no_target")
                return None
            ann = targets[0]
            url = (
                f"http://{ann.get('addr', '127.0.0.1')}"
                f":{ann['transfer_port']}/kv/reserve"
            )
            payload: dict = {"pages": int(pages)}
            if span is not None:
                payload["trace"] = span.ctx.to_dict()
            body = json.dumps(payload).encode()
            try:
                req = urllib.request.Request(
                    url, data=body, method="POST",
                    headers={"Content-Type": "application/json"},
                )
                t_send = time.time()
                with urllib.request.urlopen(
                    req, timeout=self._reserve_timeout
                ) as resp:
                    out = json.loads(resp.read().decode())
                _tracing.tag_hop_fields(span, t_send, time.time(), out)
            except (OSError, ValueError, urllib.error.HTTPError) as e:
                _log.debug(
                    "reserve on rank %s failed: %s", ann.get("rank"), e
                )
                if span is not None:
                    span.annotate(f"rank{ann.get('rank')}:{e}")
                failed.add(ann["rank"])
                continue
            with self._lock:
                self._debits[ann["rank"]] = (
                    self._debits.get(ann["rank"], 0) + int(pages)
                )
            if span is not None:
                span.end(outcome="ok", rank=int(ann["rank"]))
            return {
                "rank": ann["rank"],
                "addr": ann.get("addr", "127.0.0.1"),
                "port": int(ann["transfer_port"]),
                "rid": out["reservation"],
                "pages": int(pages),
            }
        if span is not None:
            span.end(outcome="exhausted")
        return None

    def _credit(self, reservation: dict) -> None:
        with self._lock:
            rank = reservation["rank"]
            left = self._debits.get(rank, 0) - reservation["pages"]
            if left > 0:
                self._debits[rank] = left
            else:
                self._debits.pop(rank, None)

    # -------------------------------------------------------------- handoff

    def start_handoff(
        self, batcher, req, kept, length: int, reservation: dict,
    ) -> None:
        """Scheduler-thread entry: only the DEVICE-side page gather
        runs here (``engine.gather_pages`` — an async indexed read into
        fresh buffers nothing the executables' donated carry can
        invalidate later); the blocking host materialization — one
        batched ``device_get`` over every leaf — happens on the handoff
        thread (``_stream`` → ``engine.pages_to_host``), so an
        in-flight transfer never stalls the scheduler's decode
        admission rounds."""
        raw = self.engine.gather_pages(kept)
        threading.Thread(
            target=self._stream,
            args=(batcher, req, kept, length, reservation, raw),
            name=f"hvd-kv-handoff-{req.id}",
            daemon=True,
        ).start()

    def _post(self, url: str, body: bytes, timeout: float,
              site: str = CHAOS_SITE) -> dict:
        """One chaos-instrumented HTTP attempt (the RetryPolicy's unit
        of work): 5xx and transport faults raise — retryable; 4xx is
        the frame's own fault and surfaces immediately."""
        import urllib.error
        import urllib.request

        try:
            _chaos.inject(site)
        except _chaos.InjectedServerError:
            raise  # retryable=True already
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/octet-stream"},
        )
        try:
            t_send = time.time()
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                out = json.loads(resp.read().decode())
            # per-attempt NTP stamps onto the stream/migrate span this
            # handoff thread runs under (tracing.active); the last
            # successful attempt's edge wins
            _tracing.tag_hop_fields(
                _tracing.current(), t_send, time.time(), out
            )
            return out
        except urllib.error.HTTPError as e:
            if e.code == 429 or 500 <= e.code <= 599:
                raise OSError(f"transfer target HTTP {e.code}") from e
            raise RuntimeError(
                f"transfer rejected (HTTP {e.code})"
            ) from e

    def _stream(self, batcher, req, kept, length, reservation, raw):
        base = f"http://{reservation['addr']}:{reservation['port']}"
        t0 = time.perf_counter()
        # the handoff thread runs UNDER the stream span (tracing.active)
        # so RetryPolicy annotations and the _post hop stamps land on it;
        # the receiver parents its kv.ingest span off meta["trace"]
        span = _tracing.start_span(
            "kv.stream", getattr(req, "trace", None),
            rank=int(reservation.get("rank", -1)),
            pages=len(kept), wire=self.wire,
        )
        try:
            with _tracing.active(span):
                # blocking half of the page extraction: one batched
                # device_get + tail zeroing, OFF the scheduler thread
                raw = self.engine.pages_to_host(raw, kept, length)
                meta, blob = pack_raw_pages(
                    raw, [lp for lp, _ in kept], length,
                    page_tokens=self.engine.manager.page_tokens,
                    wire=self.wire, seed=req.id,
                )
                from .paged_kv import page_hashes

                remaining_ms = None
                if req.deadline_ts is not None:
                    remaining_ms = max(
                        (req.deadline_ts - time.monotonic()) * 1e3, 1.0
                    )
                meta.update(
                    request_id=f"{id(self)}-{req.id}",
                    reservation=reservation["rid"],
                    prompt=[int(t) for t in req.prompt],
                    first_token=int(req.out_tokens[-1]),
                    max_new_tokens=int(req.max_new_tokens),
                    deadline_ms=remaining_ms,
                    # sampling knobs ride the wire; the seed is resolved
                    # HERE (sender request id when unpinned) so the
                    # decode worker reproduces what a local decode
                    # would have drawn
                    temperature=float(req.temperature),
                    top_k=int(req.top_k),
                    seed=int(req.id if req.seed is None else req.seed),
                    hashes=[
                        h.hex() for h in page_hashes(
                            req.prompt, self.engine.manager.page_tokens
                        )
                    ],
                )
                if span is not None:
                    meta["trace"] = span.ctx.to_dict()
                body = frame(meta, blob)
                out = self._retry.call(
                    self._post, base + "/kv/ingest", body,
                    self._retry.attempt_timeout_s, peer=base,
                )
            transfer_ms = (time.perf_counter() - t0) * 1e3
            _metrics.counter("serve.kv_transfer_bytes", len(body))
            _metrics.counter("serve.kv_transfer_pages", len(kept))
            _metrics.counter("serve.kv_transfer_ms", transfer_ms)
            _metrics.counter("serve.transfers")
            if span is not None:
                # the span covers pack+stream, not the remote decode —
                # the receiver's own spans pick the story up from here
                span.end(
                    outcome="ok", bytes=len(body),
                    transfer_ms=round(transfer_ms, 3),
                )
            result = self._await_result(base, out["rid"], req)
        except Exception as e:  # noqa: BLE001 — any wire failure falls back
            _log.warning(
                "kv transfer of request %d to rank %s failed (%s); "
                "falling back to local decode", req.id,
                reservation.get("rank"), e,
            )
            if span is not None:
                span.end(
                    outcome="fallback",
                    error=f"{type(e).__name__}: {e}",
                )
            self._credit(reservation)
            batcher.requeue_fallback(req, kept, length)
            return
        self._credit(reservation)
        if result.get("status") not in ("done", "deadline"):
            _log.warning(
                "decode worker returned status %r for request %d; "
                "falling back to local decode",
                result.get("status"), req.id,
            )
            batcher.requeue_fallback(req, kept, length)
            return
        # remote decode finished: the local page holds are no longer
        # needed (the prefix index may still pin published pages)
        self.engine.manager.release_kept(kept)
        batcher.complete_handoff(req, result)

    def _await_result(self, base: str, rid: str, req) -> dict:
        """Long-poll the decode result. Idempotent by construction, so
        transport faults simply re-poll until the coordinator-level
        deadline."""
        import urllib.request

        deadline = time.monotonic() + self._result_timeout
        poll = f"{base}/kv/result?rid={rid}&timeout=30"
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(poll, timeout=45) as resp:
                    out = json.loads(resp.read().decode())
            except (OSError, ValueError) as e:
                last = e
                time.sleep(0.2)
                continue
            if out.get("done"):
                return out
        raise TimeoutError(
            f"decode result for rid {rid} never arrived: {last}"
        )

    # ------------------------------------------------------------ migration

    def migrate(self, batcher, rec: dict) -> bool:
        """Live-migrate one exported in-flight sequence (a
        ``batcher.export_inflight`` record: request + detached pages +
        armed sampling snapshot) to a reserved peer. Scheduler/drain
        thread entry — only the async device gather runs here; the
        host materialization and HTTP leg ride a handoff thread. No
        capacity anywhere → the request comes home for a local decode
        (``requeue_fallback``) and False is returned."""
        req, kept, length = rec["req"], rec["kept"], rec["length"]
        reservation = self.reserve(
            len(kept), roles=("decode", "unified"),
            trace=getattr(req, "trace", None),
        )
        if reservation is None:
            batcher.requeue_fallback(req, kept, length)
            return False
        raw = self.engine.gather_pages(kept)
        threading.Thread(
            target=self._stream_migrate,
            args=(batcher, rec, reservation, raw),
            name=f"hvd-kv-migrate-{req.id}",
            daemon=True,
        ).start()
        return True

    def _stream_migrate(self, batcher, rec, reservation, raw):
        req, kept, length = rec["req"], rec["kept"], rec["length"]
        base = f"http://{reservation['addr']}:{reservation['port']}"
        t0 = time.perf_counter()
        span = _tracing.start_span(
            "kv.migrate", getattr(req, "trace", None),
            rank=int(reservation.get("rank", -1)),
            pages=len(kept), wire=self.wire,
            tokens=len(req.out_tokens),
        )
        try:
            with _tracing.active(span):
                raw = self.engine.pages_to_host(raw, kept, length)
                meta, blob = pack_raw_pages(
                    raw, [lp for lp, _ in kept], length,
                    page_tokens=self.engine.manager.page_tokens,
                    wire=self.wire, seed=req.id,
                )
                remaining_ms = None
                if req.deadline_ts is not None:
                    remaining_ms = max(
                        (req.deadline_ts - time.monotonic()) * 1e3, 1.0
                    )
                meta.update(
                    request_id=f"{id(self)}-mig-{req.id}",
                    reservation=reservation["rid"],
                    prompt=[int(t) for t in req.prompt],
                    # the FULL generated history (vs ingest's
                    # first_token): the receiver seeds out_tokens with
                    # it and continues mid-decode — no token is ever
                    # re-decoded
                    tokens=[int(t) for t in req.out_tokens],
                    max_new_tokens=int(req.max_new_tokens),
                    deadline_ms=remaining_ms,
                    sample=rec.get("sample"),
                )
                if span is not None:
                    meta["trace"] = span.ctx.to_dict()
                body = frame(meta, blob)
                out = self._retry.call(
                    functools.partial(
                        self._post, site=MIGRATE_CHAOS_SITE
                    ),
                    base + "/kv/migrate", body,
                    self._retry.attempt_timeout_s, peer=base,
                )
            _metrics.counter("serve.kv_transfer_bytes", len(body))
            _metrics.counter("serve.kv_transfer_pages", len(kept))
            _metrics.counter("serve.migrations")
            migration_ms = (time.perf_counter() - t0) * 1e3
            _metrics.counter("serve.migration_ms", migration_ms)
            if span is not None:
                span.end(
                    outcome="ok", bytes=len(body),
                    migration_ms=round(migration_ms, 3),
                )
            result = self._await_result(base, out["rid"], req)
        except Exception as e:  # noqa: BLE001 — any wire failure falls back
            _log.warning(
                "live migration of request %d to rank %s failed (%s); "
                "falling back to local decode", req.id,
                reservation.get("rank"), e,
            )
            if span is not None:
                span.end(
                    outcome="fallback",
                    error=f"{type(e).__name__}: {e}",
                )
            self._credit(reservation)
            batcher.requeue_fallback(req, kept, length)
            return
        self._credit(reservation)
        if result.get("status") not in ("done", "deadline"):
            _log.warning(
                "migration target returned status %r for request %d; "
                "falling back to local decode",
                result.get("status"), req.id,
            )
            batcher.requeue_fallback(req, kept, length)
            return
        self.engine.manager.release_kept(kept)
        batcher.complete_handoff(req, result)
