"""Native (C++) runtime layer tests.

The reference's native core is tested only end-to-end through Python
(SURVEY.md §4.6 — a gap); here each native component gets differential
tests against its pure-Python twin, which also keeps the fallback path
honest.
"""

import numpy as np
import pytest

from horovod_tpu._native import loader


pytestmark = pytest.mark.skipif(
    not loader.available(), reason="native library unavailable (no g++?)"
)


# ------------------------------------------------------------- timeline

def test_timeline_buffer_roundtrip():
    tl = loader.timeline_buffer()
    events = [f'{{"name": "ev{i}", "ts": {i}}}' for i in range(100)]
    for e in events:
        tl.emit(e)
    assert len(tl) == 100
    assert tl.drain() == events
    assert tl.drain() == []
    assert len(tl) == 0


def test_timeline_feeds_chrome_trace(tmp_path):
    """common/timeline.py writes valid Chrome JSON through the native sink."""
    import json

    from horovod_tpu.common.timeline import Timeline

    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    assert tl._native is not None  # native sink picked up
    tl.begin("grad/w", "ALLREDUCE")
    tl.end("grad/w", "ALLREDUCE")
    tl.close()
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "ALLREDUCE" in names and "process_name" in names


# --------------------------------------------------------------- adasum

def test_adasum_pair_matches_formula(rng):
    a = rng.normal(size=257).astype(np.float32)
    b = rng.normal(size=257).astype(np.float32)
    out = loader.adasum_pair(a, b)
    af, bf = a.astype(np.float64), b.astype(np.float64)
    dot, asq, bsq = af @ bf, af @ af, bf @ bf
    want = (1 - dot / (2 * asq)) * af + (1 - dot / (2 * bsq)) * bf
    np.testing.assert_allclose(out, want.astype(np.float32), atol=1e-5)


def test_adasum_scale_invariance(rng):
    """The defining property: adasum(a, b) == adasum(s*a, b) direction-wise
    for orthogonal parts; concretely combine(a, a) == a (self-average)."""
    a = rng.normal(size=64)
    out = loader.adasum_pair(a, a)
    np.testing.assert_allclose(out, a, atol=1e-10)


def test_adasum_tree_matches_host_fallback(rng, monkeypatch):
    stack = rng.normal(size=(5, 33)).astype(np.float32)
    native = loader.adasum_tree(stack)
    # Force the pure-python path and compare.
    from horovod_tpu.ops import adasum as adasum_mod

    monkeypatch.setenv("HOROVOD_NATIVE", "0")
    fallback = adasum_mod.adasum_tree_host(stack)
    np.testing.assert_allclose(native, fallback, rtol=1e-5, atol=1e-5)


def test_adasum_host_matches_traced_pair(rng):
    """Host combiner agrees with the jit/XLA pair math (ops/adasum.py)."""
    from horovod_tpu.ops.adasum import adasum_pair

    a = rng.normal(size=128).astype(np.float32)
    b = rng.normal(size=128).astype(np.float32)
    np.testing.assert_allclose(
        loader.adasum_pair(a, b), np.asarray(adasum_pair(a, b)),
        rtol=1e-4, atol=1e-5,
    )


# ------------------------------------------------------------------- GP

def test_gp_matches_numpy_gp(rng):
    from horovod_tpu.common.autotune import GaussianProcess

    x = rng.uniform(size=(12, 2))
    y = rng.normal(size=12)
    gp_py = GaussianProcess()
    gp_py.fit(x, y)
    gp_c = loader.NativeGaussianProcess()
    gp_c.fit(x, y)
    q = rng.uniform(size=(50, 2))
    mu_py, sd_py = gp_py.predict(q)
    mu_c, sd_c = gp_c.predict(q)
    np.testing.assert_allclose(mu_c, mu_py, atol=1e-9)
    np.testing.assert_allclose(sd_c, sd_py, atol=1e-9)


def test_autotune_uses_native_gp():
    from horovod_tpu.common.autotune import make_gaussian_process

    gp = make_gaussian_process()
    assert type(gp).__name__ == "NativeGaussianProcess"


def test_autotune_convergence_with_native_gp():
    """The full ParameterManager loop still converges to a frozen choice."""
    from horovod_tpu.common.autotune import ParameterManager

    pm = ParameterManager(
        initial_threshold=1 << 20, initial_cycle_ms=1.0,
        warmup_samples=1, steps_per_sample=1, max_samples=5,
    )
    # Synthetic signal: bigger thresholds score better.
    for _ in range(20):
        if pm.frozen:
            break
        threshold, _cycle = pm.current()
        pm.record(bytes_=threshold, seconds=1.0)
    assert pm.frozen


# ----------------------------------------------------------------- pack

def test_pack_unpack_roundtrip(rng):
    arrays = [
        rng.normal(size=(4, 5)).astype(np.float32),
        np.arange(11, dtype=np.int64),
        rng.normal(size=3).astype(np.float64),
    ]
    buf = loader.pack(arrays)
    assert buf.nbytes == sum(a.nbytes for a in arrays)
    outs = loader.unpack(buf, arrays)
    for out, src in zip(outs, arrays):
        np.testing.assert_array_equal(out, src)


# -------------------------------------------------------------- kvstore

def test_native_kv_server_with_python_client():
    from horovod_tpu.runner.rendezvous import RendezvousClient
    from horovod_tpu.runner.secret import make_secret_key

    secret = make_secret_key()
    srv = loader.NativeKVServer(secret_key=secret)
    try:
        cli = RendezvousClient("127.0.0.1", srv.port, secret_key=secret)
        cli.put("round0", "rank0", b"addr:1234")
        cli.put("round0", "rank1", b"addr:5678")
        assert cli.get("round0", "rank0") == b"addr:1234"
        assert cli.get("round0", "missing") is None
        assert cli.keys("round0") == ["rank0", "rank1"]
        # binary-safe values
        blob = bytes(range(256)) * 17
        cli.put("round0", "blob", blob)
        assert cli.get("round0", "blob") == blob
        # driver-side direct store access (elastic driver surface)
        assert srv.get("round0", "rank0") == b"addr:1234"
        srv.put("round1", "x", b"1")
        assert cli.get("round1", "x") == b"1"
        srv.drop_scope("round0")
        assert cli.keys("round0") == []
    finally:
        srv.stop()


def test_native_kv_rejects_bad_hmac():
    from horovod_tpu.runner.rendezvous import RendezvousClient
    from horovod_tpu.runner.secret import make_secret_key

    srv = loader.NativeKVServer(secret_key=make_secret_key())
    try:
        evil = RendezvousClient(
            "127.0.0.1", srv.port, secret_key=make_secret_key()
        )
        with pytest.raises(RuntimeError):
            evil.put("s", "k", b"spoof")
        assert evil.get("s", "k") is None  # 403 reads as absent
        unsigned = RendezvousClient("127.0.0.1", srv.port)
        with pytest.raises(RuntimeError):
            unsigned.put("s", "k", b"spoof")
    finally:
        srv.stop()


def test_rendezvous_server_auto_selects_native():
    from horovod_tpu.runner.rendezvous import RendezvousServer

    srv = RendezvousServer()
    try:
        assert srv.backend == "native"
        srv.start()
        srv.store.put("s", "k", b"v")
        assert srv.store.get("s", "k") == b"v"
    finally:
        srv.stop()


def test_rendezvous_python_backend_still_works():
    from horovod_tpu.runner.rendezvous import (
        RendezvousClient,
        RendezvousServer,
    )
    from horovod_tpu.runner.secret import make_secret_key

    secret = make_secret_key()
    srv = RendezvousServer(secret_key=secret, backend="python")
    try:
        assert srv.backend == "python"
        port = srv.start()
        cli = RendezvousClient("127.0.0.1", port, secret_key=secret)
        cli.put("s", "k", b"v")
        assert cli.get("s", "k") == b"v"
    finally:
        srv.stop()


def test_native_kv_survives_malformed_requests():
    """Garbage on the wire (port scanners, broken proxies) must not take
    down the driver: bad Content-Length used to std::terminate via an
    uncaught stoul exception in a detached thread."""
    import socket

    from horovod_tpu.runner.rendezvous import RendezvousClient

    secret = b"k" * 32
    srv = loader.NativeKVServer(secret_key=secret)
    try:
        payloads = [
            b"GET /kv HTTP/1.1\r\nContent-Length: abc\r\n\r\n",
            b"PUT /kv/s/k HTTP/1.1\r\nContent-Length: 99999999999999\r\n\r\n",
            b"garbage\r\n\r\n",
            b"\r\n\r\n",
            b"",
        ]
        for payload in payloads:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            if payload:
                s.sendall(payload)
            try:
                s.recv(256)
            except OSError:
                pass
            s.close()
        cli = RendezvousClient("127.0.0.1", srv.port, secret_key=secret)
        cli.put("s", "k", b"alive")
        assert cli.get("s", "k") == b"alive"
    finally:
        srv.stop()


def test_hmac_interop_cpp_python():
    """C++ HMAC-SHA256 must equal hashlib's for arbitrary payloads —
    exercised through an end-to-end authed request with a long body."""
    from horovod_tpu.runner.rendezvous import RendezvousClient
    from horovod_tpu.runner.secret import make_secret_key

    secret = make_secret_key()
    srv = loader.NativeKVServer(secret_key=secret)
    try:
        cli = RendezvousClient("127.0.0.1", srv.port, secret_key=secret)
        # >64-byte HMAC key path and >1-block bodies
        payload = b"x" * 100_000
        cli.put("big", "k", payload)
        assert cli.get("big", "k") == payload
    finally:
        srv.stop()


# ------------------------------------------- cext (CPython binding half)

@pytest.mark.skipif(
    not loader.ext_available(),
    reason="CPython extension unavailable (e.g. no Python dev headers);"
    " the ctypes fallback covers this environment",
)
class TestCExt:
    """csrc/cext.cc — the buffer-protocol native half (SURVEY.md §2.3:
    the adapter layer's surviving TPU job is host staging)."""

    def test_builds_and_loads(self):
        ext = loader.get_ext()
        assert ext is not None, "CPython extension failed to build"
        assert hasattr(ext, "pack_into")
        assert hasattr(ext, "unpack_into")

    def test_pack_unpack_into_roundtrip(self, rng):
        ext = loader.get_ext()
        srcs = [
            rng.normal(size=(3, 7)).astype(np.float32),
            np.arange(5, dtype=np.int64),
            b"raw-bytes-source",          # plain buffer object
            memoryview(bytes(range(9))),  # memoryview source
        ]
        total = sum(
            s.nbytes if isinstance(s, np.ndarray) else len(bytes(s))
            for s in srcs
        )
        dst = np.empty(total + 8, dtype=np.uint8)  # oversize dst is fine
        written = ext.pack_into(dst, srcs)
        assert written == total
        outs = [np.empty_like(srcs[0]), np.empty_like(srcs[1]),
                bytearray(len(srcs[2])), bytearray(len(bytes(srcs[3])))]
        read = ext.unpack_into(dst, outs)
        assert read == total
        np.testing.assert_array_equal(outs[0], srcs[0])
        np.testing.assert_array_equal(outs[1], srcs[1])
        assert bytes(outs[2]) == srcs[2]
        assert bytes(outs[3]) == bytes(srcs[3])

    def test_dst_too_small_raises(self):
        ext = loader.get_ext()
        with pytest.raises(ValueError, match="dst holds"):
            ext.pack_into(np.empty(3, np.uint8),
                          [np.zeros(4, np.uint8)])

    def test_src_too_short_raises(self):
        ext = loader.get_ext()
        with pytest.raises(ValueError, match="destinations need"):
            ext.unpack_into(np.zeros(3, np.uint8),
                            [np.empty(4, np.uint8)])

    def test_non_buffer_source_raises(self):
        ext = loader.get_ext()
        with pytest.raises(TypeError):
            ext.pack_into(np.empty(8, np.uint8), [object()])

    def test_readonly_dst_rejected(self):
        ext = loader.get_ext()
        with pytest.raises((TypeError, BufferError)):
            ext.pack_into(b"readonly", [np.zeros(2, np.uint8)])

    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_NATIVE", "0")
        assert loader.get_ext() is None
        assert loader.snapshot_arrays([np.zeros(2)]) is None


class TestPackedSnapshot:
    def test_roundtrip_views_and_copies(self, rng):
        arrays = [
            rng.normal(size=(2, 3)).astype(np.float32),
            np.arange(6, dtype=np.int32).reshape(3, 2),
            np.array([True, False, True]),
            np.empty((0, 4), dtype=np.float64),  # zero-byte leaf
            np.array(7.25, dtype=np.float32),    # 0-d: shape must survive
        ]
        snap = loader.snapshot_arrays(arrays)
        assert snap is not None
        assert len(snap) == len(arrays)
        assert snap.nbytes == sum(a.nbytes for a in arrays)
        # mutate the sources: the snapshot must not move
        originals = [a.copy() for a in arrays]
        for a in arrays:
            if a.size:
                a.fill(0)
        for i, orig in enumerate(originals):
            np.testing.assert_array_equal(snap.view(i), orig)
            assert snap.view(i).dtype == orig.dtype
            assert snap.view(i).shape == orig.shape
        # views alias the block; arrays() are owned copies
        assert np.shares_memory(snap.view(0), snap.buf)
        copies = snap.arrays()
        assert not np.shares_memory(copies[0], snap.buf)
        np.testing.assert_array_equal(copies[1], originals[1])
