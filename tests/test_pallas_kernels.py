"""Pallas kernel tests (interpret mode on the CPU test mesh).

The same kernel code lowers to Mosaic on real TPU; the TPU numerics were
validated on hardware during development and bench.py exercises the
device path every round.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import pallas_kernels as pk


def test_scale_cast_matches_reference(rng):
    x = jnp.asarray(rng.normal(size=777).astype(np.float32))
    out = pk.scale_cast(x, 0.5, jnp.bfloat16)
    assert out.dtype == jnp.bfloat16 and out.shape == x.shape
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(x) * 0.5, rtol=1e-2, atol=1e-3
    )


def test_scale_cast_identity_dtype(rng):
    x = jnp.asarray(rng.normal(size=(13, 17)).astype(np.float32))
    out = pk.scale_cast(x, 2.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 2.0, rtol=1e-6)


def test_int8_quantize_roundtrip_error_bound(rng):
    x = jnp.asarray(rng.normal(size=(33, 47)).astype(np.float32))
    values, scale = pk.int8_quantize(x, seed=1)
    assert values.dtype == jnp.int8
    back = pk.int8_dequantize(values, scale)
    # stochastic rounding: per-element error bounded by one quantum
    err = np.abs(np.asarray(back) - np.asarray(x)).max()
    assert err <= float(scale) * 1.01


def test_int8_quantize_unbiased(rng):
    x = jnp.full((64, 128), 0.3, jnp.float32)
    errs = []
    for seed in range(5):
        v, s = pk.int8_quantize(x, seed=seed)
        back = pk.int8_dequantize(v, s)
        errs.append(float(np.mean(np.asarray(back) - np.asarray(x))))
    # bias shrinks under averaging over seeds
    assert abs(np.mean(errs)) < float(s) * 0.1


def test_adasum_pallas_matches_jax_reference(rng):
    from horovod_tpu.ops.adasum import adasum_pair as ada_ref

    a = jnp.asarray(rng.normal(size=1000).astype(np.float32))
    b = jnp.asarray(rng.normal(size=1000).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(pk.adasum_pair(a, b)),
        np.asarray(ada_ref(a, b)),
        rtol=1e-4,
        atol=1e-5,
    )


def test_adasum_pallas_self_combine_identity(rng):
    a = jnp.asarray(rng.normal(size=300).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(pk.adasum_pair(a, a)), np.asarray(a), rtol=1e-5, atol=1e-6
    )


def test_int8_compressor_roundtrip(rng):
    from horovod_tpu.ops.compression import Compression

    x = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32))
    wire, ctx = Compression.int8.compress(x)
    assert wire.dtype == jnp.int8
    back = Compression.int8.decompress(wire, ctx)
    assert back.dtype == x.dtype
    _, scale = ctx
    assert np.abs(np.asarray(back) - np.asarray(x)).max() <= float(scale) * 1.01


def test_int8_compressor_passes_through_ints():
    from horovod_tpu.ops.compression import Compression

    x = jnp.arange(10, dtype=jnp.int32)
    wire, ctx = Compression.int8.compress(x)
    assert wire.dtype == jnp.int32
    np.testing.assert_array_equal(
        np.asarray(Compression.int8.decompress(wire, ctx)), np.asarray(x)
    )


def test_quantized_allreduce_on_mesh(hvd, rng):
    """int8-wire allreduce approximates the exact psum within quantization
    error, across an 8-device mesh."""
    from functools import partial

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops import traced

    mesh = hvd.mesh()
    per_rank = np.stack(
        [rng.normal(size=256).astype(np.float32) * (r + 1) for r in range(8)]
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(hvd.WORLD_AXIS),
        out_specs=P(hvd.WORLD_AXIS),
        check_rep=False,
    )
    def qmean(x):
        return traced.quantized_allreduce(x[0], op=hvd.Average)[None]

    got = np.asarray(jax.jit(qmean)(jnp.asarray(per_rank)))
    want = per_rank.mean(axis=0)
    # every rank sees the same result
    for r in range(8):
        np.testing.assert_allclose(got[r], got[0], rtol=0, atol=0)
    # two quantization stages (per-chunk scatter + reduced-shard gather):
    # stage-1 error ≤ mean of per-rank quanta, stage-2 ≤ one quantum of
    # the reduced shard — bound generously at 3x the largest quantum.
    quantum = np.abs(per_rank).max() / 127.0
    assert np.abs(got[0] - want).max() <= 3 * quantum


def test_distributed_optimizer_int8_compression(hvd, rng):
    """DistributedOptimizer(compression=int8) routes through the
    quantized collective and still averages gradients correctly."""
    from functools import partial

    import optax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    opt = hvd.DistributedOptimizer(
        optax.sgd(1.0), compression=hvd.Compression.int8
    )
    mesh = hvd.mesh()
    per_rank = np.stack(
        [rng.normal(size=512).astype(np.float32) for _ in range(8)]
    )
    params = jnp.zeros(512, jnp.float32)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(hvd.WORLD_AXIS), P()),
        out_specs=P(),
        check_rep=False,
    )
    def step(g, p):
        state = opt.init(p)
        updates, _ = opt.update(g[0], state, p)
        return updates

    updates = np.asarray(jax.jit(step)(jnp.asarray(per_rank), params))
    want = per_rank.mean(axis=0)
    quantum = np.abs(per_rank).max() / 127.0
    # sgd(1.0) updates are -grad
    assert np.abs(-updates - want).max() <= 3 * quantum


def test_quantized_allreduce_rejects_min():
    from horovod_tpu.ops import traced

    with pytest.raises(ValueError):
        # op check happens before any collective; no mesh needed
        traced.quantized_allreduce(jnp.zeros(4), op="min")
