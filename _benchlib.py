"""Shared bench-harness helpers: AOT compile + XLA FLOP counting + MFU.

One place owns the MFU methodology for every bench (bench.py,
bench_lm.py): compile the jitted step ONCE ahead of time (the same
compiled object runs the timed loop — no second trace/compile), read
the step's FLOPs from XLA cost analysis, and divide measured FLOP/s by
the chip's peak bf16 FLOP/s.

It also owns the bench RUN ID: one id per bench process (or one per
sweep when the driver exports ``BENCH_RUN_ID``), stamped onto every
JSON artifact line AND into the flight-recorder step records
(``telemetry.set_run_id``) — a bench number and the step telemetry
that produced it join on ``run_id`` instead of on filename archaeology.
"""

import os
import uuid

_RUN_ID = None


def run_id() -> str:
    """This bench process's run id. ``BENCH_RUN_ID`` wins (a sweep
    driver threads one id through every bench it launches); otherwise
    a fresh 16-hex id. First call also stamps it into the telemetry
    hub so flight-recorder records carry the same id."""
    global _RUN_ID
    if _RUN_ID is None:
        _RUN_ID = os.environ.get("BENCH_RUN_ID") or uuid.uuid4().hex[:16]
        try:
            from horovod_tpu.common import telemetry

            telemetry.set_run_id(_RUN_ID)
        except Exception:  # bench without the package on path
            pass
    return _RUN_ID


def stamp(line: dict) -> dict:
    """Add ``run_id`` to a bench JSON record (in place, returned for
    chaining). Never overwrites — a parent re-emitting a child's
    already-stamped line keeps the child's id."""
    line.setdefault("run_id", run_id())
    return line

# Public peak bf16 TFLOP/s per chip, keyed by the sandbox's generation
# env var. Override with BENCH_PEAK_TFLOPS.
PEAK_BF16_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}

# Public peak HBM bandwidth (GB/s) per generation — the roofline
# denominator. Override with BENCH_PEAK_HBM_GBS.
PEAK_HBM_GBS = {"v4": 1228.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1638.0}


def peak_hbm_gbs(platform: str):
    if platform == "cpu":
        return None
    if os.environ.get("BENCH_PEAK_HBM_GBS"):
        return float(os.environ["BENCH_PEAK_HBM_GBS"])
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    return PEAK_HBM_GBS.get(gen)


def peak_tflops(platform: str):
    """MFU denominator for this chip; None when there isn't a meaningful
    one (CPU)."""
    if platform == "cpu":
        return None
    if os.environ.get("BENCH_PEAK_TFLOPS"):
        return float(os.environ["BENCH_PEAK_TFLOPS"])
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    return PEAK_BF16_TFLOPS.get(gen)


def sync(x):
    """Reliable device sync: force a host transfer of the first leaf of
    ``x`` and return it as a float. ``jax.block_until_ready`` proved
    advisory on the sandbox's axon PJRT tunnel (observed: a chained
    10-step BERT-large loop "completing" in 2.8 ms/step under
    block_until_ready vs 152 ms/step under a value dependency, measured
    2026-07-30) — a host transfer of a value that data-depends on the
    whole loop is the only sync the tunnel can't fake. Call it on the
    final loss BEFORE starting the timer too: the first transfer also
    drains the warmup queue. Only ONE scalar crosses the wire: the leaf
    is sliced on-device first, so syncing on a 128 MB allreduce buffer
    doesn't pay a 128 MB transfer."""
    import jax
    import numpy as np

    leaf = jax.tree.leaves(x)[0]
    if hasattr(leaf, "reshape"):
        leaf = leaf.reshape(-1)[:1]
    return float(np.asarray(leaf).ravel()[0])


def aot_compile(step_fn, *args):
    """AOT-compile a jitted fn once; returns (callable, flops_or_None).
    Falls back to the jitted fn itself on backends without AOT. The
    step's XLA-estimated HBM traffic (the roofline numerator) is read
    separately with :func:`bytes_accessed`."""
    try:
        compiled = step_fn.lower(*args).compile()
    except Exception:
        return step_fn, None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        flops = None
    return compiled, flops


def bytes_accessed(compiled):
    """XLA's 'bytes accessed' estimate for a compiled step, or None
    (its own failure domain — a missing bytes field must never cost
    the FLOPs number)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get("bytes accessed", 0.0)) or None
    except Exception:
        return None


def mfu_fields(flops, iters, dt, platform, step_bytes=None):
    """The tflops_per_sec / mfu keys for a bench JSON line (empty dict
    when FLOPs are unknown). ``step_bytes`` (from
    :func:`bytes_accessed` on the SAME compiled step) adds the
    roofline side: XLA's bytes estimate over the measured step time vs
    the chip's HBM peak — an mbu near 1.0 with mfu well below 1.0 is
    the quantified bandwidth-bound argument VERDICT r3 asked for
    (XLA assumes perfect fusion, so read mbu as a lower bound)."""
    if flops is None or dt <= 0:
        return {}
    tflops = flops * iters / dt / 1e12
    out = {"tflops_per_sec": round(tflops, 2)}
    peak = peak_tflops(platform)
    if peak:
        out["mfu"] = round(tflops / peak, 4)
    if step_bytes and platform != "cpu":
        gbs = step_bytes * iters / dt / 1e9
        out["hbm_gb_per_sec"] = round(gbs, 1)
        peak_bw = peak_hbm_gbs(platform)
        if peak_bw:
            out["mbu"] = round(gbs / peak_bw, 4)
    return out
