"""Timeline for the TRACED (jit/shard_map) path — the fast path.

The reference's timeline instruments its background loop per collective
(ref: horovod/common/timeline.cc hooks + NVTX ranges,
nvtx_op_range.h [V] — SURVEY.md §5.1). Under jit there is no per-op
dispatch to hook: XLA runs the whole step as one executable. The honest
TPU equivalent is the XLA profiler itself — it records every compiled
op (collectives included) with real device timestamps. This module
wraps ``jax.profiler`` so the traced path gets the same user surface as
the eager timeline:

    hvd.start_timeline("/tmp/tl.json", traced=True)
    for i in range(steps):
        with hvd.timeline_step("train", i):   # NVTX-range analog
            params, loss = step(params, batch)
    hvd.stop_timeline()                        # writes chrome-trace JSON

``stop()`` post-processes the profiler's ``*.trace.json.gz`` into one
plain chrome://tracing JSON at the requested path; the raw TensorBoard
logdir (XPlane protos) is kept next to it for users who want the full
TB profile UI.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import shutil
from contextlib import contextmanager
from typing import Optional


class TracedTimeline:
    """jax.profiler session shaped like the eager Timeline."""

    def __init__(self, path: str):
        self._path = os.path.abspath(path)
        # TB logdir kept alongside the requested JSON for the full UI.
        self._logdir = self._path + ".profile"
        self._active = False
        # the last session's exposed/hidden collective ledger (set by
        # stop() → _export_chrome_trace); telemetry StepStats read the
        # same numbers through the overlap.* registry gauges
        self.last_overlap_stats = None

    @property
    def active(self) -> bool:
        return self._active

    @property
    def logdir(self) -> str:
        return self._logdir

    def start(self) -> None:
        if self._active:
            return
        import jax

        shutil.rmtree(self._logdir, ignore_errors=True)
        os.makedirs(self._logdir, exist_ok=True)
        jax.profiler.start_trace(self._logdir)
        self._active = True

    @contextmanager
    def step(self, name: str = "step", step_num: Optional[int] = None):
        """Mark one training step in the trace (the NVTX-range analog,
        nvtx_op_range.h [V]). No-op overhead when the timeline is off."""
        if not self._active:
            yield
            return
        import jax

        kwargs = {} if step_num is None else {"step_num": step_num}
        with jax.profiler.StepTraceAnnotation(name, **kwargs):
            yield

    @contextmanager
    def annotate(self, name: str):
        """Free-form range annotation inside a step."""
        if not self._active:
            yield
            return
        import jax

        with jax.profiler.TraceAnnotation(name):
            yield

    def stop(self) -> None:
        if not self._active:
            return
        import jax

        jax.profiler.stop_trace()
        self._active = False
        self._export_chrome_trace()

    # close() aliases stop() so GlobalState teardown treats eager and
    # traced timelines uniformly.
    def close(self) -> None:
        self.stop()

    def _export_chrome_trace(self) -> None:
        """Merge the profiler's per-host trace.json.gz into one plain
        chrome://tracing JSON at the requested path.

        Multi-host traces reuse pid numbers (each host's profiler
        starts from the same ids), so each source file's pids are
        remapped into a disjoint range and the host is recorded in the
        process_name metadata — without this, chrome://tracing renders
        every host's processes overlapped."""
        events = []
        pattern = os.path.join(
            self._logdir, "plugins", "profile", "*", "*.trace.json.gz"
        )
        files = sorted(glob.glob(pattern))
        pid_stride = 10_000
        for host_idx, fname in enumerate(files):
            try:
                with gzip.open(fname, "rt") as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            host = os.path.basename(fname).split(".")[0]
            offset = host_idx * pid_stride
            for ev in data.get("traceEvents", []):
                if "pid" in ev:
                    ev = dict(ev)
                    ev["pid"] = int(ev["pid"]) + offset
                    if (
                        len(files) > 1
                        and ev.get("ph") == "M"
                        and ev.get("name") == "process_name"
                    ):
                        args = dict(ev.get("args", {}))
                        args["name"] = f"{host}: {args.get('name', '')}"
                        ev["args"] = args
                events.append(ev)
        # synthetic pid one stride past the last host's remapped range
        # (host pids are assumed < stride, as the remap above already
        # requires) so it can never collide with a real process
        synth_pid = max(len(files), 1) * pid_stride
        # exposed-vs-hidden collective time: the overlap ledger the
        # bucketed gradient exchange (ops/overlap.py) is tuned against.
        # Computed on the REAL device events only — the synthetic twin
        # track below would double-count every span.
        stats = collective_overlap_stats(events)
        self.last_overlap_stats = stats
        events.extend(_collective_spans(events, synth_pid))
        if stats["spans"]:
            from . import metrics as _metrics

            _metrics.registry.update(
                "overlap",
                {
                    "collective_ms": stats["collective_us"] / 1e3,
                    "exposed_collective_ms": stats["exposed_us"] / 1e3,
                    "hidden_collective_ms": stats["hidden_us"] / 1e3,
                    "collective_spans": stats["spans"],
                },
            )
            last_ts = max(
                (
                    ev.get("ts", 0) + ev.get("dur", 0)
                    for ev in events
                    if ev.get("ph") == "X"
                ),
                default=0,
            )
            for name, val in (
                ("hvd.exposed_collective_ms", stats["exposed_us"] / 1e3),
                ("hvd.hidden_collective_ms", stats["hidden_us"] / 1e3),
            ):
                events.append(
                    {
                        "ph": "C",
                        "pid": synth_pid,
                        "name": name,
                        "ts": last_ts,
                        "args": {"ms": round(val, 3)},
                    }
                )
        tmp = f"{self._path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": events}, f)
        os.replace(tmp, self._path)


# Device-event name -> horovod phase. Covers both the TPU profiler's
# HLO names (all-reduce.N, collective-permute-start.N, fused variants)
# and the CPU thunk names JAX emits in tests (psum.N, all_gather.N).
_COLLECTIVE_PHASES = (
    ("all-reduce", "ALLREDUCE"),
    ("all_reduce", "ALLREDUCE"),
    ("psum_scatter", "REDUCESCATTER"),  # before psum: longest match
    ("reduce-scatter", "REDUCESCATTER"),
    ("reduce_scatter", "REDUCESCATTER"),
    ("psum", "ALLREDUCE"),
    ("all-gather", "ALLGATHER"),
    ("all_gather", "ALLGATHER"),
    ("all-to-all", "ALLTOALL"),
    ("all_to_all", "ALLTOALL"),
    ("collective-broadcast", "BROADCAST"),
    ("collective-permute", "PPERMUTE"),
    ("ppermute", "PPERMUTE"),
)


def _classify_collective(name: str):
    """The shared event classifier: phase string for a collective
    device event, None for compute (or skipped async-start/end-marker
    halves, returned as the sentinel ``"skip"``)."""
    low = name.lower()
    if low.startswith("end:"):
        return "skip"
    if "-start" in low:
        return "skip"
    for needle, ph in _COLLECTIVE_PHASES:
        if needle in low:
            return ph
    return None


def _interval_overlap(span, intervals):
    """Microseconds of ``span=(t0, t1)`` covered by the UNION of the
    sorted, merged ``intervals``."""
    t0, t1 = span
    covered = 0.0
    for a, b in intervals:
        if b <= t0:
            continue
        if a >= t1:
            break
        covered += min(b, t1) - max(a, t0)
    return covered


def _merge_intervals(intervals):
    out = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def collective_overlap_stats(events):
    """Exposed-vs-hidden collective device time, distilled from the
    profiler's device spans — the measurement the bucketed gradient
    exchange (ops/overlap.py) exists to move: a MONOLITHIC exchange
    shows its whole collective time exposed (nothing left to run
    against it); a bucketed schedule hides bucket k's wire time behind
    buckets k+1..N-1's remaining backward compute.

    Per device pid, a collective span's HIDDEN time is the part of its
    duration during which some compute (non-collective) device event on
    the same pid is also running — concurrency across the pid's rows
    (tids) is exactly how XLA's async collectives appear in the trace;
    the rest is EXPOSED (the step was waiting on the wire). Returns
    totals in microseconds plus the span count. Cost-model caveat: a
    compute op that itself waits on the collective's result cannot
    overlap in reality, so this is an upper bound on hiding — but the
    MONOLITHIC-vs-bucketed DELTA is honest, since both sides carry the
    same bias.

    CONTAINER rows are excluded from the compute side: the profiler
    exports step/module/scope annotation rows ("Steps", "XLA Modules",
    "Framework Name Scope", ...) as sibling tids of the SAME device
    pid, and a whole-step container span would blanket every
    collective as "hidden" regardless of schedule. Rows are identified
    by their ``thread_name`` metadata; rows without metadata (unit
    traces, thunk exports) are kept."""
    _container = ("step", "module", "scope", "framework", "source")
    skip_rows = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            tname = str(ev.get("args", {}).get("name", "")).lower()
            if any(n in tname for n in _container):
                skip_rows.add((ev.get("pid", 0), ev.get("tid", 0)))
    per_pid: dict = {}
    for ev in events:
        if ev.get("ph") != "X" or not ev.get("dur"):
            continue
        phase = _classify_collective(str(ev.get("name", "")))
        if phase == "skip":
            continue
        pid = ev.get("pid", 0)
        if phase is None and (pid, ev.get("tid", 0)) in skip_rows:
            continue
        coll, comp = per_pid.setdefault(pid, ([], []))
        t0 = float(ev.get("ts", 0))
        span = (t0, t0 + float(ev["dur"]))
        (coll if phase is not None else comp).append(span)
    total = hidden = 0.0
    spans = 0
    for coll, comp in per_pid.values():
        if not coll:
            continue
        merged = _merge_intervals(comp)
        for span in coll:
            dur = span[1] - span[0]
            total += dur
            hidden += _interval_overlap(span, merged)
            spans += 1
    return {
        "collective_us": total,
        "hidden_us": hidden,
        "exposed_us": total - hidden,
        "spans": spans,
    }


def _collective_spans(events, pid):
    """Per-collective DEVICE spans distilled from the profiler events —
    the traced-path analog of the eager timeline's per-op phase ranges
    (ref: timeline.cc phase semantics [V]; VERDICT r4 item 9). Each
    compiled collective op (complete 'X' events with a duration) gets a
    twin event on the 'horovod collectives' track (`pid`), named by its
    horovod phase with the HLO/thunk op recorded in args.hlo_op, device
    timestamps preserved. Rows (tids) are the SOURCE events' remapped
    pids — host-disjoint after the merge — so multi-host spans never
    overlap on one row; the source tid rides in args. Async HLO pairs
    contribute ONE span: the `-start` half is skipped (its duration is
    launch, not the collective), the `-done` half ends at device
    completion — the phase-aggregation-friendly choice."""
    out = []
    rows = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        # one classifier for this track AND the exposed/hidden ledger
        # (collective_overlap_stats) — they must never disagree about
        # what counts as a collective
        phase = _classify_collective(name)
        if phase is None or phase == "skip":
            continue
        row = ev.get("pid", 0)
        rows.setdefault(row, 0)
        out.append(
            {
                "ph": "X",
                "pid": pid,
                "tid": row,
                "ts": ev.get("ts", 0),
                "dur": ev.get("dur", 0),
                "name": f"{phase} {name}",
                "args": {
                    "hlo_op": name,
                    "phase": phase,
                    "src_tid": ev.get("tid", 0),
                },
            }
        )
    if out:
        out.append(
            {
                "ph": "M",
                "pid": pid,
                "name": "process_name",
                "args": {"name": "horovod collectives"},
            }
        )
        for row in rows:
            out.append(
                {
                    "ph": "M",
                    "pid": pid,
                    "tid": row,
                    "name": "thread_name",
                    "args": {"name": f"src pid {row}"},
                }
            )
    return out
