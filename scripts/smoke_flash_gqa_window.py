"""On-chip numerics smoke: GQA and causal-sliding-window flash kernel
paths (interpret-validated until this runs on a real chip), fwd+bwd vs
an fp32 dense oracle.  Prints ALL OK on success (chipwork smoke()).
"""
import numpy as np
import jax
import jax.numpy as jnp

assert jax.devices()[0].platform == "tpu"
from horovod_tpu.ops import flash_attention as fa

rng = np.random.default_rng(0)
b, t, h, g, d = 2, 512, 8, 2, 64
q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
k = jnp.asarray(rng.normal(size=(b, t, g, d)), jnp.float32)
v = jnp.asarray(rng.normal(size=(b, t, g, d)), jnp.float32)
lengths = jnp.asarray([512, 301], jnp.int32)
W = 128


def dense(q, k, v, window=None, lengths=None):
    r = q.shape[2] // k.shape[2]
    kk, vv = jnp.repeat(k, r, axis=2), jnp.repeat(v, r, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kk,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    band = rows >= cols
    if window is not None:
        band = band & (rows - cols < window)
    s = jnp.where(band[None, None], s, -1e30)
    if lengths is not None:
        valid = jnp.arange(t)[None, :] < lengths[:, None]
        s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vv.astype(jnp.float32))
    if lengths is not None:
        valid = jnp.arange(t)[None, :] < lengths[:, None]
        o = jnp.where(valid[:, :, None, None], o, 0.0)
    return o


ok = True
for name, kw in (("gqa", {}), ("gqa+window", {"window": W}),
                 ("gqa+window+lengths", {"window": W, "lengths": lengths})):
    out = fa.flash_attention(q, k, v, causal=True, **kw)
    ref = dense(q, k, v, **kw)
    e = float(jnp.max(jnp.abs(out - ref)))
    print(name, "fwd maxerr", e)
    ok &= e < 2e-3
    gg = jax.grad(lambda q, k, v: fa.flash_attention(
        q, k, v, causal=True, **kw).sum(), argnums=(0, 1, 2))(q, k, v)
    rr = jax.grad(lambda q, k, v: dense(q, k, v, **kw).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for gname, a, bb in zip(("dq", "dk", "dv"), gg, rr):
        e = float(jnp.max(jnp.abs(a - bb)))
        print(name, gname, "maxerr", e)
        ok &= e < 2e-3
print("ALL OK" if ok else "SMOKE FAIL")
