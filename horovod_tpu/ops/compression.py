"""Gradient wire compression.

API parity with the reference's compression module
(ref: horovod/torch/compression.py + horovod/tensorflow/compression.py [V],
SURVEY.md §2.4): ``Compression.none`` and ``Compression.fp16``, each a
(compress, decompress) pair applied around the allreduce.

On TPU the natural wire format is bfloat16 (same exponent range as fp32 —
no loss-scaling dance, and the MXU consumes it natively), so ``bf16`` is
added alongside the reference's fp16. XLA fuses the casts into the
collective's producer/consumer, so compression costs no extra HBM pass.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """A (compress, decompress) pair. ``compress`` returns (tensor, ctx)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 on the wire, restore original dtype
    after (ref: FP16Compressor [V])."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx != tensor.dtype else tensor


class BF16Compressor(Compressor):
    """TPU-native wire compression: bfloat16 keeps fp32's exponent range."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx != tensor.dtype else tensor


class Compression:
    """Namespace mirroring hvd.Compression [V]."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
