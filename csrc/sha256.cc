// SHA-256 (FIPS 180-4) and HMAC-SHA256 (RFC 2104). Written from the
// spec: message schedule + 64-round compression over 512-bit blocks,
// then the standard ipad/opad HMAC construction. Used by kvstore.cc to
// verify X-Horovod-Digest headers against the per-job secret
// (parity with horovod_tpu/runner/secret.py, which uses hashlib).

#include "sha256.h"

#include <cstring>
#include <vector>

namespace hvd {
namespace {

constexpr uint32_t kInit[8] = {
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
};

constexpr uint32_t kRound[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

void compress(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; ++i) {
    w[i] = (uint32_t(block[i * 4]) << 24) | (uint32_t(block[i * 4 + 1]) << 16) |
           (uint32_t(block[i * 4 + 2]) << 8) | uint32_t(block[i * 4 + 3]);
  }
  for (int i = 16; i < 64; ++i) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; ++i) {
    uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + s1 + ch + kRound[i] + w[i];
    uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

}  // namespace

void sha256(const uint8_t* data, size_t len, uint8_t* out) {
  uint32_t state[8];
  std::memcpy(state, kInit, sizeof(kInit));

  size_t full = len / 64;
  for (size_t i = 0; i < full; ++i) compress(state, data + i * 64);

  // Final block(s): remaining bytes + 0x80 + zero pad + 64-bit bit length.
  uint8_t tail[128] = {0};
  size_t rem = len - full * 64;
  std::memcpy(tail, data + full * 64, rem);
  tail[rem] = 0x80;
  size_t tail_len = (rem + 1 + 8 <= 64) ? 64 : 128;
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; ++i) {
    tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
  }
  compress(state, tail);
  if (tail_len == 128) compress(state, tail + 64);

  for (int i = 0; i < 8; ++i) {
    out[i * 4] = uint8_t(state[i] >> 24);
    out[i * 4 + 1] = uint8_t(state[i] >> 16);
    out[i * 4 + 2] = uint8_t(state[i] >> 8);
    out[i * 4 + 3] = uint8_t(state[i]);
  }
}

void hmac_sha256(const uint8_t* key, size_t key_len, const uint8_t* msg,
                 size_t msg_len, uint8_t* out) {
  uint8_t k[64] = {0};
  if (key_len > 64) {
    sha256(key, key_len, k);  // hashed key, 32 bytes, rest zero
  } else {
    std::memcpy(k, key, key_len);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  // inner = H(ipad || msg)
  uint8_t inner[32];
  {
    // Stream: compress ipad block, then continue with msg via a small
    // buffer — reuse sha256 over a concatenated copy to stay simple
    // (payloads here are rendezvous-sized: method+path+body, < a few KB).
    std::vector<uint8_t> buf;
    buf.reserve(64 + msg_len);
    buf.insert(buf.end(), ipad, ipad + 64);
    buf.insert(buf.end(), msg, msg + msg_len);
    sha256(buf.data(), buf.size(), inner);
  }
  uint8_t outer[96];
  std::memcpy(outer, opad, 64);
  std::memcpy(outer + 64, inner, 32);
  sha256(outer, 96, out);
}

}  // namespace hvd
