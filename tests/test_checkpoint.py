"""Checkpoint subsystem tests — the durability layer the reference
lacks (SURVEY.md §5.4: in-memory elastic commits only)."""

import jax.numpy as jnp
import numpy as np
import pytest


def test_save_restore_roundtrip(hvd, tmp_path, rng):
    from horovod_tpu.checkpoint import CheckpointManager

    tree = {
        "params": {"w": jnp.asarray(rng.normal(size=(4, 3)), jnp.float32)},
        "step": jnp.asarray(7, jnp.int32),
    }
    with CheckpointManager(str(tmp_path / "ck")) as mgr:
        assert mgr.save(1, tree)
        mgr.wait_until_finished()
        out = mgr.restore(1, like=tree)
    np.testing.assert_allclose(
        np.asarray(out["params"]["w"]), np.asarray(tree["params"]["w"])
    )
    assert int(out["step"]) == 7


def test_latest_and_retention(hvd, tmp_path):
    from horovod_tpu.checkpoint import CheckpointManager

    tree = {"x": jnp.zeros(2)}
    with CheckpointManager(str(tmp_path / "ck"), max_to_keep=2) as mgr:
        for step in (1, 2, 3):
            mgr.save(step, {"x": jnp.full(2, float(step))})
            mgr.wait_until_finished()
        assert mgr.latest_step() == 3
        assert mgr.all_steps() == [2, 3]  # oldest pruned
        out = mgr.restore(like=tree)
    np.testing.assert_allclose(np.asarray(out["x"]), 3.0)


def test_restore_missing_raises(hvd, tmp_path):
    from horovod_tpu.checkpoint import CheckpointManager

    with CheckpointManager(str(tmp_path / "empty")) as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore()


def test_sharded_leaf_roundtrip(hvd, tmp_path, rng):
    """A rank-major world-sharded array restores with its sharding."""
    from horovod_tpu.checkpoint import CheckpointManager

    x = hvd.shard_from_rank_fn(
        lambda r: np.full((3,), float(r), np.float32), hvd.mesh()
    )
    with CheckpointManager(str(tmp_path / "ck")) as mgr:
        mgr.save(1, {"x": x})
        mgr.wait_until_finished()
        out = mgr.restore(1, like={"x": x})
    np.testing.assert_allclose(np.asarray(out["x"]), np.asarray(x))
    assert out["x"].sharding == x.sharding


def test_durable_state_resume(hvd, tmp_path):
    """Full-job-restart resume: a fresh DurableJaxState picks up where
    the dead job's last durable commit left off."""
    from horovod_tpu.checkpoint import DurableJaxState

    ckdir = str(tmp_path / "elastic_ck")
    params = {"w": jnp.ones((2, 2), jnp.float32)}
    state = DurableJaxState(
        checkpoint_dir=ckdir, params=params, step=0, epoch=0
    )
    state.params = {"w": jnp.full((2, 2), 5.0, jnp.float32)}
    state.step = 42
    state.commit()
    state.wait_until_finished()
    state.close()

    # "restarted job": new process, same directory
    fresh = DurableJaxState(
        checkpoint_dir=ckdir, params=params, step=0, epoch=0
    )
    assert fresh.resume_latest()
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), 5.0)
    assert fresh.step == 42
    # in-memory rollback still works on top of the resumed state
    fresh.step = 99
    fresh.restore()
    assert fresh.step == 42
    fresh.close()


def test_durable_state_save_interval(hvd, tmp_path):
    from horovod_tpu.checkpoint import DurableJaxState

    state = DurableJaxState(
        checkpoint_dir=str(tmp_path / "ck"),
        save_interval=3,
        params={"w": jnp.zeros(2)},
        step=0,
    )
    for i in range(1, 7):
        state.step = i
        state.commit()
    state.wait_until_finished()
    # 6 commits / interval 3 => exactly 2 durable checkpoints
    assert len(state._ckpt.all_steps()) == 2
    state.close()


def test_durable_state_fresh_start(hvd, tmp_path):
    from horovod_tpu.checkpoint import DurableJaxState

    state = DurableJaxState(
        checkpoint_dir=str(tmp_path / "ck"), params={"w": jnp.zeros(2)},
        step=0,
    )
    assert not state.resume_latest()
    state.close()
