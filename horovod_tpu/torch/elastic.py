"""Elastic state for the torch shim: ``TorchState``.

Parity target: ``horovod.torch.elastic.state.TorchState`` [V]
(SURVEY.md §2.5 "Elastic worker API") — wrap a torch module +
optimizer (+ scalars like epoch/batch) so elastic training can
``commit()`` (host snapshot), ``restore()`` (roll back to the last
commit after a failure), and ``sync()`` (broadcast from the new rank 0
after a membership change). Reuses the shim's
``broadcast_parameters`` / ``broadcast_optimizer_state`` /
``broadcast_object`` for the sync leg and the base ``ObjectState``
machinery for scalar attributes; use with ``hvd.elastic.run`` exactly
like ``JaxState``.
"""

from __future__ import annotations

import copy
from typing import Any

from ..elastic.state import ObjectState, State  # noqa: F401 — re-export
from ..elastic.worker import run  # noqa: F401 — hvd.torch.elastic.run


class TorchState(ObjectState):
    """Commit/restore/sync over a torch model + optimizer
    (ref: horovod/torch/elastic/state.py TorchState [V])."""

    def __init__(self, model=None, optimizer=None, **kwargs: Any) -> None:
        self.model = model
        self.optimizer = optimizer
        self._saved_model_state: Any = None
        self._saved_optimizer_state: Any = None
        super().__init__(**kwargs)
        self.save()

    @staticmethod
    def _clone_state_dict(sd):
        import torch

        def clone(v):
            if isinstance(v, torch.Tensor):
                return v.detach().clone()
            if isinstance(v, dict):
                return {k: clone(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return type(v)(clone(x) for x in v)
            return copy.deepcopy(v)

        return clone(sd)

    def save(self) -> None:
        if self.model is not None:
            self._saved_model_state = self._clone_state_dict(
                self.model.state_dict()
            )
        if self.optimizer is not None:
            self._saved_optimizer_state = self._clone_state_dict(
                self.optimizer.state_dict()
            )
        super().save()

    def restore(self) -> None:
        # load_state_dict copies (params via copy_, optimizer via its
        # own deepcopy), so the snapshots can be passed directly
        if self.model is not None and self._saved_model_state is not None:
            self.model.load_state_dict(self._saved_model_state)
        if (
            self.optimizer is not None
            and self._saved_optimizer_state is not None
        ):
            self.optimizer.load_state_dict(self._saved_optimizer_state)
        super().restore()

    def sync(self) -> None:
        from . import broadcast_optimizer_state, broadcast_parameters

        if self.model is not None:
            broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            broadcast_optimizer_state(self.optimizer, root_rank=0)
        super().sync()  # scalar attributes via broadcast_object
        self.save()
