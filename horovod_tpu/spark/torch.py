"""TorchEstimator — the torch half of the Estimator family.

Parity target: ``horovod.spark.torch.TorchEstimator`` [V] (declare a
torch model + optimizer factory + loss, call fit, get a servable model
back, checkpoints through the Store). Rebuilt on the torch shim:
parameters and optimizer state broadcast from rank 0 before the first
step, the optimizer is wrapped with the shim's ``DistributedOptimizer``
(grouped gradient allreduce at step time), and per-epoch losses are
metric-averaged across workers.

Data enters as arrays or an iterable of ``(x, y)`` batches — the
Petastorm/DataFrame slot of the reference (scope: docs/design.md
"Spark / Ray depth").
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import numpy as np

from . import Store


class TorchModelWrapper:
    """Servable result of :meth:`TorchEstimator.fit` (ref: the
    TorchModel transformer [V])."""

    def __init__(self, model):
        self.model = model

    def predict(self, x):
        import torch

        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.as_tensor(np.asarray(x)))
        return out.detach().cpu().numpy()

    def save(self, path: str) -> None:
        import torch

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        torch.save(self.model.state_dict(), path)

    @classmethod
    def load(cls, model, path: str) -> "TorchModelWrapper":
        """Load into ``model`` (the architecture object — torch
        state_dicts carry tensors, not module graphs)."""
        import torch

        model.load_state_dict(torch.load(path, weights_only=True))
        return cls(model)


class TorchEstimator:
    """Declarative torch trainer (ref: horovod/spark/torch/estimator.py
    TorchEstimator [V]): declare model + optimizer + loss, call
    ``fit``, receive a :class:`TorchModelWrapper`.

    ``optimizer`` may be an optimizer instance or a factory
    ``params -> optimizer`` (the reference takes an optimizer bound to
    the model's params; the factory form avoids the bound-before-fit
    footgun when the caller constructs the estimator early).
    """

    def __init__(
        self,
        model,
        loss: Optional[Callable] = None,
        optimizer=None,
        store: Optional[Store] = None,
        run_id: str = "run",
        epochs: int = 1,
        batch_size: int = 32,
        backward_passes_per_step: int = 1,
        checkpoint_every_n_epochs: int = 1,
    ):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.store = store
        self.run_id = run_id
        self.epochs = int(epochs)
        self.batch_size = int(batch_size)
        self.backward_passes_per_step = int(backward_passes_per_step)
        self.checkpoint_every = int(checkpoint_every_n_epochs)
        self.history: list = []

    def _batches(self, x, y):
        import torch

        n = x.shape[0]
        steps = n // self.batch_size
        for i in range(steps):
            sl = slice(i * self.batch_size, (i + 1) * self.batch_size)
            yield torch.as_tensor(x[sl]), torch.as_tensor(y[sl])

    def fit(self, x, y=None) -> TorchModelWrapper:
        """Train. ``x`` may be a feature array (with ``y`` labels) or an
        iterable of ``(x_batch, y_batch)`` pairs per epoch."""
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        model = self.model
        loss_fn = self.loss or torch.nn.MSELoss()
        opt = self.optimizer
        if opt is None:
            opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        elif callable(opt) and not hasattr(opt, "param_groups"):
            opt = opt(model.parameters())

        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        opt = hvd.DistributedOptimizer(
            opt,
            named_parameters=model.named_parameters(),
            backward_passes_per_step=self.backward_passes_per_step,
        )

        ckpt_dir = None
        if self.store is not None:
            ckpt_dir = self.store.checkpoint_dir(self.run_id)
            os.makedirs(ckpt_dir, exist_ok=True)
            os.makedirs(self.store.logs_dir(self.run_id), exist_ok=True)

        if y is not None:
            x = np.asarray(x)
            y = np.asarray(y)
            if x.shape[0] < self.batch_size:
                raise ValueError(
                    f"batch_size {self.batch_size} exceeds dataset size "
                    f"{x.shape[0]}: every epoch would train zero steps"
                )
        else:
            # Materialize the batch source: a one-shot generator must
            # re-iterate every epoch (same contract as TpuEstimator).
            x = list(x)
            if not x:
                raise ValueError("empty batch iterable")

        model.train()
        self.history = []  # fresh per fit(): re-fit must not append
        for epoch in range(self.epochs):
            epoch_losses = []
            batches = self._batches(x, y) if y is not None else iter(x)
            for xb, yb in batches:
                xb = torch.as_tensor(np.asarray(xb))
                yb = torch.as_tensor(np.asarray(yb))
                opt.zero_grad()
                loss = loss_fn(model(xb), yb)
                loss.backward()
                opt.step()
                epoch_losses.append(float(loss.detach()))
            # a step count not divisible by backward_passes_per_step
            # leaves a partial window — flush it so the tail batches
            # still contribute (and windows never span epochs)
            opt.flush()
            # metric-average across workers (ref: the Estimator's
            # metric aggregation / MetricAverageCallback semantics [V])
            mean_loss = float(
                hvd.allreduce(
                    torch.tensor(np.mean(epoch_losses or [np.nan])),
                    average=True,
                    name="spark.torch.epoch_loss",
                )
            )
            self.history.append({"epoch": epoch, "loss": mean_loss})
            if ckpt_dir is not None and (
                (epoch + 1) % self.checkpoint_every == 0
            ):
                if hvd.rank() == 0:
                    torch.save(
                        {
                            "model": model.state_dict(),
                            "optimizer": opt.state_dict(),
                            "epoch": epoch,
                        },
                        os.path.join(ckpt_dir, f"ckpt-{epoch:03d}.pt"),
                    )

        return TorchModelWrapper(model)
