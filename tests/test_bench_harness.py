"""The bench harnesses are round artifacts — their sweep/efficiency
logic must hold without running a full benchmark (VERDICT r1 #3: a
world-size sweep with scaling_efficiency output, pod-ready)."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from bench_allreduce import (  # noqa: E402
    ring_factor,
    scaling_efficiency,
    sweep_worlds,
)


def test_sweep_worlds_small_box():
    assert sweep_worlds(1) == [1]
    assert sweep_worlds(8) == [1, 2, 4, 8]
    assert sweep_worlds(6) == [1, 2, 4, 6]


def test_sweep_worlds_pod_starts_at_8():
    """On a pod slice the sweep is the north star's 8→256 window."""
    assert sweep_worlds(256) == [8, 16, 32, 64, 128, 256]
    assert sweep_worlds(64) == [8, 16, 32, 64]


def test_ring_factor():
    assert ring_factor(1) == 1.0
    assert ring_factor(2) == 1.0
    assert abs(ring_factor(8) - 1.75) < 1e-12
    assert abs(ring_factor(256) - 2 * 255 / 256) < 1e-12


def test_scaling_efficiency_vs_base():
    base, eff = scaling_efficiency({1: 10.0, 2: 9.0, 4: 8.0})
    assert base == 1
    assert eff[1] == 1.0
    assert abs(eff[2] - 0.9) < 1e-12
    assert abs(eff[4] - 0.8) < 1e-12


def test_scaling_efficiency_empty():
    assert scaling_efficiency({}) == (None, {})


class TestStaleArtifactFallback:
    """BENCH_r03 regression (rc=124): the orchestrator must ALWAYS emit
    a parseable line inside its budget, preferring a committed real-TPU
    artifact over a CPU number when the backend is down."""

    METRIC = "resnet50_synth_img_per_sec"

    def _write(self, d, name, payload):
        (d / name).write_text(json.dumps(payload) + "\n")

    def _tpu_line(self, value=100.0, metric=None):
        return {
            "metric": metric or self.METRIC,
            "value": value,
            "unit": "img/s",
            "vs_baseline": 1.0,
            "platform": "tpu",
        }

    def test_picks_most_recent_tpu_artifact(self, tmp_path, monkeypatch):
        import bench

        self._write(tmp_path, "old_r01.json", self._tpu_line(1.0))
        self._write(tmp_path, "new_r03.json", self._tpu_line(2.0))
        os.utime(tmp_path / "old_r01.json", (1000, 1000))
        monkeypatch.setenv("BENCH_RESULTS_DIR", str(tmp_path))
        parsed, path, _ = bench._stale_artifact(self.METRIC)
        assert parsed["value"] == 2.0
        assert path.endswith("new_r03.json")

    def test_skips_sim_cpu_zero_and_stale_artifacts(
        self, tmp_path, monkeypatch
    ):
        import bench

        self._write(tmp_path, "sim_thing.json", self._tpu_line(5.0))
        cpu = self._tpu_line(6.0)
        cpu["platform"] = "cpu"
        self._write(tmp_path, "cpu_fallback.json", cpu)
        self._write(tmp_path, "failed.json", self._tpu_line(0.0))
        self._write(tmp_path, "other_metric.json",
                    self._tpu_line(7.0, metric="bert_large_samples_per_sec"))
        # a prior outage's reprint must never be re-laundered with a
        # fresh captured_at
        reprint = self._tpu_line(8.0)
        reprint["stale"] = True
        self._write(tmp_path, "reprint_r04.json", reprint)
        monkeypatch.setenv("BENCH_RESULTS_DIR", str(tmp_path))
        assert bench._stale_artifact(self.METRIC) is None

    def test_prefers_embedded_captured_at_over_mtime(
        self, tmp_path, monkeypatch
    ):
        """mtime is checkout time after a fresh clone; the measurement's
        own stamp wins."""
        import bench

        newer = self._tpu_line(1.0)
        newer["captured_at"] = "2026-07-30T06:00:00Z"
        older = self._tpu_line(2.0)
        older["captured_at"] = "2026-07-29T06:00:00Z"
        self._write(tmp_path, "a.json", newer)
        self._write(tmp_path, "b.json", older)
        os.utime(tmp_path / "a.json", (1000, 1000))  # mtime says a is old
        # an UNSTAMPED artifact with a fresh mtime (= checkout time on a
        # clone) must lose to ANY stamped one
        self._write(tmp_path, "unstamped.json", self._tpu_line(3.0))
        monkeypatch.setenv("BENCH_RESULTS_DIR", str(tmp_path))
        parsed, _, when = bench._stale_artifact(self.METRIC)
        assert parsed["value"] == 1.0
        assert when == "2026-07-30T06:00:00Z"

    def _run_orchestrator(self, tmp_path, extra_env):
        env = dict(os.environ)
        env.update(
            {
                "BENCH_RESULTS_DIR": str(tmp_path),
                "BENCH_FAIL_INNER": "1",  # every spawn dies instantly
                "BENCH_ATTEMPTS": "1",
                "BENCH_ATTEMPT_TIMEOUT": "30",
                "BENCH_TOTAL_BUDGET": "60",
                "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
            }
        )
        env.update(extra_env)
        return subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=120,
        )

    def test_config_mismatch_never_substituted(self, tmp_path, monkeypatch):
        """A space_to_depth-stem or odd-batch probe shares the metric
        name; an outage reprint must not swap configs silently."""
        import bench

        s2d = self._tpu_line(9999.0)
        s2d["stem"] = "space_to_depth"
        s2d["captured_at"] = "2026-07-30T09:00:00Z"
        self._write(tmp_path, "resnet50_s2d_r03.json", s2d)
        big_batch = self._tpu_line(8888.0)
        big_batch["batch"] = 1024
        self._write(tmp_path, "resnet50_b1024.json", big_batch)
        default = self._tpu_line(2577.0)
        default["captured_at"] = "2026-07-30T05:00:00Z"
        default["batch"] = 256
        self._write(tmp_path, "resnet50_r03.json", default)
        monkeypatch.setenv("BENCH_RESULTS_DIR", str(tmp_path))
        cfg = {"batch": (256, 256), "stem": ("conv7", "conv7")}
        parsed, _, _ = bench._stale_artifact(self.METRIC, config=cfg)
        assert parsed["value"] == 2577.0

    def test_orchestrator_reprints_stale_tpu_line(self, tmp_path):
        art = self._tpu_line(2585.0)
        art["stem"] = "space_to_depth"  # the r04 default config
        self._write(tmp_path, "resnet50_s2d_r04.json", art)
        proc = self._run_orchestrator(tmp_path, {})
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        assert line["value"] == 2585.0
        assert line["platform"] == "tpu"
        assert line["stale"] is True
        assert "captured_at" in line and "source" in line

    def test_orchestrator_never_substitutes_conv7_for_default(self, tmp_path):
        """Artifacts predating the stem field were conv7 captures; the
        r04 space_to_depth default must not reprint them (3% apart —
        provenance over availability)."""
        self._write(tmp_path, "resnet50_r03.json", self._tpu_line(2577.0))
        proc = self._run_orchestrator(tmp_path, {"BENCH_PLATFORM": ""})
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        # falls past the stale rung: either the CPU fallback (also dies
        # under BENCH_FAIL_INNER here) -> diagnostic value-0 line
        assert not line.get("stale")
        assert line["value"] == 0.0

    def test_orchestrator_diagnostic_line_when_nothing_left(self, tmp_path):
        """No stale artifact + CPU fallback also fails: still ONE
        parseable line (value 0, error populated), nonzero rc."""
        proc = self._run_orchestrator(tmp_path, {"BENCH_STALE": "0"})
        assert proc.returncode == 1
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        assert line["value"] == 0.0
        assert "error" in line

    def test_budget_default_inside_driver_timeout(self):
        """The r3 postmortem contract: the DEFAULT total budget plus
        fallback floors must fit `timeout 1200 python bench.py`."""
        import bench  # noqa: F401 — import keeps the constant honest

        src = open(os.path.join(_REPO, "bench.py")).read()
        assert '"BENCH_TOTAL_BUDGET", "900"' in src
        assert '"BENCH_ATTEMPT_TIMEOUT", "600"' in src


@pytest.mark.slow
def test_bench_allreduce_cpu_sim_end_to_end():
    """The sweep runs on the simulated mesh and emits both per-point
    busbw lines and the scaling summary, parseable."""
    from _hermetic import hermetic_cpu_env

    env = hermetic_cpu_env(n_devices=8)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["BENCH_SIZES"] = "4096,65536"
    env["BENCH_ITERS"] = "3"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench_allreduce.py")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    busbw = [ln for ln in lines if ln["metric"] == "allreduce_busbw"]
    scaling = [ln for ln in lines if ln["metric"] == "allreduce_scaling"]
    assert {ln["world"] for ln in busbw} == {1, 2, 4, 8}
    assert {ln["world"] for ln in scaling} == {1, 2, 4, 8}
    assert all(ln["base_world"] == 1 for ln in scaling)
    base_line = next(ln for ln in scaling if ln["world"] == 1)
    assert base_line["value"] == 1.0
    # CPU-sim quarantine: every non-TPU scaling line carries the
    # logic-validation-only note (VERDICT r3 weak #8)
    assert all("logic-validation only" in ln["note"] for ln in scaling)


# ------------------------------------------------ round-5 microbenches


def _run_harness(script, env, timeout=420):
    """Run a bench harness as a user would (subprocess, tiny config);
    return its parsed JSON lines. Keeps the chip-queued harnesses from
    rotting while they wait out a backend outage. hermetic_cpu_env is
    load-bearing: it strips the sitecustomize gate that would register
    the real TPU plugin at child startup (one-chip discipline — a raw
    env copy would claim the chip out from under the capture chains)."""
    from _hermetic import hermetic_cpu_env

    full_env = hermetic_cpu_env(n_devices=8)
    full_env.update(env)
    full_env.setdefault("BENCH_PLATFORM", "cpu")
    proc = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=full_env,
        cwd=os.path.dirname(os.path.abspath(__file__)) + "/..",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [
        json.loads(ln)
        for ln in proc.stdout.splitlines()
        if ln.startswith("{")
    ]
    assert lines, proc.stdout
    return lines


@pytest.mark.slow
def test_bench_fusion_harness_smoke():
    lines = _run_harness(
        "bench_fusion.py",
        {
            "BENCH_FUSION_N": "8",
            "BENCH_FUSION_BYTES": "16384",
            "BENCH_ITERS": "2",
            "BENCH_AUTOTUNE_TRIALS": "2",
        },
    )
    modes = {l["mode"] for l in lines if l["metric"] == "eager_fusion"}
    # later PRs added modes (host_pack, bucketing_*, gather_*); the
    # original quartet must still be present
    assert modes >= {"unfused", "fused", "default", "traced"}
    assert any(l["metric"] == "eager_fusion_speedup" for l in lines)
    auto = [l for l in lines if l["metric"] == "fusion_autotune"]
    assert auto and auto[0]["trials"] == 2
    # CPU lines must carry the quarantine note
    assert all("note" in l for l in lines)


@pytest.mark.slow
def test_bench_int8_harness_smoke():
    lines = _run_harness(
        "bench_int8.py",
        {"BENCH_SIZES": "65536", "BENCH_ITERS": "2"},
    )
    (line,) = lines
    assert line["metric"] == "int8_compute_tax"
    assert line["quant_ms"] > 0 and line["plain_ms"] > 0
    assert "note" in line


@pytest.mark.slow
def test_bench_overlap_harness_smoke():
    import tempfile

    art = tempfile.mkdtemp()
    lines = _run_harness(
        "bench_overlap.py",
        {
            "BENCH_DRYRUN": "1",
            "BENCH_ITERS": "2",
            "BENCH_ARTIFACT_DIR": art,
        },
    )
    legs = {l["leg"] for l in lines if l["metric"] == "overlap_ab"}
    assert legs == {"ab_monolithic", "ab_bucketed", "ab_bucketed_rs"}
    rs = next(
        l
        for l in lines
        if l["metric"] == "overlap_ab" and l["leg"] == "ab_bucketed_rs"
    )
    tuner = next(l for l in lines if l["metric"] == "overlap_tuner")
    assert tuner["choice"] in tuner["candidates"]
    # compiled-program evidence rides the artifact: bucketed ZeRO-1 leg
    # must carry N independent rs + ag collectives
    assert rs["collectives"]["reduce_scatter"] == rs["n_buckets"]
    assert rs["collectives"]["all_gather"] == rs["n_buckets"]
    # CPU A/B lines carry the quarantine note (the tuner verdict line
    # is a derived summary, not a measurement claim)
    assert all(
        "note" in l for l in lines if l["metric"] == "overlap_ab"
    )
    for leg in legs:
        assert os.path.getsize(
            os.path.join(art, f"overlap_{leg}.json")
        ) > 0


@pytest.mark.slow
def test_bench_seq_harness_smoke():
    lines = _run_harness(
        "bench_seq.py",
        {
            "BENCH_SEQS": "128",
            "BENCH_BATCH": "1",
            "BENCH_HEADS": "2",
            "BENCH_ITERS": "2",
        },
    )
    engines = {l["engine"] for l in lines}
    assert engines == {"flash", "dense"}
    # "tflops" is rounded to 2dp and can legitimately round to 0.0 at
    # this tiny config on a slow host — assert structure, not speed
    assert all(
        "tflops" in l and l["value"] > 0 and "note" in l for l in lines
    )
