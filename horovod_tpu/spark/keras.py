"""KerasEstimator — the TF half of the Estimator family.

Parity target: ``horovod.spark.keras.KerasEstimator`` [V] (declare a
compiled-able Keras model + optimizer + loss, call fit, get a servable
model back, checkpoints through the Store). Rebuilt on the TF shim:
the optimizer is wrapped with the shim's ``DistributedOptimizer``
(gradient allreduce), training starts with the broadcast callback so
every worker begins identical, and epoch metrics ride
``MetricAverageCallback``.

Data enters as arrays or a ``tf.data.Dataset`` — the Petastorm/
DataFrame slot of the reference (scope: docs/design.md "Spark / Ray
depth").
"""

from __future__ import annotations

import os
from typing import Optional

from . import Store


class KerasModelWrapper:
    """Servable result of :meth:`KerasEstimator.fit` (ref: the
    KerasModel transformer [V])."""

    def __init__(self, model):
        self.model = model

    def predict(self, x):
        return self.model.predict(x, verbose=0)

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.model.save(path)

    @classmethod
    def load(cls, path: str, custom_objects=None) -> "KerasModelWrapper":
        # The saved compile config references the dynamic Distributed*
        # optimizer class, which plain tf.keras.models.load_model can't
        # resolve; the shim's load_model injects the reconstruction
        # factories (the reference ships hvd.keras.load_model for the
        # same reason [V]). compile=False: serving needs no optimizer.
        import horovod_tpu.tensorflow as hvd_tf

        return cls(
            hvd_tf.load_model(
                path, custom_objects=custom_objects, compile=False
            )
        )


class KerasEstimator:
    def __init__(
        self,
        model,
        optimizer=None,
        loss="mse",
        metrics=None,
        store: Optional[Store] = None,
        run_id: str = "run",
        epochs: int = 1,
        batch_size: int = 32,
        custom_objects: Optional[dict] = None,
        verbose: int = 0,
    ):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics or []
        self.store = store
        self.run_id = run_id
        self.epochs = int(epochs)
        self.batch_size = int(batch_size)
        # held for KerasModelWrapper.load(path, custom_objects=...) —
        # custom layers need them at deserialization time
        self.custom_objects = custom_objects
        self.verbose = verbose
        self.history = None

    def fit(self, x, y=None, validation_data=None) -> KerasModelWrapper:
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd
        from horovod_tpu.tensorflow import callbacks as hvd_cb

        hvd.init()
        opt = self.optimizer or tf.keras.optimizers.Adam()
        opt = hvd.DistributedOptimizer(opt)
        self.model.compile(
            optimizer=opt, loss=self.loss, metrics=self.metrics
        )
        callbacks = [
            hvd_cb.BroadcastGlobalVariablesCallback(0),
            hvd_cb.MetricAverageCallback(),
        ]
        ckpt_dir = None
        if self.store is not None:
            ckpt_dir = self.store.checkpoint_dir(self.run_id)
            os.makedirs(ckpt_dir, exist_ok=True)
            os.makedirs(self.store.logs_dir(self.run_id), exist_ok=True)
            # weights-only: the wrapped optimizer is a dynamic
            # subclass (DistributedX) that Keras can't deserialize;
            # weights + architecture are the servable artifact anyway
            callbacks.append(
                tf.keras.callbacks.ModelCheckpoint(
                    os.path.join(
                        ckpt_dir, "ckpt-{epoch:03d}.weights.h5"
                    ),
                    save_weights_only=True,
                )
            )
        self.history = self.model.fit(
            x,
            y,
            epochs=self.epochs,
            batch_size=self.batch_size if y is not None else None,
            validation_data=validation_data,
            callbacks=callbacks,
            verbose=self.verbose,
        )
        return KerasModelWrapper(self.model)
