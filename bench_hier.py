"""Two-level (topology-aware) wire A/B (PR 10, ops/traced.py recipe
family + ops/overlap.py routing).

Measures what the hierarchical decomposition buys on the axis that
matters at multi-slice scale: bytes crossing the DCN hop. Three legs
over the SAME bucketed gradient exchange (a synthetic multi-slice split
of the 8-device mesh, HOROVOD-style intra groups of ``BENCH_INTRA``),
each appending one JSON artifact under BENCH_ARTIFACT_DIR (default
bench_results/hier/):

* ``ab_flat``      — the flat wire: every bucket is one world-axis
  collective; the whole payload crosses the (modeled) DCN boundary.
* ``ab_hier``      — the two-level wire at fp32: intra reduce-scatter
  -> inter collective on the 1/L shard -> intra all-gather; the DCN
  hop carries 1/L of the bytes.
* ``ab_hier_int8`` — the EQuARX placement: same shape, block-scaled
  int8 with stochastic rounding on the inter hop only (~4x less again
  on the scarce hop; ICI hops stay exact).

Each artifact records ms/step, the lowered collective counts (the
compiled-program evidence: per bucket one intra-group reduce-scatter +
one inter-group collective + one intra-group all-gather), and the
PER-HOP byte accounting from the shared payload-width model
(``FusionManager._hop_bytes`` — ring/topology factors cancel in every
ratio): ``inter_bytes`` / ``intra_bytes`` per step and the
``inter_ratio_vs_flat`` each leg achieves. BENCH_DRYRUN=1 is the CI
smoke shape (tiny tree, 2 iters; ``./ci.sh bench-smoke`` gates on the
artifacts AND on the pre-registered prediction that the hier-int8 leg
drops inter-hop bytes >= 3x vs the flat fp32 leg — docs/perf.md).
CPU lines carry the quarantine note: wall-clock claims need the
on-chip capture; the dryrun validates harness + HLO shape + byte
accounting.

Env: BENCH_LAYERS / BENCH_WIDTH / BENCH_BUCKETS / BENCH_INTRA /
BENCH_ITERS / BENCH_DRYRUN / BENCH_ARTIFACT_DIR.
"""

import json
import os
import time

from _benchlib import stamp as _stamp

_SIM_NOTE = (
    "logic-validation only (CPU simulation); step-time is NOT a TPU "
    "wall-clock number — byte accounting and HLO shape are exact"
)


def _collective_counts(lowered) -> dict:
    """Lowered-module collective counts via the shared
    horovod_tpu.analysis parser (same gate as tests/test_hier_wire)."""
    from horovod_tpu import analysis

    return analysis.parse_module(lowered).counts()


def _hop_accounting(bucket_elems, leg, L, H, block):
    """Per-step per-rank wire bytes by hop, payload-width model
    (FusionManager._hop_bytes). The flat leg's whole payload crosses
    the inter (DCN) boundary on a multi-slice world; the hier legs
    cross with the 1/L shard at the inter wire."""
    from horovod_tpu.ops.fusion import FusionManager

    intra = inter = 0
    for elems in bucket_elems:
        if leg == "ab_flat":
            b, _ = FusionManager._hop_bytes(elems, "fp32", 4, L * H, block)
            inter += b
        else:
            ib, _ = FusionManager._hop_bytes(elems, "fp32", 4, L, block)
            intra += ib
            shard = -(-elems // L)
            wire = "int8" if leg == "ab_hier_int8" else "fp32"
            eb, _ = FusionManager._hop_bytes(shard, wire, 4, H, block)
            inter += eb
    return {"intra_bytes": intra, "inter_bytes": inter}


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu.common.compat import shard_map
    from horovod_tpu.common.topology import hierarchical_stage_groups
    from horovod_tpu.ops import overlap
    from horovod_tpu.ops.compression import Compression

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    iters = int(os.environ.get("BENCH_ITERS", "2" if dryrun else "30"))
    layers = int(os.environ.get("BENCH_LAYERS", "4" if dryrun else "16"))
    width = int(os.environ.get("BENCH_WIDTH", "64" if dryrun else "1024"))
    n_buckets = int(os.environ.get("BENCH_BUCKETS", "4"))
    intra = int(os.environ.get("BENCH_INTRA", "4"))
    block = 512

    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "hier")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()
    if world % intra:
        intra = 2 if world % 2 == 0 else 1
    stages = hierarchical_stage_groups(world, intra)
    if stages is None:
        raise SystemExit(
            f"no two-level split for world={world} intra={intra}"
        )
    L, H = intra, world // intra
    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    grads_host = {
        f"g{i:02d}": rng.normal(size=(world, width, width)).astype(
            np.float32
        )
        for i in range(layers)
    }
    grad_bytes = sum(
        int(np.prod(g.shape[1:])) * 4 for g in grads_host.values()
    )

    def make_step(leg):
        hier = None if leg == "ab_flat" else stages
        comp = (
            Compression.int8_block
            if leg == "ab_hier_int8"
            else Compression.none
        )

        def body(t, s):
            local = jax.tree_util.tree_map(lambda x: x[0], t)
            out = overlap.bucketed_allreduce(
                local, op=hvd.Sum, n_buckets=n_buckets,
                min_bucket_bytes=0, compression=comp, seed=s,
                hier_stages=hier,
            )
            return jax.tree_util.tree_map(lambda x: x[None], out)

        return jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(P(hvd.WORLD_AXIS), P()),
                out_specs=P(hvd.WORLD_AXIS),
                check_vma=False,
            )
        )

    def emit(leg, ms, counts, hops, extra=None):
        line = {
            "metric": "hier_ab",
            "leg": leg,
            "world": world,
            "intra": L,
            "slices": H,
            "layers": layers,
            "width": width,
            "grad_bytes": grad_bytes,
            "n_buckets": n_buckets,
            "value": round(ms, 3),
            "unit": "ms/step",
            "platform": platform,
            "collectives": counts,
            **hops,
        }
        if extra:
            line.update(extra)
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)
        with open(
            os.path.join(artifact_dir, f"hier_{leg}.json"), "a"
        ) as f:
            f.write(json.dumps(_stamp(line)) + "\n")

    # the schedule's bucket sizes drive the byte model: build it once
    leaves = [
        np.zeros(g.shape[1:], np.float32) for g in grads_host.values()
    ]
    sched = overlap.build_bucket_schedule(leaves, n_buckets, 0)
    bucket_elems = [b // 4 for b in sched.bucket_bytes]

    flat_hops = None
    results = {}
    for leg in ("ab_flat", "ab_hier", "ab_hier_int8"):
        step = make_step(leg)
        t = {k: jnp.asarray(v) for k, v in grads_host.items()}
        counts = _collective_counts(step.lower(t, jnp.int32(0)))
        out = step(t, jnp.int32(0))  # compile + warm
        _sync(out)
        t0 = time.perf_counter()
        for i in range(iters):
            out = step(t, jnp.int32(i + 1))
        _sync(out)
        ms = (time.perf_counter() - t0) * 1e3 / iters
        hops = _hop_accounting(bucket_elems, leg, L, H, block)
        if leg == "ab_flat":
            flat_hops = hops
        ratio = (
            round(flat_hops["inter_bytes"] / hops["inter_bytes"], 2)
            if hops["inter_bytes"]
            else None
        )
        hops["inter_ratio_vs_flat"] = ratio
        emit(leg, ms, counts, hops)
        results[leg] = (counts, hops)

    # structural gates (valid on every backend): per bucket one
    # intra-group RS + one inter collective + one intra-group AG
    nb = sched.n_buckets
    c_flat, c_hier = results["ab_flat"][0], results["ab_hier"][0]
    assert c_flat["all_reduce"] == nb, c_flat
    assert c_hier["reduce_scatter"] == nb, c_hier
    assert c_hier["all_reduce"] == nb, c_hier
    assert c_hier["all_gather"] == nb, c_hier
    c_q = results["ab_hier_int8"][0]
    assert c_q["reduce_scatter"] == nb, c_q
    assert c_q["all_to_all"] == 2 * nb, c_q  # int8 payload + scales
    # the pre-registered DCN-byte prediction (docs/perf.md): >= L x
    # for hier-fp32, >= 3x for hier-int8 (4L x minus scale overhead)
    assert results["ab_hier"][1]["inter_ratio_vs_flat"] >= L, results
    assert results["ab_hier_int8"][1]["inter_ratio_vs_flat"] >= 3.0, (
        results
    )
    print(
        json.dumps(
            {
                "metric": "hier_ab_summary",
                "inter_ratio_hier": results["ab_hier"][1][
                    "inter_ratio_vs_flat"
                ],
                "inter_ratio_hier_int8": results["ab_hier_int8"][1][
                    "inter_ratio_vs_flat"
                ],
                "gate": "inter bytes drop >=L (fp32) / >=3x (int8)",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
