"""Warm-standby host warmer (``HOROVOD_WARM_STANDBY``).

A standby host is capacity the elastic driver deliberately holds OUT of
the gang so a quarantine / sched-divergence restart or a Router-observed
serve saturation can swap it in WITHOUT a cold start. The warmer is a
small process the driver launches on each reserved host; its lifecycle
(docs/elastic.md) is three KV announcements in the rendezvous
``standby`` scope:

``announce``
    Registered with the driver's rendezvous — the host is reachable and
    the warmer is alive.
``staging``
    Paying the cold-start costs ahead of time: every persistent
    executable-cache entry for this topology is deserialized
    (``exe_cache.preload`` — validates headers, faults the files into
    the page cache, exercises the exact deserialization path the
    swapped-in worker will take) and, when a checkpoint directory is
    configured, the latest digest-verified checkpoint is staged through
    ``CheckpointManager.restore_latest_good``.
``armed``
    Ready. The announcement carries what was staged
    (``exes``/``exe_bytes``/``ckpt_step``) and the warmer settles into
    a keepalive loop, refreshing its ``ts`` so the driver can age out a
    dead warmer.

The driver releases a standby by writing ``release`` under the host's
key in the same scope (or by SIGTERM); the warmer acknowledges with a
``released`` announcement and exits 0, at which point the host is plain
discovery capacity again and the next gang launch includes it.

Runs as ``python -m horovod_tpu.elastic.standby`` with the same
rendezvous env contract as a worker (``HOROVOD_GLOO_RENDEZVOUS_ADDR`` /
``PORT`` / ``HOROVOD_SECRET_KEY``) plus ``HOROVOD_STANDBY_HOSTNAME``.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

from ..common.logging import get_logger

_log = get_logger("standby")

# keepalive cadence for the armed announcement (driver ages out entries
# whose ts stops advancing, same contract as the heartbeat ledger)
KEEPALIVE_S = 5.0


class StandbyWarmer:
    """One standby host's announce → stage → armed lifecycle."""

    def __init__(
        self,
        client,
        hostname: str,
        exe_cache_base: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        fingerprint: Optional[str] = None,
    ) -> None:
        self._client = client
        self.hostname = str(hostname)
        self._exe_base = exe_cache_base
        self._ckpt_dir = checkpoint_dir
        self._fingerprint = fingerprint
        self._stop = threading.Event()
        self.staged: dict = {}

    # ------------------------------------------------------ lifecycle

    def _announce(self, state: str, detail: Optional[dict] = None) -> None:
        from ..runner.rendezvous import put_standby

        try:
            put_standby(self._client, self.hostname, state, detail)
        except Exception:
            # rendezvous going away = job ending; a standby must never
            # crash because the driver it serves is mid-teardown
            _log.debug("standby announce %s failed", state, exc_info=True)

    def stage(self) -> dict:
        """Deserialize cached executables + stage the latest checkpoint.
        Best-effort on every leg: staging is an optimization of the
        swap-in, never a gate on it."""
        self._announce("staging")
        detail: dict = {"exes": 0, "exe_bytes": 0, "ckpt_step": None}
        if self._exe_base:
            try:
                from ..common import exe_cache as _exe_cache

                loaded, nbytes = _exe_cache.preload(
                    fingerprint=self._fingerprint, base=self._exe_base
                )
                detail["exes"] = loaded
                detail["exe_bytes"] = nbytes
            except Exception:
                _log.warning("standby exe preload failed", exc_info=True)
        if self._ckpt_dir and os.path.isdir(self._ckpt_dir):
            try:
                from ..checkpoint import CheckpointManager

                mgr = CheckpointManager(self._ckpt_dir, async_save=False)
                step, _ = mgr.restore_latest_good()
                detail["ckpt_step"] = int(step)
            except FileNotFoundError:
                pass  # no checkpoint yet: nothing to stage
            except Exception:
                _log.warning("standby checkpoint stage failed",
                             exc_info=True)
        self.staged = detail
        return detail

    def _released(self) -> bool:
        """Has the driver released this standby? (``release`` written
        under our key, or the whole scope dropped with a release
        marker.)"""
        from ..runner.rendezvous import STANDBY_SCOPE

        try:
            raw = self._client.get(
                STANDBY_SCOPE, f"release.{self.hostname}"
            )
        except OSError:
            return True  # driver gone: stop holding the host
        return raw is not None

    def run(self) -> int:
        """announce → stage → armed → keepalive until released."""
        self._announce("announce")
        detail = self.stage()
        self._announce("armed", detail)
        _log.info(
            "standby %s armed: %d cached executable(s) (%d bytes), "
            "checkpoint step %s",
            self.hostname, detail["exes"], detail["exe_bytes"],
            detail["ckpt_step"],
        )
        while not self._stop.is_set():
            if self._released():
                self._announce("released", detail)
                _log.info("standby %s released", self.hostname)
                return 0
            self._announce("armed", detail)
            self._stop.wait(KEEPALIVE_S)
        self._announce("released", detail)
        return 0

    def stop(self) -> None:
        self._stop.set()


def main(argv=None) -> int:
    """``python -m horovod_tpu.elastic.standby`` entry point."""
    from ..common import config as config_mod
    from ..runner.rendezvous import _client_from_cfg

    cfg = config_mod.Config.from_env()
    if not (cfg.rendezvous_addr and cfg.rendezvous_port):
        _log.error("standby warmer needs the rendezvous env contract")
        return 2
    hostname = os.environ.get(
        "HOROVOD_STANDBY_HOSTNAME", os.uname().nodename
    )
    warmer = StandbyWarmer(
        _client_from_cfg(cfg),
        hostname,
        exe_cache_base=cfg.exe_cache,
        checkpoint_dir=os.environ.get("HOROVOD_CHECKPOINT_DIR") or None,
        fingerprint=os.environ.get("HOROVOD_STANDBY_FINGERPRINT") or None,
    )

    def _term(signum, frame):  # release on SIGTERM: driver teardown
        warmer.stop()

    signal.signal(signal.SIGTERM, _term)
    return warmer.run()


if __name__ == "__main__":  # pragma: no cover — subprocess entry
    raise SystemExit(main())
