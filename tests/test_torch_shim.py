"""horovod_tpu.torch binding tests — modeled on the reference's
test/parallel/test_torch.py core cases [V]: op x dtype coverage,
in-place variants, DistributedOptimizer step equivalence, and
broadcast_parameters/broadcast_optimizer_state round-trips."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu.torch as hvd_torch  # noqa: E402


@pytest.fixture
def hvdt(hvd):
    """The JAX-side fixture brings the mesh up; the torch shim shares
    the same global state."""
    return hvd_torch


def test_identity_and_size(hvdt):
    assert hvdt.is_initialized()
    assert hvdt.size() >= 1
    assert hvdt.rank() == 0


def test_allreduce_average(hvdt):
    x = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvdt.allreduce(x, op=hvdt.Average)
    # single controller: every rank contributes this tensor
    assert torch.allclose(out, x)
    assert out.dtype == x.dtype


def test_allreduce_sum_scales_by_world(hvdt):
    x = torch.ones(4)
    out = hvdt.allreduce(x, op=hvdt.Sum)
    assert torch.allclose(out, torch.full((4,), float(hvdt.size())))


def test_allreduce_inplace(hvdt):
    x = torch.ones(3)
    ret = hvdt.allreduce_(x, op=hvdt.Sum)
    assert ret is x
    assert torch.allclose(x, torch.full((3,), float(hvdt.size())))


def test_allreduce_async_poll_wait(hvdt):
    x = torch.ones(2)
    handle = hvdt.allreduce_async(x, op=hvdt.Sum)
    out = hvdt.synchronize(handle)
    assert torch.allclose(out, torch.full((2,), float(hvdt.size())))


@pytest.mark.parametrize("dtype", [torch.float32, torch.float64, torch.int32])
def test_allreduce_dtypes(hvdt, dtype):
    x = torch.arange(4).to(dtype)
    out = hvdt.allreduce(x, op=hvdt.Sum)
    assert out.dtype == dtype
    assert torch.equal(out, x * hvdt.size())


def test_allgather(hvdt):
    x = torch.arange(3, dtype=torch.float32)
    out = hvdt.allgather(x)
    assert out.shape == (3 * hvdt.size(),)
    for r in range(hvdt.size()):
        assert torch.allclose(out[r * 3 : (r + 1) * 3], x)


def test_broadcast(hvdt):
    x = torch.full((4,), 3.25)
    out = hvdt.broadcast(x, root_rank=0)
    assert torch.allclose(out, x)
    y = torch.zeros(4)

    # in-place from a replicated payload keeps root's values
    hvdt.broadcast_(x, root_rank=0)
    assert torch.allclose(x, torch.full((4,), 3.25))
    del y


def test_grouped_allreduce(hvdt):
    tensors = [torch.ones(2), torch.full((3,), 2.0)]
    outs = hvdt.grouped_allreduce(tensors, op=hvdt.Average)
    assert torch.allclose(outs[0], torch.ones(2))
    assert torch.allclose(outs[1], torch.full((3,), 2.0))


def test_fp16_compression_roundtrip(hvdt):
    x = torch.randn(8)
    wire, ctx = hvdt.Compression.fp16.compress(x)
    assert wire.dtype == torch.float16
    back = hvdt.Compression.fp16.decompress(wire, ctx)
    assert back.dtype == torch.float32
    assert torch.allclose(back, x, atol=1e-3)


def test_distributed_optimizer_step_equivalence(hvdt):
    """Wrapped SGD must equal manual allreduce + plain SGD (the
    reference's canonical optimizer test [V])."""
    torch.manual_seed(0)
    model_a = torch.nn.Linear(4, 2)
    model_b = torch.nn.Linear(4, 2)
    model_b.load_state_dict(model_a.state_dict())

    opt_a = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model_a.parameters(), lr=0.1),
        named_parameters=model_a.named_parameters(),
        op=hvd_torch.Average,
    )
    opt_b = torch.optim.SGD(model_b.parameters(), lr=0.1)

    x = torch.randn(5, 4)
    y = torch.randn(5, 2)

    def loss_of(m):
        return torch.nn.functional.mse_loss(m(x), y)

    opt_a.zero_grad()
    loss_of(model_a).backward()
    opt_a.step()

    opt_b.zero_grad()
    loss_of(model_b).backward()
    # manual allreduce (average over the world = identity here)
    for p in model_b.parameters():
        p.grad.copy_(hvd_torch.allreduce(p.grad, op=hvd_torch.Average))
    opt_b.step()

    for pa, pb in zip(model_a.parameters(), model_b.parameters()):
        assert torch.allclose(pa, pb, atol=1e-6)


def test_distributed_optimizer_backward_passes_per_step(hvdt):
    """The canonical backward/step/zero_grad loop must apply the SUM of
    all k microbatch gradients — zero_grad between microbatches must not
    discard the aggregation window (ref: local grad aggregation [V])."""
    torch.manual_seed(1)
    model = torch.nn.Linear(2, 1, bias=False)
    ref_model = torch.nn.Linear(2, 1, bias=False)
    ref_model.load_state_dict(model.state_dict())
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        backward_passes_per_step=2,
    )
    batches = [torch.ones(1, 2), torch.full((1, 2), 2.0)]
    before = [p.clone() for p in model.parameters()]
    for x in batches:
        opt.zero_grad()
        model(x).sum().backward()
        opt.step()
    # no update after microbatch 1, update after 2
    assert not torch.equal(next(model.parameters()), before[0])
    # equivalence: one step with the SUM of both microbatch grads
    ref_opt = torch.optim.SGD(ref_model.parameters(), lr=0.1)
    ref_opt.zero_grad()
    for x in batches:
        ref_model(x).sum().backward()  # grads accumulate
    ref_opt.step()
    for p, rp in zip(model.parameters(), ref_model.parameters()):
        assert torch.allclose(p, rp, atol=1e-6)


def test_reducescatter_even(hvdt):
    """Even case: rank 0 (this controller) gets the first dim-0 shard of
    the world-summed tensor."""
    world = hvdt.size()
    x = torch.arange(4 * world, dtype=torch.float32).reshape(4 * world, 1)
    out = hvdt.reducescatter(x, op=hvdt.Sum)
    assert out.shape[0] == 4  # dim0 / world
    assert torch.allclose(out, (x * world)[: out.shape[0]])


def test_reducescatter_uneven(hvdt):
    """Uneven dim0: rank 0 gets the (largest) first shard — v-variant
    semantics (earlier ranks get the extra elements)."""
    world = hvdt.size()
    n = 4 * world + 1 if world > 1 else 3
    x = torch.ones(n, 2)
    out = hvdt.reducescatter(x, op=hvdt.Sum)
    base, rem = divmod(n, world)
    assert out.shape[0] == base + (1 if rem else 0)
    assert torch.allclose(out, torch.full_like(out, float(world)))


def test_alltoall_uneven_splits(hvdt):
    """alltoall with a 1-D splits vector returns (output,
    received_splits) — the reference's torch v-variant [V]."""
    world = hvdt.size()
    splits = [1] * world
    splits[0] = 2
    n = sum(splits)
    x = torch.arange(n * 3, dtype=torch.float32).reshape(n, 3)
    out, recv = hvdt.alltoall(x, splits=splits)
    # every rank sends the same (replicated) tensor; rank 0 receives
    # each rank's first split (2 rows each)
    assert recv.tolist() == [2] * world
    assert out.shape == (2 * world, 3)
    for r in range(world):
        assert torch.allclose(out[2 * r : 2 * r + 2], x[:2])


def test_grouped_allreduce_async_single_handle(hvdt):
    """hvd.synchronize(grouped_allreduce_async(...)) is the reference's
    API shape — the grouped handle must be one waitable object."""
    tensors = [torch.ones(2), torch.full((3,), 2.0)]
    handle = hvd_torch.grouped_allreduce_async(tensors, op=hvdt.Sum)
    outs = hvd_torch.synchronize(handle)
    w = float(hvdt.size())
    assert torch.allclose(outs[0], torch.full((2,), w))
    assert torch.allclose(outs[1], torch.full((3,), 2.0 * w))


def test_accum_buffer_dropped_for_inactive_param(hvdt):
    """A param that participates in one aggregation cycle but not the
    next must not be re-reduced with zeros (stateful optimizers would
    still move it)."""
    a = torch.nn.Linear(2, 1, bias=False)
    b = torch.nn.Linear(2, 1, bias=False)
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.Adam(list(a.parameters()) + list(b.parameters()),
                         lr=0.1),
        backward_passes_per_step=2,
    )
    x = torch.ones(1, 2)
    # cycle 1: both params get grads
    for _ in range(2):
        opt.zero_grad()
        (a(x).sum() + b(x).sum()).backward()
        opt.step()
    frozen = next(b.parameters()).clone()
    # cycle 2: only `a` participates
    for _ in range(2):
        opt.zero_grad()
        a(x).sum().backward()
        opt.step()
    assert torch.equal(next(b.parameters()), frozen)


def test_collectives_accept_process_set(hvdt):
    """process_set threads through the torch surface (global set in the
    1-process suite — sub-mesh correctness is covered by the eager
    tests)."""
    ps = hvdt.global_process_set()
    x = torch.ones(4)
    out = hvdt.allreduce(x, op=hvdt.Sum, process_set=ps)
    assert torch.allclose(out, torch.full((4,), float(hvdt.size())))
    g = hvdt.allgather(x, process_set=ps)
    assert g.shape[0] == 4 * hvdt.size()
    b = hvdt.broadcast(x, root_rank=0, process_set=ps)
    assert torch.allclose(b, x)


def test_backward_passes_flushes_accum_when_boundary_grad_is_none(hvdt):
    """A param that accumulated grads in earlier microsteps but has
    grad None on the boundary microstep must still be reduced and
    stepped with its accumulated sum (regression: it was silently
    dropped and its buffer never flushed)."""
    torch.manual_seed(2)
    a = torch.nn.Linear(2, 1, bias=False)
    b = torch.nn.Linear(2, 1, bias=False)
    ref_a = torch.nn.Linear(2, 1, bias=False)
    ref_a.load_state_dict(a.state_dict())
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(list(a.parameters()) + list(b.parameters()), lr=0.1),
        backward_passes_per_step=2,
    )
    x = torch.ones(1, 2)
    # microstep 1: only `a` participates -> only `a` accumulates
    opt.zero_grad()
    a(x).sum().backward()
    opt.step()
    # microstep 2 (boundary): only `b` participates; `a.grad` is None
    opt.zero_grad()
    b(x).sum().backward()
    opt.step()
    # `a` must have taken a step using its microstep-1 gradient
    ref_opt = torch.optim.SGD(ref_a.parameters(), lr=0.1)
    ref_opt.zero_grad()
    ref_a(x).sum().backward()
    ref_opt.step()
    for p, rp in zip(a.parameters(), ref_a.parameters()):
        assert torch.allclose(p, rp, atol=1e-6)


def test_broadcast_parameters_state_dict(hvdt):
    model = torch.nn.Linear(3, 3)
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    # values survive the round-trip unchanged under a single controller
    assert all(torch.isfinite(p).all() for p in model.parameters())


def test_broadcast_optimizer_state(hvdt):
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    loss = model(torch.ones(2, 3)).sum()
    loss.backward()
    opt.step()
    hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
    # Adam state (step/exp_avg) intact and loadable
    sd = opt.state_dict()
    assert sd["state"], "optimizer state empty after broadcast"


def test_sync_batch_norm_matches_local_bn(hvdt):
    """Stat equivalence vs torch.nn.BatchNorm2d: with every rank seeing
    the same replicated batch, global stats == local stats, so forward,
    input grads, and running stats must match the single-process module
    (ref: horovod/torch/sync_batch_norm.py [V] — the reference's own
    equivalence contract)."""
    torch = pytest.importorskip("torch")
    torch.manual_seed(0)
    x = torch.randn(4, 3, 5, 5, dtype=torch.float64)

    sbn = hvdt.SyncBatchNorm(3, eps=1e-5, momentum=0.1)
    bn = torch.nn.BatchNorm2d(3, eps=1e-5, momentum=0.1)
    sbn.double()
    bn.double()

    xa = x.clone().requires_grad_(True)
    xb = x.clone().requires_grad_(True)
    ya = sbn(xa)
    yb = bn(xb)
    # stats ride the f32 collective path (JAX x64 off), so the
    # equivalence tolerance is f32-level even for f64 modules
    np.testing.assert_allclose(
        ya.detach().numpy(), yb.detach().numpy(), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        sbn.running_mean.numpy(), bn.running_mean.numpy(), rtol=1e-5,
        atol=1e-7,
    )
    # running_var's unbiased correction uses the GLOBAL element count
    # (world×local, like torch.nn.SyncBatchNorm), not the local one —
    # rescale the single-process value before comparing.
    n_local = float(x.numel() // x.shape[1])
    n_global = n_local * hvdt.size()
    biased = (bn.running_var.numpy() - 0.9) / 0.1 * (n_local - 1) / n_local
    expected_var = 0.9 + 0.1 * biased * n_global / (n_global - 1)
    np.testing.assert_allclose(
        sbn.running_var.numpy(), expected_var, rtol=1e-5, atol=1e-7
    )

    ya.sum().backward()
    yb.sum().backward()
    np.testing.assert_allclose(
        xa.grad.numpy(), xb.grad.numpy(), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        sbn.weight.grad.numpy(), bn.weight.grad.numpy(), rtol=1e-4,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        sbn.bias.grad.numpy(), bn.bias.grad.numpy(), rtol=1e-4, atol=1e-6
    )


def test_sync_batch_norm_eval_uses_running_stats(hvdt):
    torch = pytest.importorskip("torch")
    sbn = hvdt.SyncBatchNorm(2)
    with torch.no_grad():
        sbn.running_mean.copy_(torch.tensor([1.0, -1.0]))
        sbn.running_var.copy_(torch.tensor([4.0, 0.25]))
    sbn.eval()
    x = torch.ones(3, 2)
    out = sbn(x)
    expected = np.stack(
        [np.full(3, (1.0 - 1.0) / np.sqrt(4.0 + 1e-5)),
         np.full(3, (1.0 + 1.0) / np.sqrt(0.25 + 1e-5))], axis=1
    )
    np.testing.assert_allclose(out.detach().numpy(), expected, rtol=1e-5)


def test_grouped_allgather_torch(hvdt):
    torch = pytest.importorskip("torch")
    xs = [torch.full((2, 3), float(i)) for i in range(3)]
    outs = hvdt.grouped_allgather(xs)
    n = hvdt.size()
    for i, out in enumerate(outs):
        assert tuple(out.shape) == (2 * n, 3)
        np.testing.assert_allclose(out.numpy(), np.full((2 * n, 3), float(i)))


def test_grouped_reducescatter_torch(hvdt):
    torch = pytest.importorskip("torch")
    n = hvdt.size()
    xs = [torch.arange(2.0 * n) + i for i in range(2)]
    outs = hvdt.grouped_reducescatter(xs, op=hvdt.Sum)
    for i, out in enumerate(outs):
        # rank 0 shard of the world sum
        expected = (np.arange(2.0) + i) * n
        np.testing.assert_allclose(out.numpy(), expected)


def test_alltoall_v_over_process_set_torch(hvdt):
    """Uneven alltoall scoped to a set through the torch shim (the
    former NotImplementedError path)."""
    torch = pytest.importorskip("torch")
    ps = hvdt.add_process_set([0, 2, 4])
    try:
        x = torch.arange(12, dtype=torch.float32).reshape(6, 2)
        out, recv = hvdt.alltoall(x, splits=[1, 2, 3], process_set=ps)
        # rank 0 = first member: receives 1 row from each of 0, 2, 4
        assert out.shape == (3, 2)
        assert recv.tolist() == [1, 1, 1]
        # every member replicates rank-major under the single
        # controller, so the first row is row 0 of member 0's tensor
        np.testing.assert_allclose(out[0].numpy(), x[0].numpy())
    finally:
        hvdt.remove_process_set(ps)


def test_grouped_allreduce_atomic_over_threshold_torch(hvdt):
    """The torch grouped path must ride the eager group machinery: a
    group bigger than the fusion threshold completes in ONE cycle
    (group_table.cc atomicity [V]; the old per-tensor enqueues could
    split mid-group)."""
    torch = pytest.importorskip("torch")
    from horovod_tpu.common import basics

    fusion = basics.state().fusion
    old_threshold = fusion.threshold_bytes
    fusion.threshold_bytes = 64  # tiny: every member crosses it
    try:
        cycles_before = fusion.cycles
        outs = hvdt.grouped_allreduce(
            [torch.ones(64) * (i + 1) for i in range(4)], op=hvdt.Sum
        )
        n = hvdt.size()
        for i, out in enumerate(outs):
            np.testing.assert_allclose(
                out.numpy(), np.full(64, float((i + 1) * n))
            )
        assert fusion.cycles == cycles_before + 1, (
            fusion.cycles, cycles_before
        )
    finally:
        fusion.threshold_bytes = old_threshold


def test_allreduce_result_is_dlpack_zero_copy(hvdt):
    """VERDICT r3 #6: on the CPU jax backend the returned tensor must
    SHARE the XLA result buffer (torch.from_dlpack), not copy it —
    asserted by pointer identity against the jax row."""
    torch = pytest.importorskip("torch")
    from horovod_tpu.torch import _jax_to_torch

    import jax.numpy as jnp

    row = jnp.arange(1 << 20, dtype=jnp.float32)  # 4 MB result stand-in
    like = torch.empty(1, dtype=torch.float32)
    out = _jax_to_torch(row, like)
    jax_ptr = row.addressable_data(0).unsafe_buffer_pointer()
    assert out.data_ptr() == jax_ptr, "expected dlpack buffer sharing"

    # and end-to-end through the public op: correct values, no crash on
    # a big tensor (the 100 MB-class path the VERDICT names)
    big = torch.ones(25_000_000, dtype=torch.float32)  # 100 MB
    reduced = hvdt.allreduce(big, op=hvdt.Sum)
    assert float(reduced[0]) == 8.0  # world=8 replicated sum
    assert reduced.shape == big.shape


def test_dlpack_fallback_dtype_mismatch(hvdt):
    """A dtype the caller wants converted still round-trips (the .to()
    conversion path), and the fallback numpy path stays correct."""
    torch = pytest.importorskip("torch")
    x = torch.arange(6, dtype=torch.float64)
    out = hvdt.allreduce(x, op=hvdt.Sum)
    assert out.dtype == torch.float64
    np.testing.assert_allclose(out.numpy(), x.numpy() * 8)


def test_nonmember_alltoall_output_does_not_alias_input(hvdt):
    """The identity pass-through must COPY: a dlpack view would let
    mutations of the output corrupt the caller's input tensor."""
    torch = pytest.importorskip("torch")
    import warnings as _w

    ps = hvdt.add_process_set([1, 2])
    try:
        x = torch.arange(6, dtype=torch.float32).reshape(6, 1)
        with _w.catch_warnings():
            _w.simplefilter("ignore")  # the non-member warning is tested elsewhere
            out, recv = hvdt.alltoall(x, splits=[3, 3], process_set=ps)
        assert out.data_ptr() != x.data_ptr()
        out.mul_(2)
        np.testing.assert_array_equal(
            x.numpy(), np.arange(6, dtype=np.float32).reshape(6, 1)
        )
    finally:
        hvdt.remove_process_set(ps)


def test_allreduce_prescale_postscale(hvdt):
    """prescale/postscale ride through to the eager path (ref: the
    reference's allreduce prescale_factor/postscale_factor args [V])."""
    x = torch.full((3,), 4.0)
    out = hvdt.allreduce(
        x, op=hvdt.Sum, prescale_factor=0.5, postscale_factor=10.0
    )
    want = 4.0 * 0.5 * hvdt.size() * 10.0
    assert torch.allclose(out, torch.full((3,), want))


def test_grouped_allreduce_prescale(hvdt):
    xs = [torch.ones(2), torch.full((2,), 2.0)]
    outs = hvdt.grouped_allreduce(xs, op=hvdt.Sum, prescale_factor=2.0)
    assert torch.allclose(outs[0], torch.full((2,), 2.0 * hvdt.size()))
    assert torch.allclose(outs[1], torch.full((2,), 4.0 * hvdt.size()))


def test_torch_barrier(hvd):
    """hvd.torch.barrier parity (ref: horovod.torch.barrier [V])."""
    import horovod_tpu.torch as hvdt

    hvdt.barrier()
    ps = hvdt.add_process_set([0, 1])
    try:
        hvdt.barrier(process_set=ps)
    finally:
        hvdt.remove_process_set(ps)
