"""FSDP-style parameter sharding for the jit/GSPMD path.

Beyond the reference's surface (Horovod replicates parameters on every
rank). Where ``ShardedDistributedOptimizer`` shards the *optimizer
update* with explicit collectives inside ``shard_map``, this module
serves the **jit + NamedSharding** style: annotate each parameter leaf
as sharded along the data axis and let GSPMD insert the all-gathers
(before use) and reduce-scatters (for grads) — the XLA
weight-update-sharding recipe (PAPERS.md arXiv:2004.13336; the
scaling-book FSDP axis). Parameters, gradients, and optimizer state
then all live 1/N-sharded in HBM with no manual collective code.

Usage::

    shardings = fsdp_sharding(params, mesh)          # pytree of NamedSharding
    params = fsdp_shard(params, mesh)                # device_put accordingly
    opt_state = jax.tree.map(...)                    # init from sharded params
    step = jax.jit(train_step, donate_argnums=(0, 1))
    # XLA inserts gather/scatter; batch rides P(axis) as usual

Sharding rule per leaf: the largest dimension divisible by the axis
size is sharded; leaves with no divisible dimension or fewer than
``min_elems`` elements replicate (tiny leaves cost more to gather than
they save). This is deliberately static and predictable — no cost
model, same rule every run.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.topology import WORLD_AXIS


def fsdp_spec(
    leaf, axis_size: int, axis: str = WORLD_AXIS, min_elems: int = 2**14
) -> P:
    """PartitionSpec for one leaf under the FSDP rule."""
    shape = np.shape(leaf)
    if int(np.prod(shape, dtype=np.int64)) < min_elems:
        return P()
    best_dim, best_len = None, 0
    for d, length in enumerate(shape):
        if length % axis_size == 0 and length > best_len:
            best_dim, best_len = d, length
    if best_dim is None:
        return P()
    spec = [None] * len(shape)
    spec[best_dim] = axis
    return P(*spec)


def fsdp_sharding(
    params,
    mesh: Mesh,
    axis: str = WORLD_AXIS,
    min_elems: int = 2**14,
):
    """Pytree of NamedShardings implementing the FSDP rule over ``mesh``."""
    n = int(np.prod([mesh.shape[a] for a in ([axis] if isinstance(axis, str) else axis)]))
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(
            mesh, fsdp_spec(x, n, axis=axis, min_elems=min_elems)
        ),
        params,
    )


def fsdp_shard(
    params,
    mesh: Mesh,
    axis: str = WORLD_AXIS,
    min_elems: int = 2**14,
):
    """device_put every leaf onto its FSDP sharding (1/N of each large
    leaf per rank; XLA gathers on use)."""
    shardings = fsdp_sharding(params, mesh, axis=axis, min_elems=min_elems)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
