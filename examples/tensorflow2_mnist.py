"""TensorFlow-2-shim MNIST — the reference's canonical TF2 example,
ported by changing one import (ref:
examples/tensorflow2/tensorflow2_mnist.py [V]: init →
DistributedGradientTape → broadcast_variables after first step).

Synthetic MNIST-shaped data keeps the example hermetic (no downloads).

Run (CPU simulation): JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/tensorflow2_mnist.py --steps 20
"""

import argparse
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def build_model():
    return tf.keras.Sequential(
        [
            tf.keras.layers.Conv2D(8, 3, activation="relu"),
            tf.keras.layers.MaxPooling2D(),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(10),
        ]
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=64)
    args = parser.parse_args()

    hvd.init()
    tf.random.set_seed(0)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(512,))
    x += y[:, None, None, None] * 0.1

    model = build_model()
    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True
    )
    opt = tf.keras.optimizers.SGD(learning_rate=0.01 * hvd.size())

    first = True
    losses = []
    for step in range(args.steps):
        idx = rng.integers(0, 512, size=(args.batch_size,))
        xb = tf.constant(x[idx])
        yb = tf.constant(y[idx])
        with tf.GradientTape() as tape:
            loss = loss_obj(yb, model(xb, training=True))
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first:
            # Broadcast AFTER the first step so optimizer slots exist —
            # the reference's documented ordering [V].
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
            first = False
        losses.append(float(loss))
        if step % 10 == 0:
            print(f"step {step}: loss {losses[-1]:.4f}")
    assert np.mean(losses[-10:]) < np.mean(losses[:10])
    print("tf2 shim example done")


if __name__ == "__main__":
    main()
