"""On-chip numerics smoke: padded flash attention (lengths= / SMEM
scalar spec) and the block-512 defaults, fwd+bwd vs an fp32 dense
oracle.  Prints ALL OK on success (chipwork smoke() gate).

Oracle discipline (VERDICT r4 Weak #5): the dense reference is computed
entirely in fp32 with the same masking semantics the kernel documents
(pad region zeroed in outputs and gradients).
"""
import numpy as np
import jax
import jax.numpy as jnp

assert jax.devices()[0].platform == "tpu"

from horovod_tpu.ops import flash_attention as fa


def dense_padded(q, k, v, causal, lengths):
    b, t, h, d = q.shape
    valid = jnp.arange(t)[None, :] < lengths[:, None]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return jnp.where(valid[:, None, :, None].transpose(0, 2, 1, 3), o, 0.0)


rng = np.random.default_rng(0)
b, t, h, d = 2, 512, 4, 64
q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
           for _ in range(3))
lengths = jnp.asarray([512, 301], jnp.int32)
ok = True

# 1) padded path fwd + grads at the block-512 default (SMEM lens spec)
out = fa.flash_attention(q, k, v, causal=True, lengths=lengths)
ref = dense_padded(q, k, v, True, lengths)
err = float(jnp.max(jnp.abs(out - ref)))
print("padded fwd maxerr", err)
ok &= err < 2e-3
rg = jax.grad(lambda q, k, v: (dense_padded(q, k, v, True, lengths)).sum(),
              argnums=(0, 1, 2))(q, k, v)
gg = jax.grad(lambda q, k, v: fa.flash_attention(
    q, k, v, causal=True, lengths=lengths).sum(), argnums=(0, 1, 2))(q, k, v)
for name, a, bb in zip(("dq", "dk", "dv"), gg, rg):
    e = float(jnp.max(jnp.abs(a - bb)))
    print("padded", name, "maxerr", e)
    ok &= e < 2e-3
pad_zero = float(jnp.max(jnp.abs(gg[0][1, 301:])))
print("padded dq pad-region max", pad_zero)
ok &= pad_zero == 0.0


# 2) unpadded fwd+bwd at the 512 default vs dense
def dense(q, k, v):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


e = float(jnp.max(jnp.abs(
    fa.flash_attention(q, k, v, causal=True) - dense(q, k, v))))
print("blk512 fwd maxerr", e)
ok &= e < 2e-3

print("ALL OK" if ok else "SMOKE FAIL")
