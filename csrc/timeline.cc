// Timeline event ring buffer.
//
// TPU-native rebuild of the reference's timeline writer core (ref:
// horovod/common/timeline.cc/.h — SURVEY.md §5.1). The reference
// buffers per-tensor lifecycle events in C++ on the background thread
// and serializes Chrome-trace JSON off the hot path; here the Python
// layer (horovod_tpu/common/timeline.py) formats each event once and
// hands the string to this buffer, so the per-event cost on the
// dispatch path is one lock + one string append instead of a Python
// list append holding the GIL, and drain() hands everything back for
// the final file write.

#include "export.h"

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct TimelineBuffer {
  std::mutex mu;
  std::vector<std::string> events;
  long total_bytes = 0;  // sum of event lengths (excl. separators)
};

}  // namespace

HVD_EXPORT void* hvd_tl_create() { return new TimelineBuffer(); }

HVD_EXPORT void hvd_tl_destroy(void* h) {
  delete static_cast<TimelineBuffer*>(h);
}

HVD_EXPORT void hvd_tl_emit(void* h, const char* json) {
  auto* tl = static_cast<TimelineBuffer*>(h);
  std::lock_guard<std::mutex> lock(tl->mu);
  tl->events.emplace_back(json);
  tl->total_bytes += static_cast<long>(tl->events.back().size());
}

HVD_EXPORT long hvd_tl_count(void* h) {
  auto* tl = static_cast<TimelineBuffer*>(h);
  std::lock_guard<std::mutex> lock(tl->mu);
  return static_cast<long>(tl->events.size());
}

// Bytes needed for drain(): every event plus one '\n' separator each.
HVD_EXPORT long hvd_tl_drain_size(void* h) {
  auto* tl = static_cast<TimelineBuffer*>(h);
  std::lock_guard<std::mutex> lock(tl->mu);
  return tl->total_bytes + static_cast<long>(tl->events.size());
}

// Write all buffered events into dst, newline-separated, and clear the
// buffer. Returns bytes written, or -1 if cap is too small (buffer is
// left intact so the caller can retry with hvd_tl_drain_size()).
HVD_EXPORT long hvd_tl_drain(void* h, char* dst, long cap) {
  auto* tl = static_cast<TimelineBuffer*>(h);
  std::lock_guard<std::mutex> lock(tl->mu);
  long need = tl->total_bytes + static_cast<long>(tl->events.size());
  if (need > cap) return -1;
  long off = 0;
  for (const auto& e : tl->events) {
    std::memcpy(dst + off, e.data(), e.size());
    off += static_cast<long>(e.size());
    dst[off++] = '\n';
  }
  tl->events.clear();
  tl->total_bytes = 0;
  return off;
}
