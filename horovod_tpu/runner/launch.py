"""``hvdrun`` — the launcher CLI.

TPU-native rebuild of ``horovodrun`` (ref: horovod/runner/launch.py
`run_commandline` + gloo_run.py/mpi_run.py [V] — SURVEY.md §2.5, §3.3;
empty mount, structural citations).

Where the reference picks between mpirun and SSH+Gloo, this launcher has
two placement modes:

* **per-host** (TPU pods): one process per host driving all local chips —
  the JAX single-controller-per-host model. Remote hosts are reached via
  ssh exactly like the reference's gloo_run.
* **per-slot** (localhost / tests): one process per rank, each seeing one
  CPU device, wired together with ``jax.distributed`` — the moral
  equivalent of the reference's multi-process localhost testing mode
  (SURVEY.md §4).

Either way the driver: generates a per-job HMAC secret, starts the HTTP
KV rendezvous, exports the ``HOROVOD_*`` env contract + coordinator
address to every worker, watches exit codes, and tears everything down
on first failure (ref §3.3 failure path).

Usage:
    python -m horovod_tpu.runner -np 4 python train.py
    python -m horovod_tpu.runner -np 8 -H host1:4,host2:4 python train.py
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from .hosts import HostInfo, SlotInfo, assign_slots, parse_hostfile, parse_hosts
from .rendezvous import RendezvousServer
from .secret import make_secret_key

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def _is_local(hostname: str) -> bool:
    return hostname in _LOCAL_NAMES or hostname == socket.gethostname()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _load_config_file(path: str, parser: argparse.ArgumentParser) -> dict:
    """Parse a hvdrun params YAML (ref: horovodrun --config-file,
    upstream runner/launch.py [V]) into argparse defaults.

    Format: a mapping whose keys are the long option names (dashes or
    underscores both accepted); one level of nesting joins section and
    key with a dash, so

        num-proc: 8
        cycle-time-ms: 3.5
        fusion:
          threshold-mb: 32
        autotune: true

    sets --num-proc/--cycle-time-ms/--fusion-threshold-mb/--autotune.
    Precedence (documented contract): explicit CLI flags > config file
    > built-in defaults — the file is applied via parser defaults, so a
    flag given on the command line always wins. Unknown keys fail fast.
    """
    import yaml

    try:
        with open(path) as f:
            data = yaml.safe_load(f) or {}
    except OSError as e:
        raise SystemExit(f"--config-file {path}: {e}") from None
    except yaml.YAMLError as e:
        raise SystemExit(f"--config-file {path}: invalid YAML: {e}") from None
    if not isinstance(data, dict):
        raise SystemExit(
            f"--config-file {path}: expected a YAML mapping, got "
            f"{type(data).__name__}"
        )
    flat: dict = {}
    for k, v in data.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}-{k2}"] = v2
        else:
            flat[k] = v
    by_dest = {a.dest: a for a in parser._actions}
    out = {}
    for k, v in flat.items():
        dest = str(k).replace("-", "_")
        if dest in ("help", "command", "config_file") or dest not in by_dest:
            raise SystemExit(
                f"--config-file {path}: unknown parameter {k!r} "
                "(keys are hvdrun's long option names)"
            )
        action = by_dest[dest]
        if isinstance(action, argparse._StoreTrueAction):
            v = bool(v)
        elif action.type is not None and v is not None:
            v = action.type(v)
        out[dest] = v
    return out


def parse_args(argv: Optional[Sequence[str]] = None) -> argparse.Namespace:
    """Flag surface mirrors horovodrun's (launch.py [V]); flags that
    configure the runtime translate into HOROVOD_* env for workers, same
    as the reference."""
    argv = list(sys.argv[1:] if argv is None else argv)
    # Pre-scan so config-file values can satisfy the -np requirement.
    # The scan walks hvdrun's OWN flags only: it stops at "--" or at the
    # first positional (where the REMAINDER command begins), skipping
    # each value-taking flag's argument, so a --config-file belonging to
    # the launched program is never misread as ours.
    no_value_flags = {
        "--verbose", "--timeline-mark-cycles", "--autotune",
        "--hierarchical-allreduce", "--gloo", "--mpi", "-h", "--help",
        "-cb", "--check-build",
    }
    check_build = False
    config_path = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--":
            break
        if a in ("-cb", "--check-build"):
            check_build = True
            i += 1
        elif a.startswith("--config-file="):
            config_path = a.split("=", 1)[1]
            i += 1
        elif a == "--config-file":
            if i + 1 < len(argv):
                config_path = argv[i + 1]
            i += 2
        elif a.startswith("-"):
            i += 1 if (a in no_value_flags or "=" in a) else 2
        else:
            break  # first positional = start of the launched command
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu job across hosts/chips.",
        # abbreviations would desync the exact-string pre-scan above
        # (e.g. --config would reach argparse but not the scan)
        allow_abbrev=False,
    )
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print the framework/controller/op build summary "
                        "and exit (ref: horovodrun --check-build [V])")
    p.add_argument("--config-file", default=None,
                   help="params YAML; CLI flags override its values "
                        "(keys = long option names, one nesting level "
                        "joins with a dash)")
    p.add_argument("-np", "--num-proc", type=int,
                   required=config_path is None and not check_build,
                   help="total number of ranks (chips)")
    p.add_argument("-H", "--hosts", default=None,
                   help="comma-separated host:slots list")
    p.add_argument("--hostfile", default=None,
                   help="file with one 'host slots=N' per line")
    p.add_argument("--placement", choices=("per-host", "per-slot", "auto"),
                   default="auto",
                   help="process placement: per-host (TPU pods), per-slot "
                        "(localhost CPU simulation), auto = per-slot iff "
                        "all hosts are local")
    p.add_argument("--start-timeout", type=float, default=600.0)
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("--coordinator-port", type=int, default=9874,
                   help="fixed port for the jax.distributed coordinator "
                        "on the first worker host (multi-host jobs; "
                        "local jobs pick a free port automatically)")
    p.add_argument("--output-filename", default=None,
                   help="redirect each worker's stdout/stderr to "
                        "<output-filename>/rank.<N>.{out,err}")
    p.add_argument("--verbose", action="store_true")
    # runtime knobs forwarded as env (parity with horovodrun flags [V])
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--log-level", default=None)
    p.add_argument("--stall-timeout", type=float, default=None)
    p.add_argument("--hierarchical-allreduce", action="store_true")
    # elastic mode (ref: horovodrun --host-discovery-script/--min-np/
    # --max-np, horovod/runner/launch.py [V]): supervises gangs through
    # elastic.ElasticDriver instead of a one-shot launch
    p.add_argument("--host-discovery-script", default=None,
                   help="executable printing 'host:slots' per line; "
                        "presence switches hvdrun into elastic mode")
    p.add_argument("--min-np", type=int, default=None,
                   help="elastic: minimum world size (default: -np)")
    p.add_argument("--max-np", type=int, default=None,
                   help="elastic: maximum world size (default: -np)")
    p.add_argument("--slots-per-host", type=int, default=None,
                   help="elastic: override slots per discovered host")
    p.add_argument("--reset-limit", type=int, default=None,
                   help="elastic: max gang restarts before giving up")
    # accepted for script compat; the data plane is always XLA/ICI here
    p.add_argument("--gloo", action="store_true",
                   help="accepted for compatibility (no-op: TPU data "
                        "plane is XLA collectives)")
    p.add_argument("--mpi", action="store_true",
                   help="accepted for compatibility (no-op)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and args to launch on every worker")
    if config_path is not None:
        p.set_defaults(**_load_config_file(config_path, p))
    args = p.parse_args(argv)
    if args.num_proc is None and not args.check_build:
        p.error("-np/--num-proc is required (on the CLI or in "
                "--config-file)")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    return args


def _resolve_hosts(args: argparse.Namespace) -> List[HostInfo]:
    if args.hosts and args.hostfile:
        raise ValueError("use either -H/--hosts or --hostfile, not both")
    if args.hosts:
        return parse_hosts(args.hosts)
    if args.hostfile:
        return parse_hostfile(args.hostfile)
    # No explicit hosts: consult TPU slice metadata (the reference's
    # NIC-probing slot, SURVEY §2.5 → tpu_discovery) before assuming a
    # single local machine.
    from .tpu_discovery import discover_hosts

    hosts = discover_hosts()
    if len(hosts) == 1 and _is_local(hosts[0].hostname):
        # single-host: allow oversubscription up to the requested np
        return [HostInfo(hosts[0].hostname, max(hosts[0].slots, args.num_proc))]
    return hosts


def _runtime_env(args: argparse.Namespace) -> Dict[str, str]:
    """CLI flags → HOROVOD_* env, the same translation horovodrun does
    (launch.py [V])."""
    env: Dict[str, str] = {}
    if args.fusion_threshold_mb is not None:
        env["HOROVOD_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024)
        )
    if args.cycle_time_ms is not None:
        env["HOROVOD_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        env["HOROVOD_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.timeline_filename:
        env["HOROVOD_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if args.autotune:
        env["HOROVOD_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HOROVOD_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.log_level:
        env["HOROVOD_LOG_LEVEL"] = args.log_level
    if args.stall_timeout is not None:
        env["HOROVOD_STALL_CHECK_TIME_SECONDS"] = str(args.stall_timeout)
    if args.hierarchical_allreduce:
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    return env


def worker_envs(
    slots: Sequence[SlotInfo],
    placement: str,
    rendezvous_addr: str,
    rendezvous_port: int,
    coordinator_port: int,
    secret_hex: str,
    extra: Optional[Dict[str, str]] = None,
) -> List[Dict[str, str]]:
    """Build the per-process environment blocks.

    per-host: one block per host (lead slot), process drives local_size
    chips. per-slot: one block per rank, each process is its own "host"
    with one device (CPU backend, jax.distributed over localhost).
    """
    extra = dict(extra or {})
    blocks: List[Dict[str, str]] = []
    if placement == "per-host":
        leads = [s for s in slots if s.local_rank == 0]
        n_proc = len(leads)
        for i, s in enumerate(leads):
            env = s.to_env()
            env.update(extra)
            env["HOROVOD_NUM_PROCESSES"] = str(n_proc)
            env["HOROVOD_PROCESS_ID"] = str(i)
            blocks.append(env)
    elif placement == "per-slot":
        n_proc = len(slots)
        for i, s in enumerate(slots):
            # each rank is a standalone 1-chip "host"
            env = SlotInfo(
                hostname=s.hostname,
                rank=s.rank,
                size=s.size,
                local_rank=0,
                local_size=1,
                cross_rank=i,
                cross_size=n_proc,
            ).to_env()
            env.update(extra)
            env["HOROVOD_NUM_PROCESSES"] = str(n_proc)
            env["HOROVOD_PROCESS_ID"] = str(i)
            env.setdefault("JAX_PLATFORMS", "cpu")
            # Per-slot is the CPU-backend local mode by contract; an
            # ambient axon/TPU PJRT plugin would override JAX_PLATFORMS
            # via sitecustomize and every rank would sit in the
            # exclusive chip-claim queue until start_timeout. Empty
            # pool = plugin registers nothing, CPU wins. Caller-passed
            # env (extra) still overrides.
            env.setdefault("PALLAS_AXON_POOL_IPS", "")
            # One device per slot, whatever the ambient XLA_FLAGS say —
            # an inherited --xla_force_host_platform_device_count=8
            # (e.g. from a test harness) would give every rank 8 local
            # devices and a 8*np-device world. Caller-passed flags (via
            # `extra`) are preserved; only the device-count token is
            # replaced.
            base_flags = env.get(
                "XLA_FLAGS", os.environ.get("XLA_FLAGS", "")
            )
            kept = [
                token
                for token in base_flags.split()
                if "xla_force_host_platform_device_count" not in token
            ]
            env["XLA_FLAGS"] = " ".join(
                kept + ["--xla_force_host_platform_device_count=1"]
            )
            blocks.append(env)
    else:
        raise ValueError(f"unknown placement {placement!r}")
    # The jax.distributed coordinator runs inside process 0, i.e. on the
    # FIRST WORKER's host — not on the driver (which may be a separate
    # head node). Workers must dial that host. Loopback is only valid
    # when EVERY worker is local; in a mixed job remote workers need a
    # routable name for host 0.
    coordinator_host = blocks[0]["HOROVOD_HOSTNAME"]
    if all(_is_local(b["HOROVOD_HOSTNAME"]) for b in blocks):
        coordinator_host = "127.0.0.1"
    for env in blocks:
        env["HOROVOD_CONTROLLER"] = "tpu"
        env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = rendezvous_addr
        env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(rendezvous_port)
        env["HOROVOD_SECRET_KEY"] = secret_hex
        if int(env["HOROVOD_NUM_PROCESSES"]) > 1:
            env["HOROVOD_COORDINATOR_ADDR"] = coordinator_host
            env["HOROVOD_COORDINATOR_PORT"] = str(coordinator_port)
    return blocks


def _ssh_wrap(hostname: str, ssh_port: Optional[int],
              env: Dict[str, str], command: Sequence[str]) -> List[str]:
    """Remote exec via ssh with explicit env exports — the reference's
    gloo_run launch shape (gloo_run.py [V]).

    The HMAC secret is deliberately NOT exported on the command line
    (visible to every local user via /proc/<pid>/cmdline); it is read
    from ssh's stdin instead — launch_processes pipes it in.
    """
    env = {k: v for k, v in env.items() if k != "HOROVOD_SECRET_KEY"}
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
    )
    remote = (
        "IFS= read -r HOROVOD_SECRET_KEY; export HOROVOD_SECRET_KEY; "
        f"cd {shlex.quote(os.getcwd())} && env {exports} "
        + " ".join(shlex.quote(c) for c in command)
    )
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    cmd += [hostname, remote]
    return cmd


def launch_processes(
    blocks: List[Dict[str, str]],
    command: Sequence[str],
    hostnames: List[str],
    ssh_port: Optional[int] = None,
    output_filename: Optional[str] = None,
    start_timeout: float = 600.0,
    verbose: bool = False,
) -> int:
    """Start every worker, wait, kill the rest on first failure.

    Returns the first non-zero exit code, or 0. (ref §3.3: "driver
    collects exit codes; on any nonzero → terminate all".)
    """
    procs: List[subprocess.Popen] = []
    files = []
    try:
        for env_block, hostname in zip(blocks, hostnames):
            secret_stdin = None
            if _is_local(hostname):
                full_env = dict(os.environ)
                full_env.update(env_block)
                # Workers must resolve the same horovod_tpu the driver
                # runs from, even when launched as `python script.py`
                # (script-dir-only sys.path).
                cwd = os.getcwd()
                prior = full_env.get("PYTHONPATH")
                full_env["PYTHONPATH"] = (
                    cwd if not prior else cwd + os.pathsep + prior
                )
                cmd = list(command)
            else:
                full_env = None
                cmd = _ssh_wrap(hostname, ssh_port, env_block, command)
                secret_stdin = env_block.get("HOROVOD_SECRET_KEY", "")
            stdout = stderr = None
            if output_filename:
                os.makedirs(output_filename, exist_ok=True)
                r = env_block["HOROVOD_RANK"]
                stdout = open(os.path.join(output_filename, f"rank.{r}.out"), "wb")
                stderr = open(os.path.join(output_filename, f"rank.{r}.err"), "wb")
                files += [stdout, stderr]
            if verbose:
                print(f"[hvdrun] rank {env_block['HOROVOD_RANK']} on "
                      f"{hostname}: {' '.join(cmd)}", file=sys.stderr)
            proc = subprocess.Popen(
                cmd, env=full_env, stdout=stdout, stderr=stderr,
                stdin=subprocess.PIPE if secret_stdin is not None else None,
            )
            if secret_stdin is not None:
                proc.stdin.write(secret_stdin.encode() + b"\n")
                proc.stdin.close()
            procs.append(proc)
        deadline = time.monotonic() + start_timeout
        exit_code = 0
        pending = set(range(len(procs)))
        while pending:
            for i in list(pending):
                rc = procs[i].poll()
                if rc is not None:
                    pending.discard(i)
                    if rc != 0 and exit_code == 0:
                        exit_code = rc
                        for j in pending:
                            procs[j].send_signal(signal.SIGTERM)
                        deadline = min(deadline, time.monotonic() + 15)
            if pending:
                if time.monotonic() > deadline:
                    for j in pending:
                        procs[j].kill()
                    if exit_code == 0:
                        exit_code = 124
                    break
                time.sleep(0.05)
        for prc in procs:
            try:
                prc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                prc.kill()
        return exit_code
    finally:
        # A mid-spawn exception must not orphan already-started workers.
        for prc in procs:
            if prc.poll() is None:
                prc.kill()
        for f in files:
            f.close()


def _run_elastic(args: argparse.Namespace) -> int:
    """Elastic mode: hand the job to ElasticDriver (ref: horovodrun's
    elastic launch, gloo_run elastic path [V])."""
    from ..elastic.driver import ElasticDriver
    from ..elastic.discovery import HostDiscoveryScript

    # `is None` (not `or`): --min-np 0 is an explicit value, not unset
    min_np = args.num_proc if args.min_np is None else args.min_np
    max_np = args.num_proc if args.max_np is None else args.max_np
    if min_np < 1 or max_np < min_np:
        raise SystemExit(
            f"hvdrun: inconsistent elastic bounds min_np={min_np} "
            f"max_np={max_np} (need 1 <= min-np <= max-np)"
        )
    driver = ElasticDriver(
        discovery=HostDiscoveryScript(args.host_discovery_script),
        command=args.command,
        min_np=min_np,
        max_np=max_np,
        slots_per_host=args.slots_per_host,
        placement=args.placement,
        start_timeout=args.start_timeout,
        output_filename=args.output_filename,
        reset_limit=args.reset_limit,
        extra_env=_runtime_env(args),
        ssh_port=args.ssh_port,
        verbose=args.verbose,
    )
    try:
        return driver.run()
    finally:
        driver.shutdown()


def _check_build() -> int:
    """Print the build summary (ref: horovodrun --check-build, which
    renders Available Frameworks / Controllers / Tensor Operations from
    the compiled-in feature set [V]). Here the feature set is determined
    at runtime: framework rows probe the shim imports, controller and
    op rows come from the basics predicates — the data plane is always
    XLA collectives over ICI, so the op column reports [X] XLA and [ ]
    for every GPU-era transport the reference could compile in."""
    from horovod_tpu.common import basics

    def _probe(modname):
        try:
            __import__(modname)
            return True
        except Exception:
            return False

    def box(flag):
        return "[X]" if flag else "[ ]"

    lines = [
        "Horovod-TPU v" + getattr(
            __import__("horovod_tpu"), "__version__", "?"),
        "",
        "Available Frameworks:",
        f"    {box(True)} JAX / Flax",
        f"    {box(_probe('torch'))} PyTorch (host bridge)",
        f"    {box(_probe('tensorflow'))} TensorFlow (host bridge)",
        f"    {box(_probe('mxnet'))} MXNet (host bridge)",
        "",
        "Available Controllers:",
        f"    {box(basics.mpi_built())} MPI",
        f"    {box(basics.gloo_built())} Gloo",
        f"    {box(True)} jax.distributed (TPU coordination service)",
        "",
        "Available Tensor Operations:",
        f"    {box(basics.nccl_built())} NCCL",
        f"    {box(basics.ddl_built())} DDL",
        f"    {box(basics.ccl_built())} CCL",
        f"    {box(basics.mpi_built())} MPI",
        f"    {box(basics.gloo_built())} Gloo",
        f"    {box(basics.xla_built())} XLA collectives (ICI/DCN)",
    ]
    print("\n".join(lines))
    return 0


def run_commandline(argv: Optional[Sequence[str]] = None) -> int:
    args = parse_args(argv)
    if args.check_build:
        return _check_build()
    if not args.command:
        print("hvdrun: no command given", file=sys.stderr)
        return 2
    if args.host_discovery_script:
        return _run_elastic(args)
    hosts = _resolve_hosts(args)
    slots = assign_slots(hosts, args.num_proc)
    placement = args.placement
    if placement == "auto":
        placement = (
            "per-slot" if all(_is_local(h.hostname) for h in hosts)
            else "per-host"
        )
    secret = make_secret_key()
    server = RendezvousServer(secret_key=secret)
    rendezvous_port = server.start()
    all_local = all(_is_local(h.hostname) for h in hosts)
    addr = "127.0.0.1" if all_local else socket.getfqdn()
    # Local: probe a genuinely free port (driver host == coordinator
    # host). Remote: the coordinator binds on the first worker, which we
    # cannot probe from here — use the fixed, documented port.
    coordinator_port = _free_port() if all_local else args.coordinator_port
    try:
        blocks = worker_envs(
            slots, placement, addr, rendezvous_port, coordinator_port,
            secret.hex(), extra=_runtime_env(args),
        )
        hostnames = [b["HOROVOD_HOSTNAME"] for b in blocks]
        return launch_processes(
            blocks, args.command, hostnames,
            ssh_port=args.ssh_port,
            output_filename=args.output_filename,
            start_timeout=args.start_timeout,
            verbose=args.verbose,
        )
    finally:
        server.stop()


def run(
    command: Sequence[str],
    np: int,
    hosts: Optional[str] = None,
    **cli_kwargs,
) -> int:
    """Programmatic launch — parity with ``horovod.run.run()`` [V]."""
    argv: List[str] = ["-np", str(np)]
    if hosts:
        argv += ["-H", hosts]
    for key, value in cli_kwargs.items():
        flag = "--" + key.replace("_", "-")
        if value is True:
            argv.append(flag)
        elif value is not None and value is not False:
            argv += [flag, str(value)]
    argv += ["--", *command]
    return run_commandline(argv)


def main() -> None:
    sys.exit(run_commandline())
