#!/usr/bin/env bash
# Round-5 chip work, part c: ResNet copy/transpose profile (VERDICT r4
# item 4 — the 4.9 ms layout-change bucket: recover it or close the
# case with this data). Queued behind parts a/b; same discipline.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r05

echo "=== chipwork_r05c start $(date -u +%F' '%H:%M)" >&2

while pgrep -f "chipwork_r05[ab].sh" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce|_fusion|_int8|_seq)?.py" >/dev/null 2>&1; do
  sleep 120
done

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}
wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}
hold_gate() {
  while [ -e scripts/CHIP_HOLD ]; do sleep 60; done
}

run_one() {
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "bench_results/${name}_${R}.txt" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "bench_results/${name}_${R}.txt"; then
    grep -E '^\{' "bench_results/${name}_${R}.txt" > "$out"
    rm -f "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  return 1
}
cap() {
  local name="$1"
  if [ -s "bench_results/${name}_${R}.json" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

wait_backend

cap resnet50_copy_profile       python scripts/profile_resnet_copies.py
cap resnet50_copy_profile_conv7 env BENCH_STEM=conv7 python scripts/profile_resnet_copies.py

echo "=== chipwork_r05c complete $(date -u +%F' '%H:%M)" >&2
