"""ViT-B/16 — the reference's elastic-training benchmark model
(BASELINE.json config #5: ViT-B/16 Elastic Horovod [V]). Reuses the
transformer encoder blocks; patchify via a strided conv (MXU-friendly)."""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .transformer import Block, TransformerConfig


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    # ViT's 14*14+1 = 197 tokens are untileable for the flash kernels
    # (no 8-aligned block divides them), which forced dense attention
    # until the kernels learned native right-padding. "auto": pad the
    # sequence to the next multiple of 8 (197 -> 200, +1.5% rows) and
    # run flash with lengths=197 whenever that unlocks the kernel on
    # TPU; True forces the pad (tests, off-TPU interpret); False keeps
    # the dense path.
    flash_pad: Any = "auto"
    # forwarded to the encoder blocks (TransformerConfig.flash_attention)
    flash_attention: Any = "auto"

    @staticmethod
    def b16() -> "ViTConfig":
        return ViTConfig()

    @staticmethod
    def tiny() -> "ViTConfig":
        return ViTConfig(
            image_size=32,
            patch_size=8,
            num_classes=10,
            num_layers=2,
            d_model=64,
            num_heads=4,
            d_ff=128,
            dtype=jnp.float32,
        )

    def encoder_config(self) -> TransformerConfig:
        n_patches = (self.image_size // self.patch_size) ** 2
        return TransformerConfig(
            vocab_size=1,  # unused
            num_layers=self.num_layers,
            d_model=self.d_model,
            num_heads=self.num_heads,
            d_ff=self.d_ff,
            max_len=n_patches + 1,
            causal=False,
            dtype=self.dtype,
            flash_attention=self.flash_attention,
        )


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        cfg = self.cfg
        enc = cfg.encoder_config()
        p = cfg.patch_size
        x = nn.Conv(
            cfg.d_model, (p, p), strides=(p, p), dtype=cfg.dtype,
            name="patchify",
        )(images.astype(cfg.dtype))
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, cfg.d_model)
        ).astype(cfg.dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, c)), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, x.shape[1], cfg.d_model),
        ).astype(cfg.dtype)
        x = x + pos

        t = x.shape[1]
        lengths = None
        if cfg.flash_pad == "auto":
            from ..ops.flash_attention import supports_seq

            pad_to = -(-t // 8) * 8
            do_pad = (
                pad_to != t
                and enc.uses_flash(seq=pad_to)
                and not supports_seq(t)
            )
        else:
            do_pad = bool(cfg.flash_pad) and t % 8 != 0
        if do_pad:
            pad_to = -(-t // 8) * 8
            x = jnp.pad(x, ((0, 0), (0, pad_to - t), (0, 0)))
            lengths = jnp.full((b,), t, jnp.int32)
        for i in range(cfg.num_layers):
            x = Block(enc, name=f"block_{i}")(x, None, train, lengths)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        # only the cls row (position 0) feeds the head; padded rows are
        # zeroed by the attention contract and never read
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(
            x[:, 0].astype(jnp.float32)
        )
