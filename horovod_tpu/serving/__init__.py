"""``hvd.serve`` — the elastic multi-host inference plane.

Seven PRs of training substrate (gang rendezvous, elastic driver,
donated fused executables, shape-bucketed executor caches, /metrics
telemetry, straggler ledger) turned into an inference fleet: continuous
batching over a fixed-shape donated decode step, a two-tier
(exact/bucket) prefill executor cache on the prompt-length axis, a
paged KV memory plane (block pool + page tables + hash-keyed prefix
cache — `paged_kv.py`; the PR 8 contiguous slab remains as the A/B
baseline), SLO-metered TTFT/TPOT on the existing scrape endpoint,
page-headroom capacity announcements + straggler-aware routing over
the rendezvous KV, and a SIGTERM drain that finishes every accepted
request before the worker leaves the gang.

    import horovod_tpu as hvd

    handle = hvd.serve(model, params, port=8500)
    handle.wait()          # POST /generate, GET /healthz|/metrics|/stats

Layers (docs/serving.md): models/transformer.py owns the incremental-
decode model contract (paged or slab cache layout); `engine` the
compiled prefill/decode split; `paged_kv` the block pool + prefix
cache; `kv_cache` the slab baseline + the manager factory; `batcher`
the scheduler (page-gated admission, pause-on-exhaustion); `slo` the
latency meters; `frontend` HTTP + fleet routing; `kv_transfer` the
disaggregated prefill/decode wire (role-split fleets, streamed int8
paged-KV transfer — ``HOROVOD_SERVE_ROLE``).
"""

from .batcher import (  # noqa: F401
    ContinuousBatcher,
    Rejected,
    Request,
)
from .kv_transfer import (  # noqa: F401
    KVTransferServer,
    TransferCoordinator,
    pack_raw_pages,
    unpack_pages,
    worker_role,
)
from .engine import InferenceEngine  # noqa: F401
from .frontend import (  # noqa: F401
    Router,
    ServeFrontend,
    ServeHandle,
    read_announcements,
    serve,
)
from .kv_cache import KVCacheManager, create_kv_manager  # noqa: F401
from .paged_kv import (  # noqa: F401
    PagedKVCacheManager,
    PagePoolExhausted,
    page_hashes,
)
from .slo import LatencyRecorder  # noqa: F401
