#!/usr/bin/env bash
# Round-4 chip work, part i: on-chip validation of the round's NEW
# kernel paths, queued behind the g->h capture chain:
#   1. padded flash attention (lengths= / SMEM scalar spec) — the SMEM
#      BlockSpec is interpret-validated only until this runs;
#   2. flash block 512 defaults fwd+bwd vs the dense oracle (the
#      default flip shipped mid-round; the sweep measured it but this
#      asserts numerics at the new default);
#   3. a bench_lm default capture with the new defaults, named
#      gpt2_default512 (provenance: flash_block field).
# Same discipline as parts c/g/h.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

while pgrep -f "chipwork_r04[gh].sh" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce)?.py" >/dev/null 2>&1; do
  sleep 120
done

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}
wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}

wait_backend

echo "=== padded + blk512 flash smoke $(date -u +%H:%M)" >&2
python - > bench_results/flash_padded_smoke_${R}.txt 2>&1 <<'EOF'
import numpy as np
import jax, jax.numpy as jnp

assert jax.devices()[0].platform == "tpu"

def dense_padded(q, k, v, causal, lengths):
    b, t, h, d = q.shape
    valid = jnp.arange(t)[None, :] < lengths[:, None]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return jnp.where(valid[:, None, :, None].transpose(0, 2, 1, 3), o, 0.0)

from horovod_tpu.ops import flash_attention as fa

rng = np.random.default_rng(0)
b, t, h, d = 2, 512, 4, 64
q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
           for _ in range(3))
lengths = jnp.asarray([512, 301], jnp.int32)
ok = True

# 1) padded path fwd + grads at the block-512 default (SMEM lens spec)
out = fa.flash_attention(q, k, v, causal=True, lengths=lengths)
ref = dense_padded(q, k, v, True, lengths)
err = float(jnp.max(jnp.abs(out - ref)))
print("padded fwd maxerr", err); ok &= err < 2e-3
rg = jax.grad(lambda q, k, v: (dense_padded(q, k, v, True, lengths)).sum(),
              argnums=(0, 1, 2))(q, k, v)
gg = jax.grad(lambda q, k, v: fa.flash_attention(
    q, k, v, causal=True, lengths=lengths).sum(), argnums=(0, 1, 2))(q, k, v)
for name, a, bb in zip(("dq", "dk", "dv"), gg, rg):
    e = float(jnp.max(jnp.abs(a - bb)))
    print("padded", name, "maxerr", e); ok &= e < 2e-3
pad_zero = float(jnp.max(jnp.abs(gg[0][1, 301:])))
print("padded dq pad-region max", pad_zero); ok &= pad_zero == 0.0

# 2) unpadded fwd+bwd at the new 512 default vs dense
def dense(q, k, v):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))

e = float(jnp.max(jnp.abs(
    fa.flash_attention(q, k, v, causal=True) - dense(q, k, v))))
print("blk512 fwd maxerr", e); ok &= e < 2e-3

print("PADDED FLASH PASS ON TPU" if ok else "PADDED FLASH FAIL")
EOF
grep -E "PASS|FAIL" bench_results/flash_padded_smoke_${R}.txt >&2

run_one() {
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}
cap() {
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

# 3) fresh default capture under the shipped defaults (blk512 recorded
#    in the flash_block provenance field)
cap gpt2_default512 env BENCH_MODEL=gpt2_medium python bench_lm.py

echo "=== chipwork_r04i complete $(date -u +%H:%M)" >&2
