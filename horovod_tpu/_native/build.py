"""Build the native library on demand.

Parity note: the reference compiles its native core at pip-install time
via setup.py→CMake (SURVEY.md §2.7); this repo has no install step in
the loop, so the equivalent moment is "first import" — we shell out to
g++ directly (or ``make -C csrc``) and cache the result next to this
file. Staleness is mtime-based against the csrc/ sources.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.normpath(os.path.join(_HERE, "..", "..", "csrc"))
_LIB = os.path.join(_HERE, "libhvd_native.so")

_SOURCES = [
    "timeline.cc",
    "adasum.cc",
    "gp.cc",
    "pack.cc",
    "sha256.cc",
    "kvstore.cc",
    "npyio.cc",
]


def _source_paths() -> List[str]:
    return [os.path.join(_CSRC, s) for s in _SOURCES]


def _stale() -> bool:
    if not os.path.exists(_LIB):
        return True
    lib_mtime = os.path.getmtime(_LIB)
    deps = _source_paths() + [
        os.path.join(_CSRC, "export.h"),
        os.path.join(_CSRC, "sha256.h"),
    ]
    return any(
        os.path.exists(p) and os.path.getmtime(p) > lib_mtime for p in deps
    )


def _build(sources: List[str], out: str, extra: List[str]) -> Optional[str]:
    """Compile ``sources`` into the shared object ``out``; returns the
    path on success, the existing artifact (if any) on failure. Build to
    a temp name then os.replace: concurrent builders (e.g.
    pytest-launched worker processes) each produce a complete .so and
    the last rename wins — nobody ever dlopens a half-written file."""
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    cmd = [
        os.environ.get("CXX", "g++"),
        "-std=c++17", "-O3", "-fPIC", "-Wall", "-pthread",
        "-fvisibility=hidden", "-shared",
        *extra,
        *sources,
        "-o", tmp,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=300, cwd=_CSRC
        )
        os.replace(tmp, out)
        return out
    except (subprocess.SubprocessError, OSError):
        if os.path.exists(tmp):
            os.unlink(tmp)
        return out if os.path.exists(out) else None


def lib_path() -> Optional[str]:
    """Path to an up-to-date libhvd_native.so, building it if needed.
    Returns None when the sources are missing or the build fails."""
    if not _stale():
        return _LIB
    if not all(os.path.exists(p) for p in _source_paths()):
        return _LIB if os.path.exists(_LIB) else None
    return _build(_source_paths(), _LIB, [])


# ------------------------------------------------- CPython extension half

def _ext_suffix() -> str:
    """ABI-tagged extension suffix (e.g. .cpython-311-x86_64-linux-gnu.so)
    so checkouts shared between interpreters never load an extension
    compiled against another version's headers."""
    import importlib.machinery

    return importlib.machinery.EXTENSION_SUFFIXES[0]


_EXT = os.path.join(_HERE, "_hvd_cext" + _ext_suffix())
_EXT_SRC = os.path.join(_CSRC, "cext.cc")


def _ext_stale() -> bool:
    if not os.path.exists(_EXT):
        return True
    return (
        os.path.exists(_EXT_SRC)
        and os.path.getmtime(_EXT_SRC) > os.path.getmtime(_EXT)
    )


def ext_path() -> Optional[str]:
    """Path to the up-to-date ``_hvd_cext`` CPython extension module
    (csrc/cext.cc), building it against this interpreter's headers on
    first call. A plain ``.so`` suffix imports fine on Linux
    (``importlib.machinery.EXTENSION_SUFFIXES`` ends with ``.so``);
    undefined Python symbols resolve from the host process at import,
    exactly like a setuptools-built extension."""
    if not _ext_stale():
        return _EXT
    if not os.path.exists(_EXT_SRC):
        return _EXT if os.path.exists(_EXT) else None
    import sysconfig

    include = sysconfig.get_paths().get("include")
    if not include or not os.path.exists(
        os.path.join(include, "Python.h")
    ):
        return _EXT if os.path.exists(_EXT) else None
    return _build([_EXT_SRC], _EXT, ["-I", include])
