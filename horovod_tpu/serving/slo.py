"""SLO instrumentation: TTFT / TPOT summaries for the serving plane.

Two latency families, the ones the Gemma-on-TPU serving paper meters
(PAPERS.md, arXiv 2605.25645):

* **TTFT** (time to first token): request submission → the first
  generated token leaving prefill. Queue wait is INCLUDED by design —
  it is what the user feels, and the difference between TTFT and
  prefill wall time is exactly the admission policy's cost.
* **TPOT** (time per output token): the decode-step wall time each
  subsequent token rode.

Samples land in bounded rings (newest ``capacity``), and ``publish()``
pushes p50/p95/count gauges into the metrics registry under ``serve.``
— so they appear on the existing ``/metrics`` endpoint
(common/telemetry.py MetricsServer) next to the training gauges, and
in flight-recorder StepStats via the registry snapshot.
``render_prometheus_summaries()`` additionally renders the two
families as proper Prometheus ``summary`` types for the serve
frontend's own ``/metrics`` route.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List

from ..common.metrics import registry as _metrics
from ..common.telemetry import _percentile

DEFAULT_CAPACITY = 1024


def _percentile_sample(sorted_samples, q: float):
    """Nearest-rank percentile over (value, payload) pairs already
    sorted by value — returns the WITNESS pair, not just the value, so
    the exemplar trace_id rides along. None when empty."""
    if not sorted_samples:
        return None
    idx = min(
        int(q * (len(sorted_samples) - 1) + 0.5), len(sorted_samples) - 1
    )
    return sorted_samples[idx]


class LatencyRecorder:
    """Bounded-ring p50/p95 for the two serving latency families."""

    FAMILIES = ("ttft_ms", "tpot_ms")

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._rings = {
            fam: collections.deque(maxlen=max(int(capacity), 1))
            for fam in self.FAMILIES
        }
        self._counts = {fam: 0 for fam in self.FAMILIES}
        self._sums = {fam: 0.0 for fam in self.FAMILIES}

    def record_ttft(self, ms: float, trace_id: str = "") -> None:
        self._record("ttft_ms", ms, trace_id)

    def record_tpot(self, ms: float, trace_id: str = "") -> None:
        self._record("tpot_ms", ms, trace_id)

    def _record(self, fam: str, ms: float, trace_id: str = "") -> None:
        with self._lock:
            self._rings[fam].append((float(ms), trace_id or ""))
            self._counts[fam] += 1
            self._sums[fam] += float(ms)

    def summaries(self) -> Dict[str, Dict[str, float]]:
        """{family: {p50, p95, count, sum, p95_exemplar}}. The
        quantiles are ring-windowed (newest ``capacity`` samples, like
        the step-time summary in common/telemetry.py); count AND sum
        are lifetime cumulative — the Prometheus summary pair, so
        sum/count is a true mean for any consumer computing
        rate(sum)/rate(count). ``p95_exemplar`` is the trace_id of the
        sample currently WITNESSING p95 ("" when that request was
        untraced) — "why is p95 high" becomes an openable trace
        (scripts/trace_assemble.py --trace)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            snap = {
                fam: (sorted(ring), self._counts[fam], self._sums[fam])
                for fam, ring in self._rings.items()
            }
        for fam, (samples, count, total) in snap.items():
            vals = [ms for ms, _ in samples]
            p95_witness = _percentile_sample(samples, 0.95)
            out[fam] = {
                "p50": _percentile(vals, 0.50),
                "p95": _percentile(vals, 0.95),
                "count": count,
                "sum": total,
                "p95_exemplar": p95_witness[1] if p95_witness else "",
            }
        return out

    def publish(self) -> None:
        """serve.ttft_ms_p50 / _p95 / _count (+ tpot) registry gauges —
        the existing /metrics endpoint picks them up as hvd_serve_*."""
        stats = {}
        for fam, s in self.summaries().items():
            stats[f"{fam}_p50"] = s["p50"]
            stats[f"{fam}_p95"] = s["p95"]
            stats[f"{fam}_count"] = s["count"]
        _metrics.update("serve", stats)

    def render_prometheus_summaries(self) -> List[str]:
        """Prometheus text lines rendering both families as real
        ``summary`` types (quantile labels), for the serve frontend's
        /metrics route."""
        lines: List[str] = []
        helps = {
            "ttft_ms": "Time to first token (submission -> first "
            "generated token, queue wait included), ms.",
            "tpot_ms": "Per-output-token latency (decode-step wall "
            "time per generated token), ms.",
        }
        for fam, s in self.summaries().items():
            name = f"serve_{fam}"
            exemplar = s.get("p95_exemplar", "")
            lines.append(f"# HELP {name} {helps[fam]}")
            lines.append(f"# TYPE {name} summary")
            lines.append(f'{name}{{quantile="0.5"}} {s["p50"]:.10g}')
            p95_line = f'{name}{{quantile="0.95"}} {s["p95"]:.10g}'
            if exemplar:
                # OpenMetrics-style exemplar: the trace witnessing the
                # current p95, openable via scripts/trace_assemble.py
                p95_line += (
                    f' # {{trace_id="{exemplar}"}} {s["p95"]:.10g}'
                )
            lines.append(p95_line)
            lines.append(f"{name}_sum {s['sum']:.10g}")
            lines.append(f"{name}_count {s['count']:.10g}")
            if exemplar:
                ename = f"serve_{fam[:-3]}_p95_exemplar"
                lines.append(
                    f"# HELP {ename} trace_id of the sample witnessing "
                    f"the current {fam} p95."
                )
                lines.append(f"# TYPE {ename} gauge")
                lines.append(f'{ename}{{trace_id="{exemplar}"}} 1')
        return lines
