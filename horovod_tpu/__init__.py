"""horovod_tpu — a TPU-native distributed training framework with the
capability surface of Horovod (reference: jiaqianjing/horovod, a fork of
horovod/horovod; see SURVEY.md).

Import convention mirrors the reference's per-framework modules
(``import horovod.torch as hvd`` [V]):

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()                       # the world: 1 chip = 1 rank
    out = hvd.allreduce(hvd.replicate(x))   # eager, fused + async-capable
    # ... or the TPU fast path: hvd.traced.allreduce inside jit/shard_map.

Architecture (SURVEY.md §7): traced collectives lower to XLA collectives
over ICI — the compiler statically schedules, fuses, and overlaps them,
replacing the reference's background negotiate-fuse-execute thread. The
eager API keeps Horovod's async-handle semantics on top of a fusion-cycle
dispatcher (ops/fusion.py). Everything honors the HOROVOD_* env contract.
"""

from .common import compat as _compat

# Publish jax.shard_map (+ check_vma kwarg mapping) on old JAX before
# anything — library modules, tests, and user scripts alike assume the
# modern spelling exists once horovod_tpu is imported.
_compat.install()

from .common.basics import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
    add_process_set,
    ccl_built,
    cross_rank,
    cross_size,
    cuda_built,
    ddl_built,
    get_config,
    get_process_set,
    get_process_set_ids,
    gloo_built,
    gloo_enabled,
    global_process_set,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mesh,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    remove_process_set,
    rocm_built,
    shutdown,
    size,
    topology,
    tpu_enabled,
    xla_built,
)
from .common.process_sets import ProcessSet  # noqa: F401
from .common.topology import (  # noqa: F401
    WORLD_AXIS,
    rank_sharding,
    replicated_sharding,
    shard_from_rank_fn,
)
from .ops.reduction_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
)
from .ops.compression import Compression  # noqa: F401
from .ops.eager import (  # noqa: F401
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    first,
    flush,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    barrier,
    join,
    join_ranks,
    my_row,
    poll,
    reducescatter,
    reducescatter_async,
    replicate,
    synchronize,
)
from .optimizer import (  # noqa: F401
    DistributedOptimizer,
    LocalSGDGradientTransformation,
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    grad,
    value_and_grad,
)
from .sharded_optimizer import (  # noqa: F401
    ShardedDistributedOptimizer,
)
from . import ops  # noqa: F401
from .ops import traced  # noqa: F401
from .ops import overlap  # noqa: F401
from .ops.overlap import (  # noqa: F401
    bucketed_allreduce,
    build_bucket_schedule,
    overlap_boundary,
)
from .ops.fused_xent import fused_linear_cross_entropy  # noqa: F401
from . import local_sgd  # noqa: F401  (K-step ICI-local training regime)
from . import elastic  # noqa: F401  (hvd.elastic.run / State, ref [V])
from . import callbacks  # noqa: F401  (Keras-callback parity, ref [V])
from . import data  # noqa: F401  (DistributedSampler analog + prefetch)
from . import executor  # noqa: F401  (RayExecutor / spark.run parity, ref [V])
from . import checkpoint  # noqa: F401  (durable ckpt — fills ref gap, SURVEY §5.4)
from . import preemption  # noqa: F401  (TPU preemption → durable commit)
from .common import telemetry  # noqa: F401  (flight recorder + /metrics)
from .common.telemetry import (  # noqa: F401
    step_begin,
    step_end,
)
from .common.guard import (  # noqa: F401  (non-finite sentinel)
    check as guard_check,
    status as guard_status,
)
from .audit import (  # noqa: F401  (cross-rank parameter audit)
    audit,
    maybe_audit,
    tree_digest,
)


def serve(model, params, port=None, **kwargs):
    """``hvd.serve(model, params, port=...)`` — start the inference
    plane on this worker (horovod_tpu/serving/: continuous batching
    over a compiled prefill/decode split, slot KV cache, SLO-metered
    HTTP frontend, rendezvous-announced capacity, SIGTERM drain).
    Returns a ``ServeHandle``; see docs/serving.md."""
    from .serving import serve as _serve

    return _serve(model, params, port=port, **kwargs)


def __getattr__(name):
    # hvd.SyncBatchNorm parity (ref [V]) without making flax a hard
    # import-time dependency of the whole package — launcher-only hosts
    # import horovod_tpu without any model stack.
    if name == "SyncBatchNorm":
        from .models.resnet import SyncBatchNorm

        return SyncBatchNorm
    if name == "serving":
        # lazy: the serving plane is worker-role code, not launcher code
        from . import serving

        return serving
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__version__ = "0.1.0"


def start_timeline(
    file_path: str, mark_cycles: bool = False, traced: bool = False
) -> None:
    """Runtime timeline activation (ref: hvd.start_timeline, v0.21+ [V]).

    ``traced=False`` (default): the eager per-collective lifecycle
    timeline (QUEUE/ALLREDUCE/... phases). ``traced=True``: an XLA
    profiler session for jit/shard_map runs — stop_timeline() writes a
    chrome://tracing JSON of every compiled op (collectives included,
    with device timestamps) and keeps the TensorBoard profile dir next
    to it. Use :func:`timeline_step` to mark step boundaries."""
    from .common import basics as _basics

    st = _basics._require_init()
    if traced:
        from .common.traced_timeline import TracedTimeline

        if st.traced_timeline is None:
            st.traced_timeline = TracedTimeline(file_path)
        st.traced_timeline.start()
        return
    from .common.timeline import Timeline

    if st.timeline is None:
        st.timeline = Timeline(file_path, mark_cycles=mark_cycles)
        st.fusion.timeline = st.timeline
        # keep the telemetry hub's step-boundary counter track on the
        # SAME timeline, whether it came from env at init or from this
        # runtime call (common/telemetry.py)
        from .common import telemetry as _telemetry

        _telemetry.hub().timeline = st.timeline
    st.timeline.start()


def stop_timeline() -> None:
    from .common import basics as _basics

    st = _basics._require_init()
    if st.traced_timeline is not None:
        st.traced_timeline.stop()
    if st.timeline is not None:
        st.timeline.stop()


def timeline_step(name: str = "step", step_num=None):
    """Context manager marking one traced training step in the profiler
    timeline (the NVTX-range analog, nvtx_op_range.h [V]). No-op when no
    traced timeline is active.

    When telemetry is enabled (flight recorder / /metrics scraper /
    HOROVOD_TELEMETRY=1) the same boundary also opens and closes a
    flight-recorder StepStats record, so profiler steps and telemetry
    steps share ids."""
    from .common import basics as _basics
    from .common import telemetry as _telemetry
    from .common.traced_timeline import TracedTimeline

    st = _basics._require_init()
    if st.traced_timeline is None:
        st.traced_timeline = TracedTimeline("horovod_timeline.json")
    ctx = st.traced_timeline.step(name, step_num)
    if not _telemetry.auto_enabled():
        return ctx
    import contextlib

    @contextlib.contextmanager
    def _with_telemetry():
        _telemetry.hub().step_begin(step_num)
        try:
            with ctx:
                yield
        finally:
            _telemetry.hub().step_end()

    return _with_telemetry()
