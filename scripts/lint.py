#!/usr/bin/env python
"""AST-based convention lint (``ci.sh lint``).

Upgrades the old ``compileall`` gate: every file is ``ast.parse``d (so
syntax errors still fail) and then checked against the repo's actual
conventions — the ones that have bitten before and that no generic
linter knows about:

1. **env-read** — no ``os.environ`` / ``os.getenv`` READS outside
   ``common/config.py``: runtime knobs flow through the typed Config +
   ``basics.live_config()`` ladder (the PR 7 consolidation), so a
   knob read from env at point-of-use silently ignores a live config.
   Writes (launcher child-env assembly) are allowed. Files that read
   PROTOCOL env (HOROVOD_RANK worker identity, XLA_FLAGS passthrough)
   are grandfathered in ``ENV_READ_ALLOWED`` — adding a new file to
   that list is a reviewed decision, not an accident.
2. **bare-except** — ``except:`` catches ``SystemExit``/
   ``KeyboardInterrupt`` and has eaten shutdown paths before; name the
   exception (``except Exception:`` at minimum).
3. **unused-import** — module-level imports nobody references
   (``__init__.py`` re-export surfaces are exempt; names appearing in
   string annotations / docstring examples count as uses, so typing
   imports under ``from __future__ import annotations`` don't
   false-positive).
4. **debug-callback** — ``jax.debug.callback`` escapes the compiled
   program to host Python; unvetted uses have produced per-step host
   syncs. Only the approved guard/telemetry sites may call it
   (``DEBUG_CALLBACK_ALLOWED``).

Exit 0 clean, 1 on findings, 2 on usage errors. ``--list-rules`` for
the catalog.
"""

import argparse
import ast
import os
import re
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Directories/globs linted. tests and benches are in scope for
# bare-except + unused-import; the env-read and debug-callback rules
# apply to the package only (tests legitimately monkeypatch env and
# exercise callbacks).
PACKAGE_DIRS = ("horovod_tpu",)
EXTRA_DIRS = ("tests", "scripts", "examples")
ROOT_GLOBS = ("bench", "_benchlib", "_hermetic", "__graft_entry__")

# --- rule 1 allowlist: files whose os.environ READS are the contract,
# not a config bypass (worker-protocol identity, child-env assembly,
# logging bootstrap that cannot import config yet, signal-path code
# that must not allocate). Relative to repo root.
ENV_READ_ALLOWED = {
    "horovod_tpu/common/config.py",  # THE env surface
    # worker bootstrap protocol (HOROVOD_RANK/HOSTNAME/EPOCH identity
    # stamped by the launcher — these are addresses, not knobs)
    "horovod_tpu/_executor_worker.py",
    "horovod_tpu/elastic/worker.py",
    "horovod_tpu/elastic/driver.py",
    "horovod_tpu/runner/tpu_discovery.py",
    "horovod_tpu/runner/launch.py",
    # HOROVOD_STANDBY_HOSTNAME / _FINGERPRINT / CHECKPOINT_DIR are
    # identity stamped by the driver's warmer launch, same contract
    "horovod_tpu/elastic/standby.py",
    "horovod_tpu/runner/rendezvous.py",
    "horovod_tpu/executor.py",
    # bootstrap surfaces that run before/While config exists
    "horovod_tpu/common/logging.py",
    "horovod_tpu/common/metrics.py",
    "horovod_tpu/common/telemetry.py",
    "horovod_tpu/common/autotune.py",
    # HOROVOD_EXE_CACHE resolves live like HOROVOD_TUNER_CACHE above:
    # drills/benches flip the cache root mid-process, after any init
    # snapshot (typed knob exists in config.py for the standby warmer)
    "horovod_tpu/common/exe_cache.py",
    "horovod_tpu/testing/chaos.py",
    "horovod_tpu/testing/fake_ray.py",
    "horovod_tpu/_native/loader.py",
    "horovod_tpu/_native/build.py",
    # kernel-level flags read at trace time (documented in env_vars.md;
    # they gate lowering choices, not runtime behavior)
    "horovod_tpu/ops/flash_attention.py",
    "horovod_tpu/sharded_optimizer.py",
}

# --- rule 4 allowlist: the approved jax.debug.callback sites — the
# PR 4 telemetry tick and the PR 7 guard skip-branch callback.
DEBUG_CALLBACK_ALLOWED = {
    "horovod_tpu/optimizer.py",
    "horovod_tpu/sharded_optimizer.py",
}


def _iter_files() -> List[str]:
    out = []
    for d in PACKAGE_DIRS + EXTRA_DIRS:
        for root, dirs, files in os.walk(os.path.join(REPO, d)):
            dirs[:] = [
                x for x in dirs if x != "__pycache__" and not x.startswith(".")
            ]
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    for f in sorted(os.listdir(REPO)):
        if f.endswith(".py") and any(f.startswith(g) for g in ROOT_GLOBS):
            out.append(os.path.join(REPO, f))
    return out


def _rel(path: str) -> str:
    return os.path.relpath(path, REPO).replace(os.sep, "/")


def _is_environ_read(node: ast.AST) -> bool:
    """``os.environ[...]`` / ``os.environ.get(...)`` / ``os.getenv(..)``
    in Load context. ``os.environ`` passed wholesale (child-env
    assembly like ``dict(os.environ)``) or assigned/updated is a
    write-shaped use and allowed everywhere."""
    # os.getenv(...)
    if isinstance(node, ast.Call):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "getenv"
            and isinstance(f.value, ast.Name)
            and f.value.id == "os"
        ):
            return True
        # os.environ.get(...)
        if (
            isinstance(f, ast.Attribute)
            and f.attr in ("get", "__getitem__")
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "environ"
            and isinstance(f.value.value, ast.Name)
            and f.value.value.id == "os"
        ):
            return True
    # os.environ[...] read (Load ctx only; Store/Del are writes)
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.ctx, ast.Load)
        and isinstance(node.value, ast.Attribute)
        and node.value.attr == "environ"
        and isinstance(node.value.value, ast.Name)
        and node.value.value.id == "os"
    ):
        return True
    return False


def _is_debug_callback(node: ast.AST) -> bool:
    """A call whose func ends in ``.debug.callback`` (jax.debug....)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "callback"
        and isinstance(f.value, ast.Attribute)
        and f.value.attr == "debug"
    )


_WORD = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _unused_imports(tree: ast.Module, src: str) -> List[Tuple[int, str]]:
    """Module-scope imports never referenced. A name counts as used if
    it appears as any identifier anywhere else in the AST — including
    inside string constants (quoted annotations, doctest snippets), the
    permissive direction for a lint that must never cry wolf."""
    lines = src.splitlines()

    def _noqa(lineno: int) -> bool:
        # honor `# noqa` on the import line (the existing re-export
        # convention, e.g. fusion.py's hierarchical_stage_groups)
        return 0 < lineno <= len(lines) and "noqa" in lines[lineno - 1]

    imported = {}  # name -> (lineno, display)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                imported[name] = (node.lineno, a.asname or a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                imported[name] = (node.lineno, a.asname or a.name)
    if not imported:
        return []
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # the root Name node is walked separately
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.update(_WORD.findall(node.value))
    # __all__ re-exports count
    out = []
    for name, (lineno, display) in sorted(imported.items()):
        if name in used or _noqa(lineno):
            continue
        out.append((lineno, display))
    return out


def lint_file(path: str) -> List[str]:
    rel = _rel(path)
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: syntax-error: {e.msg}"]

    findings: List[str] = []
    in_package = rel.startswith("horovod_tpu/")

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(
                f"{rel}:{node.lineno}: bare-except: name the exception "
                "(except Exception: at minimum — bare except eats "
                "SystemExit/KeyboardInterrupt)"
            )
        if in_package and rel not in ENV_READ_ALLOWED and _is_environ_read(
            node
        ):
            findings.append(
                f"{rel}:{node.lineno}: env-read: os.environ read outside "
                "common/config.py — add a typed Config knob and read it "
                "via basics.live_config() (or, for protocol env, add "
                "this file to ENV_READ_ALLOWED in scripts/lint.py with "
                "a justification)"
            )
        if (
            in_package
            and rel not in DEBUG_CALLBACK_ALLOWED
            and _is_debug_callback(node)
        ):
            findings.append(
                f"{rel}:{node.lineno}: debug-callback: jax.debug.callback "
                "outside the approved guard/telemetry sites escapes the "
                "compiled program to host Python (per-step host-sync "
                "hazard) — route through common/guard.py or "
                "common/telemetry.py, or extend DEBUG_CALLBACK_ALLOWED"
            )

    if os.path.basename(path) != "__init__.py":
        for lineno, display in _unused_imports(tree, src):
            findings.append(
                f"{rel}:{lineno}: unused-import: {display!r} is never "
                "referenced"
            )
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="lint only these files")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)
    if args.list_rules:
        print("env-read bare-except unused-import debug-callback")
        return 0

    files = (
        [os.path.abspath(p) for p in args.paths]
        if args.paths
        else _iter_files()
    )
    findings: List[str] = []
    for path in files:
        findings.extend(lint_file(path))
    for f in findings:
        print(f)
    if findings:
        print(
            f"lint: {len(findings)} finding(s) in {len(files)} file(s)",
            file=sys.stderr,
        )
        return 1
    print(f"lint: {len(files)} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
