"""Multi-process eager op parity suite (VERDICT r4 item #4).

The reference runs its WHOLE op matrix multi-process (`horovodrun -np 2
pytest test/parallel/test_torch.py`, rank-dependent closed-form asserts
[V]); until round 3 this repo exercised almost everything on the
single-process 8-device mesh only. This suite launches THREE real
processes through `python -m horovod_tpu.runner --placement per-slot`
(real jax.distributed coordination, one CPU device per rank) and runs
the eager op family with closed-form asserts inside every worker:

allreduce / grouped (atomic) / Adasum-over-a-process-set /
allgather-v (uneven rows) / broadcast root!=0 / alltoall-v (uneven
splits) / reducescatter / a process set excluding rank 0 / join mask.

Three processes (not two) so a set excluding rank 0 still has a real
2-member exchange, and odd-world edge cases (uneven reducescatter) are
covered.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r'''
import numpy as np
import jax
import horovod_tpu as hvd

hvd.init()
W = hvd.size()
assert W == 3, W
assert jax.process_count() == 3
me = hvd.rank()
mesh = hvd.mesh()


def fn(r):
    return np.asarray([r + 1.0, 2.0 * r], np.float32)


def rm(f):
    # Multi-process input idiom: each process contributes ITS rank's
    # tensor via replicate (the per-process model of the reference);
    # row r of the global array is process r's value.
    return hvd.replicate(np.asarray(f(me), np.float32))


def check(tag, got, want, rtol=1e-5):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    assert got.shape == want.shape, (tag, got.shape, want.shape)
    assert np.allclose(got, want, rtol=rtol, atol=1e-5), (tag, got, want)
    print(f"OK {tag} rank={me}", flush=True)


# 1. allreduce Sum — every row is the world sum
out = hvd.allreduce(rm(fn), op=hvd.Sum)
check("allreduce_sum", hvd.my_row(out), fn(0) + fn(1) + fn(2))

# 2. join mask — rank 2 joined, Average over ranks {0, 1}
with hvd.join_ranks([2]):
    out = hvd.allreduce(rm(fn), op=hvd.Average)
check("join_average", hvd.my_row(out), (fn(0) + fn(1)) / 2.0)

# 3. Adasum over a process set {0, 1} (2-member VHDD closed form);
#    rank 2 is a non-member and passes through unchanged
ps01 = hvd.add_process_set([0, 1])
a, b = fn(0).astype(np.float64), fn(1).astype(np.float64)
dot, na, nb = a @ b, a @ a, b @ b
adasum_expected = (1 - dot / (2 * na)) * a + (1 - dot / (2 * nb)) * b
out = hvd.allreduce(rm(fn), op=hvd.Adasum, process_set=ps01)
check(
    "adasum_pset",
    hvd.my_row(out),
    adasum_expected if me in (0, 1) else fn(2),
    rtol=1e-4,
)
hvd.remove_process_set(ps01)

# 4. broadcast root=2 — every row becomes rank 2's tensor
out = hvd.broadcast(rm(fn), root_rank=2)
check("broadcast_root2", hvd.my_row(out), fn(2))

# 5. allgather-v — ranks contribute 1/2/3 rows; every rank receives the
#    concatenation (host-list input, the documented v pattern)
rows = [np.full((r + 1, 2), float(r), np.float32) for r in range(3)]
out = hvd.allgather(list(rows))
check("allgather_v", hvd.my_row(out), np.concatenate(rows, axis=0))

# 6. alltoall-v — uneven splits, host-list input
send = [
    np.arange(3, dtype=np.float32).reshape(3, 1),         # r0: 3 rows
    10 + np.arange(4, dtype=np.float32).reshape(4, 1),    # r1: 4 rows
    20 + np.arange(4, dtype=np.float32).reshape(4, 1),    # r2: 4 rows
]
splits = [[1, 1, 1], [2, 1, 1], [1, 1, 2]]
outputs, recv_splits = hvd.alltoall([s for s in send], splits=splits)
offs = [np.concatenate([[0], np.cumsum(s)]) for s in splits]
expected = np.concatenate(
    [send[src][offs[src][me]: offs[src][me + 1]] for src in range(3)]
)
check("alltoall_v", outputs[me], expected)
assert list(map(int, recv_splits[me])) == [splits[src][me] for src in range(3)], recv_splits[me]

# 7. process set excluding rank 0 — real 2-member exchange among {1, 2}
ps12 = hvd.add_process_set([1, 2])
out = hvd.allreduce(rm(fn), op=hvd.Sum, process_set=ps12)
check("pset_excl0", hvd.my_row(out), fn(me) if me == 0 else fn(1) + fn(2))
hvd.remove_process_set(ps12)

# 8. grouped allreduce — atomic pair
g1, g2 = hvd.grouped_allreduce([rm(fn), rm(lambda r: fn(r) * 10)], op=hvd.Sum)
check("grouped_1", hvd.my_row(g1), fn(0) + fn(1) + fn(2))
check("grouped_2", hvd.my_row(g2), (fn(0) + fn(1) + fn(2)) * 10)

# 9. reducescatter Sum — row r is shard r of the world sum
base = lambda r: np.arange(6, dtype=np.float32) + r
out = hvd.reducescatter(rm(base), op=hvd.Sum)
total = base(0) + base(1) + base(2)
check("reducescatter", hvd.my_row(out), total[2 * me: 2 * me + 2])

# 10. barrier — nobody leaves before the slowest process enters.
#     Rank 2 enters ~0.8s after rank 0; rank 0's wait must absorb
#     that skew (lower-bound assert, robust to slow machines).
import time
time.sleep(0.4 * me)
t0 = time.monotonic()
hvd.barrier()
waited = time.monotonic() - t0
if me == 0:
    assert waited > 0.3, f"barrier did not block rank 0 (waited {waited:.3f}s)"
print(f"OK barrier rank={me}", flush=True)

# 11. barrier over a process set (non-member rank 2 passes through)
ps01b = hvd.add_process_set([0, 1])
hvd.barrier(process_set=ps01b)
hvd.remove_process_set(ps01b)
print(f"OK barrier_pset rank={me}", flush=True)

print(f"WORKER_DONE {me}", flush=True)
'''


@pytest.mark.slow
def test_eager_op_family_across_three_real_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""  # keep workers off the TPU claim
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    out_dir = tmp_path / "logs"
    proc = subprocess.run(
        [
            sys.executable, "-m", "horovod_tpu.runner",
            "-np", "3", "--placement", "per-slot",
            "--output-filename", str(out_dir),
            "--", sys.executable, str(script),
        ],
        env=env, timeout=600, capture_output=True, cwd=_REPO,
    )
    logs = "\n".join(
        p.read_text() for p in sorted(out_dir.glob("rank.*"))
    )
    assert proc.returncode == 0, (
        f"launcher failed:\n{proc.stderr.decode()[-3000:]}\n{logs[-3000:]}"
    )
    for r in range(3):
        assert f"WORKER_DONE {r}" in logs, logs[-3000:]
    # every op asserted on every rank
    for tag in (
        "allreduce_sum", "join_average", "adasum_pset", "broadcast_root2",
        "allgather_v", "alltoall_v", "pset_excl0", "grouped_1",
        "grouped_2", "reducescatter", "barrier", "barrier_pset",
    ):
        for r in range(3):
            assert f"OK {tag} rank={r}" in logs, (tag, r, logs[-3000:])
