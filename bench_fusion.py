"""Eager-dispatch fusion microbenchmark — the measurement behind the
core-runtime redesign's premise (VERDICT r4 Weak #2 / item 3).

`ops/fusion.py` exists because "many small eager collectives are slow
if dispatched one XLA executable each" (module header; ref:
fusion_buffer_manager.cc, parameter_manager.cc semantics [V]). This
harness measures that claim directly, on whatever backend is present:

  * unfused — threshold=1 byte: every enqueue flushes a single-entry
    batch → N executable launches per step (the no-fusion world).
  * fused — threshold > N·bytes: one flush concatenates all N entries
    into one [world, total] buffer → ONE launch per step.
  * traced — one jit'd shard_map psum over the same total bytes: the
    floor (no queue, no scatter-back, no per-entry Python).
  * autotune — `common/autotune.py`'s BayesianOptimizer proposes
    (threshold, cycle) pairs against the same workload; the run shows
    whether the GP's pick beats the shipped defaults.

Per mode prints one JSON line:
  {"metric": "eager_fusion", "mode": ..., "n_tensors": N,
   "bytes_each": B, "value": ms/step, "unit": "ms"}
then a speedup summary and the autotune verdict line.

Env: BENCH_FUSION_N (default 200), BENCH_FUSION_BYTES (default 1 MiB),
BENCH_ITERS (default 10), BENCH_AUTOTUNE_TRIALS (default 10, 0 = skip),
BENCH_PLATFORM=cpu for the simulated mesh (sim lines carry the
quarantine note — dispatch overhead on CPU validates logic only).
"""

import json
import os
import time

_SIM_NOTE = (
    "logic-validation only (CPU simulation); NOT a TPU dispatch number"
)


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu.common import basics
    from horovod_tpu.common.topology import WORLD_AXIS
    from horovod_tpu.ops import traced

    n_tensors = int(os.environ.get("BENCH_FUSION_N", "200"))
    nbytes = int(os.environ.get("BENCH_FUSION_BYTES", str(1 << 20)))
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    trials = int(os.environ.get("BENCH_AUTOTUNE_TRIALS", "10"))
    n_elems = max(nbytes // 4, 1)

    hvd.init()
    fusion = basics._state.fusion
    world = hvd.size()
    platform = jax.devices()[0].platform
    mesh = hvd.mesh()

    default_threshold = fusion.threshold_bytes
    default_cycle = fusion.cycle_time_ms

    rng = np.random.default_rng(0)
    bufs0 = [
        jnp.asarray(
            rng.normal(size=(world, n_elems)).astype(np.float32)
        )
        for _ in range(n_tensors)
    ]

    def eager_step(bufs):
        handles = [
            hvd.allreduce_async(b, op=hvd.Average, name=f"t{i}")
            for i, b in enumerate(bufs)
        ]
        return [h.wait() for h in handles]

    def run_eager(threshold, cycle_ms):
        fusion.threshold_bytes = int(threshold)
        fusion.cycle_time_ms = float(cycle_ms)
        bufs = eager_step(list(bufs0))  # warm: compile executors
        bufs = eager_step(bufs)  # warm again on committed outputs
        _sync(sum(jnp.sum(b) for b in bufs))
        t0 = time.perf_counter()
        for _ in range(iters):
            bufs = eager_step(bufs)
        _sync(sum(jnp.sum(b) for b in bufs))
        return (time.perf_counter() - t0) / iters * 1e3  # ms/step

    def emit(mode, ms, extra=None):
        line = {
            "metric": "eager_fusion",
            "mode": mode,
            "n_tensors": n_tensors,
            "bytes_each": nbytes,
            "world": world,
            "value": round(ms, 3),
            "unit": "ms",
            "platform": platform,
        }
        if extra:
            line.update(extra)
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(line), flush=True)
        return ms

    total = n_tensors * nbytes
    ms_unfused = emit("unfused", run_eager(1, 1e9))
    ms_fused = emit("fused", run_eager(total * 2, 1e9))
    ms_default = emit(
        "default",
        run_eager(default_threshold, default_cycle),
        {"threshold": default_threshold, "cycle_ms": default_cycle},
    )

    # traced floor: ONE psum over the same bytes, chained for sync
    from functools import partial

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P(WORLD_AXIS),
        out_specs=P(WORLD_AXIS),
        check_vma=False,
    )
    def reduce(x):
        return traced.allreduce(x[0], op=hvd.Average)[None]

    step = jax.jit(reduce)
    x = jnp.ones((world, n_tensors * n_elems), jnp.float32)
    x = step(step(x))
    _sync(x)
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    _sync(x)
    ms_traced = emit(
        "traced", (time.perf_counter() - t0) / iters * 1e3
    )

    line = {
        "metric": "eager_fusion_speedup",
        "value": round(ms_unfused / ms_fused, 3),
        "unit": "x",
        "unfused_ms": round(ms_unfused, 3),
        "fused_ms": round(ms_fused, 3),
        "traced_ms": round(ms_traced, 3),
        "world": world,
        "platform": platform,
    }
    if platform != "tpu":
        line["note"] = _SIM_NOTE
    print(json.dumps(line), flush=True)

    if trials > 0:
        from horovod_tpu.common.autotune import BayesianOptimizer

        bo = BayesianOptimizer(seed=0)
        # seed the GP with the three corners already measured
        bo.observe(1, 1e3, -ms_unfused)
        bo.observe(total * 2, 1e3, -ms_fused)
        bo.observe(default_threshold, default_cycle, -ms_default)
        for _ in range(trials):
            thr, cyc = bo.suggest()
            bo.observe(thr, cyc, -run_eager(thr, cyc))
        (best_thr, best_cyc) = bo.best()
        ms_best = run_eager(best_thr, best_cyc)
        line = {
            "metric": "fusion_autotune",
            "threshold": int(best_thr),
            "cycle_ms": round(float(best_cyc), 3),
            "value": round(ms_best, 3),
            "unit": "ms",
            "default_ms": round(ms_default, 3),
            "default_threshold": default_threshold,
            "trials": trials,
            "world": world,
            "platform": platform,
        }
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(line), flush=True)

    # restore shipped defaults (harmless — process exits anyway)
    fusion.threshold_bytes = default_threshold
    fusion.cycle_time_ms = default_cycle


if __name__ == "__main__":
    main()
