"""Deterministic fault injection for the control plane.

Every retry/degradation path added by ``common/retry.py`` and the
self-healing elastic driver must be *exercised*, not trusted — the
reference proves its elastic story the same way, by killing worker PIDs
and flipping discovery output mid-run (SURVEY.md §4.3). This module
makes those faults first-class, seeded, and schedulable, so a CI run
injects the exact same fault at the exact same hop every time.

A :class:`FaultPlan` is a list of rules bound to **named injection
sites** wired into the control plane:

========================  ====================================================
site                      where it fires
========================  ====================================================
``kv.request``            rendezvous KV client, start of every HTTP attempt
``kv.server``             rendezvous HTTP server, before handling a request
``kv.wait``               each poll iteration of ``RendezvousClient.wait``
``service.client``        signed-RPC client, start of every attempt
``service.server``        signed-RPC server, before dispatching a request
``heartbeat``             elastic worker heartbeat loop, before each stamp
``checkpoint.save``       ``CheckpointManager.save`` entry
``checkpoint.restore``    ``CheckpointManager.restore`` entry
``preemption.drain``      ``GracefulShutdown`` between telemetry dump and
                          the durable persist (the mid-save kill window)
``fusion.dispatch``       eager fusion flush entry (transport faults
                          surface as ``HorovodInternalError`` — the
                          elastic contract)
``local_sgd.sync``        each attempt of a local-SGD sync round's
                          inter (DCN) hop (``local_sgd.run_round``;
                          transport faults retry the round WHOLE under
                          the RetryPolicy, exhaustion DEFERS the round
                          — ``local_sgd.rounds_deferred`` — instead of
                          stalling or restarting the gang)
``serve.kv_transfer``     each HTTP attempt of a disaggregated-fleet
                          KV-page stream (serving/kv_transfer.py;
                          transport faults retry under the RetryPolicy,
                          exhaustion falls the request back to LOCAL
                          decode — ``serve.transfer_fallbacks`` — never
                          a client-visible 500)
``exe_cache.load``        persistent executable-cache read
                          (common/exe_cache.py; ``bitflip`` corrupts
                          the payload before the digest check so the
                          entry degrades to a COUNTED cold compile —
                          ``exe_cache.corrupt`` — never a failed init;
                          ``delay`` models slow disk)
``serve.worker_kill``     serving scheduler, top of every batcher round
                          (serving/batcher.py ``step``; transport kinds
                          crash the scheduler — accepted requests abort
                          and the Router's REPLAY path fires,
                          ``serve.replays``; ``kill`` SIGKILLs the
                          worker for the subprocess drills)
``serve.migrate``         each HTTP attempt of a live-migration stream
                          (serving/kv_transfer.py ``migrate`` frame;
                          transport faults retry under the RetryPolicy,
                          exhaustion brings the sequence home for a
                          local decode — ``serve.transfer_fallbacks`` —
                          never a dropped request)
========================  ====================================================

Sites the library doesn't own (a bench/smoke script's training loop)
can call :func:`inject` with their own names — the plan doesn't care.

Plan syntax (``HOROVOD_FAULT_PLAN``, or ``@/path/to/file`` holding the
same text): rules separated by ``;``, tokens within a rule by ``:``.

    seed=42;kv.request@2:reset;heartbeat:p=0.1:delay:ms=200;train.step@5:kill

* ``site@N`` — fire on the N-th hit of the site (1-based), once.
* ``site:p=0.25`` — fire each hit with probability 0.25, from a
  per-site seeded stream (deterministic given the site's hit order).
* kinds: ``delay`` (sleep ``ms``), ``reset`` (ConnectionResetError),
  ``timeout`` (TimeoutError), ``5xx`` (retryable server error; HTTP
  servers materialize it as a real 503), ``kill``
  (``SIGKILL`` to self — the process-death drill). Default: ``reset``.
* DATA kinds — ``nan`` and ``bitflip`` — never raise: :func:`inject`
  *returns* the fired kind and the site corrupts its own payload
  (``fusion.dispatch`` poisons one float of the next fused batch;
  ``checkpoint.save`` flips a byte of the just-written checkpoint so
  digest verification has something real to catch). A data kind fired
  at a site that cannot corrupt anything is counted and logged but
  otherwise a no-op — the counter still fails a drill that expected
  the corruption to surface.
* ``ms=250`` — delay duration (kind ``delay``; default 100).
* ``n=3`` — max fires for this rule (default: 1 for ``@N`` rules,
  unlimited for probabilistic/always rules).

Every fire bumps ``faults_injected`` (-> ``hvd_faults_injected`` on
``/metrics``) and ``chaos.<site>.<kind>`` in the metrics registry, so a
postmortem can correlate a slow step with the hop that was being
poked — and a chaos run that injected nothing fails loudly in CI.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Dict, List, Optional

from ..common.logging import get_logger

_log = get_logger("chaos")

KINDS = ("delay", "reset", "timeout", "5xx", "kill", "nan", "bitflip")

# Kinds that corrupt DATA instead of transport: fire() RETURNS them to
# the calling site (which owns the corruption) rather than raising.
DATA_KINDS = ("nan", "bitflip")


class InjectedServerError(RuntimeError):
    """The ``5xx`` fault: a transient server-side failure. Flagged
    ``retryable`` so ``common.retry.default_retryable`` classifies it
    without importing this module; HTTP handler sites catch it and
    answer a real 503 instead."""

    retryable = True
    code = 503

    def __init__(self, site: str):
        super().__init__(f"chaos: injected 503 at {site}")
        self.site = site


class FaultRule:
    """One parsed rule. ``at`` (1-based hit index) and ``p`` are
    mutually exclusive triggers; neither means fire on every hit."""

    def __init__(
        self,
        site: str,
        kind: str = "reset",
        at: Optional[int] = None,
        p: Optional[float] = None,
        ms: float = 100.0,
        n: Optional[int] = None,
    ) -> None:
        if kind not in KINDS:
            raise ValueError(
                f"fault kind {kind!r} not one of {'/'.join(KINDS)}"
            )
        if at is not None and p is not None:
            raise ValueError(f"{site}: '@{at}' and 'p={p}' are exclusive")
        self.site = site
        self.kind = kind
        self.at = at
        self.p = p
        self.ms = float(ms)
        # @N rules default to one shot; probabilistic/always rules to
        # unlimited (n= caps either)
        self.remaining = (
            int(n) if n is not None else (1 if at is not None else -1)
        )

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        trig = (
            f"@{self.at}" if self.at is not None
            else (f":p={self.p}" if self.p is not None else "")
        )
        return f"<FaultRule {self.site}{trig}:{self.kind}>"


class FaultPlan:
    """Seeded, deterministic fault schedule over named sites."""

    def __init__(self, rules: List[FaultRule], seed: int = 0) -> None:
        self.rules = list(rules)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._fired: List[Dict] = []
        # One RNG stream PER SITE, seeded by (plan seed, site name):
        # probability draws depend only on the site's own hit order, so
        # unrelated sites interleaving differently across runs cannot
        # perturb each other's schedules.
        self._rngs: Dict[str, random.Random] = {}

    # ------------------------------------------------------------- parsing

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``HOROVOD_FAULT_PLAN`` syntax (module docstring).
        ``@file`` specs are resolved by :func:`configure`/:func:`_load`,
        not here."""
        seed = 0
        rules: List[FaultRule] = []
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("seed="):
                seed = int(raw[len("seed="):])
                continue
            tokens = raw.split(":")
            head = tokens[0].strip()
            at: Optional[int] = None
            if "@" in head:
                head, _, at_s = head.partition("@")
                at = int(at_s)
            kw: Dict = {"site": head, "at": at}
            for tok in tokens[1:]:
                tok = tok.strip()
                if not tok:
                    continue
                if tok.startswith("p="):
                    kw["p"] = float(tok[2:])
                elif tok.startswith("ms="):
                    kw["ms"] = float(tok[3:])
                elif tok.startswith("n="):
                    kw["n"] = int(tok[2:])
                elif tok in KINDS:
                    kw["kind"] = tok
                else:
                    raise ValueError(
                        f"fault rule {raw!r}: unknown token {tok!r}"
                    )
            rules.append(FaultRule(**kw))
        return cls(rules, seed=seed)

    # ------------------------------------------------------------ read side

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def fired(self) -> List[Dict]:
        """Injection log: ``{site, kind, hit}`` per fire, in order."""
        with self._lock:
            return [dict(f) for f in self._fired]

    # ------------------------------------------------------------ fire side

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def fire(self, site: str) -> Optional[str]:
        """Count a hit at ``site`` and materialize any due fault.
        Raises the fault's exception (reset/timeout/5xx), sleeps
        (delay), or SIGKILLs the process (kill). DATA kinds
        (nan/bitflip) are returned to the caller — the site owns the
        corruption; returns None when nothing fired."""
        due: Optional[FaultRule] = None
        hit = 0
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            for rule in self.rules:
                if rule.site != site or rule.remaining == 0:
                    continue
                if rule.at is not None:
                    if hit != rule.at:
                        continue
                elif rule.p is not None:
                    if self._rng(site).random() >= rule.p:
                        continue
                if rule.remaining > 0:
                    rule.remaining -= 1
                due = rule
                break
            if due is not None:
                self._fired.append(
                    {"site": site, "kind": due.kind, "hit": hit}
                )
        if due is None:
            return None
        from ..common.metrics import registry as _metrics

        _metrics.counter("faults_injected")
        _metrics.counter(f"chaos.{site}.{due.kind}")
        _log.warning(
            "chaos: injecting %s at %s (hit %d)", due.kind, site, hit
        )
        if due.kind == "delay":
            time.sleep(due.ms / 1e3)
        elif due.kind == "reset":
            raise ConnectionResetError(
                f"chaos: injected connection reset at {site}"
            )
        elif due.kind == "timeout":
            raise TimeoutError(f"chaos: injected timeout at {site}")
        elif due.kind == "5xx":
            raise InjectedServerError(site)
        elif due.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif due.kind in DATA_KINDS:
            return due.kind
        return None


# ------------------------------------------------------------- global plan

_plan: Optional[FaultPlan] = None
_loaded = False
_load_lock = threading.Lock()


def _load() -> Optional[FaultPlan]:
    global _plan, _loaded
    with _load_lock:
        if not _loaded:
            _loaded = True
            spec = os.environ.get("HOROVOD_FAULT_PLAN", "").strip()
            if spec.startswith("@"):
                try:
                    with open(spec[1:]) as f:
                        spec = f.read().strip()
                except OSError as e:
                    _log.error("HOROVOD_FAULT_PLAN file unreadable: %s", e)
                    spec = ""
            if spec:
                _plan = FaultPlan.parse(spec)
                _log.warning(
                    "chaos: fault plan ACTIVE (%d rules, seed=%d)",
                    len(_plan.rules), _plan.seed,
                )
        return _plan


def active() -> Optional[FaultPlan]:
    """The process-wide plan (lazily loaded from env), or None."""
    if _loaded:
        return _plan
    return _load()


def configure(spec_or_plan) -> FaultPlan:
    """Install a plan programmatically (tests / smoke harnesses);
    accepts a spec string or a built FaultPlan."""
    global _plan, _loaded
    with _load_lock:
        _plan = (
            spec_or_plan
            if isinstance(spec_or_plan, FaultPlan)
            else FaultPlan.parse(spec_or_plan)
        )
        _loaded = True
        return _plan


def reset() -> None:
    """Drop the plan; the next :func:`active` re-reads the env."""
    global _plan, _loaded
    with _load_lock:
        _plan = None
        _loaded = False


def inject(site: str) -> Optional[str]:
    """The hook every instrumented site calls. Near-zero cost when no
    plan is configured (one global read + one branch). Transport kinds
    raise; DATA kinds (nan/bitflip) are returned so the site can
    corrupt its own payload — callers that can't corrupt ignore the
    return value."""
    p = _plan
    if p is None:
        if _loaded:
            return None
        p = _load()
        if p is None:
            return None
    return p.fire(site)
