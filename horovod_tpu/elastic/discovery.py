"""Host discovery for elastic jobs.

Rebuild of the reference's discovery layer (ref:
horovod/runner/elastic/discovery.py [V] — SURVEY.md §2.5): the driver
periodically asks "which hosts (with how many slots) are available right
now?", diffs against the current world, and triggers
rendezvous re-keying when the answer changes. The canonical source is a
user-supplied ``--host-discovery-script`` whose stdout lists one
``hostname:slots`` per line — kept verbatim, because every elastic
integration test in the reference drives membership by mutating that
script's output (SURVEY.md §4.3).
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List

from ..runner.hosts import HostInfo, parse_hosts


class HostDiscovery:
    """Interface: subclass and return the currently-available hosts.

    Tests subclass this with scripted sequences — the reference's own
    testing pattern (test_elastic_driver.py fake discovery [V]).
    """

    def find_available_hosts_and_slots(self) -> List[HostInfo]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user's discovery script; stdout = one host[:slots] per
    line. Empty output means "no hosts right now".

    A non-zero exit / timeout is retried under the shared
    ``RetryPolicy`` (site ``discovery``) before being treated as "no
    hosts": without the retry, ONE transient script failure (NFS blip,
    API rate-limit) read as a membership collapse and cost a full gang
    restart. Empty-but-successful output stays authoritative — the
    script said there is genuinely nothing."""

    def __init__(
        self, script: str, default_slots: int = 1, retry=None
    ) -> None:
        from ..common.retry import RetryPolicy

        self._script = script
        self._default_slots = default_slots
        # no deadline override: HOROVOD_RETRY_DEADLINE_S (default 60s)
        # applies, so a HUNG script still costs at most one 60s
        # subprocess timeout before the deadline stops the ladder —
        # refresh() runs synchronously in the driver loop, and a longer
        # stall here would starve heartbeat polling / failure detection.
        # Fast failures (the actual retry target) still get all
        # attempts.
        self._retry = retry or RetryPolicy.from_env("discovery")

    def _run_script(self) -> str:
        try:
            out = subprocess.run(
                self._script, shell=True, capture_output=True, timeout=60
            )
        except subprocess.TimeoutExpired as e:
            raise TimeoutError(
                f"discovery script timed out: {self._script!r}"
            ) from e
        if out.returncode != 0:
            raise ConnectionError(
                f"discovery script exited {out.returncode}: "
                f"{self._script!r}"
            )
        return out.stdout.decode()

    def find_available_hosts_and_slots(self) -> List[HostInfo]:
        from ..common.retry import RetryError

        try:
            stdout = self._retry.call(self._run_script)
        except (RetryError, ConnectionError, TimeoutError):
            return []
        hosts: List[HostInfo] = []
        for line in stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" not in line:
                line = f"{line}:{self._default_slots}"
            hosts.extend(parse_hosts(line))
        return hosts


class FixedHosts(HostDiscovery):
    """Static host list — elastic driver over a non-elastic allocation."""

    def __init__(self, hosts: List[HostInfo]) -> None:
        self._hosts = hosts

    def find_available_hosts_and_slots(self) -> List[HostInfo]:
        return list(self._hosts)


class HostManager:
    """Tracks available vs blacklisted hosts across discovery rounds
    (ref: HostManager in discovery.py + blacklist logic in driver.py [V]).

    A host lands on the blacklist when a worker on it fails; it stays
    there until the job ends (the reference's behavior — a flapping host
    is worse than a small world)."""

    def __init__(self, discovery: HostDiscovery) -> None:
        self._discovery = discovery
        self._lock = threading.Lock()
        self._blacklist: set = set()
        self._current: Dict[str, HostInfo] = {}

    def blacklist(self, hostname: str) -> None:
        with self._lock:
            self._blacklist.add(hostname)
            self._current.pop(hostname, None)

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return hostname in self._blacklist

    @property
    def blacklisted(self) -> List[str]:
        with self._lock:
            return sorted(self._blacklist)

    def current_hosts(self) -> List[HostInfo]:
        with self._lock:
            return [self._current[k] for k in sorted(self._current)]

    def refresh(self) -> bool:
        """Poll discovery; returns True when membership changed."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            usable = {
                h.hostname: h for h in found
                if h.hostname not in self._blacklist
            }
            changed = usable != self._current
            self._current = usable
            return changed
