"""Fleet trace assembler CLI: /traces scrapes → one chrome://tracing JSON.

Pulls span rings from every worker — live (``--url`` against the
MetricsServer ``/traces`` route, repeatable) or post-mortem (``--file``
against the flight recorder's ``<path>.spans`` JSON-lines siblings) —
then hands them to analysis/trace_merge.py for NTP-style skew
correction and Perfetto rendering.

Every live scrape is ITSELF an NTP edge: the reply carries the
worker's ``recv_ts``/``send_ts`` stamps, and this process's
send/receive times complete the quadruple — so a fleet whose workers
never spoke to each other directly still assembles onto one clock,
through the assembler's own hops.

Usage::

    python scripts/trace_assemble.py \
        --url http://10.0.0.1:9100/traces \
        --url http://10.0.0.2:9100/traces \
        --file /tmp/flight.jsonl.spans \
        [--trace <32-hex id>] [--list] --out fleet_trace.json

Open the output at chrome://tracing or https://ui.perfetto.dev.
"""

import argparse
import json
import os
import socket
import sys
import time
import urllib.request

# runnable as `python scripts/trace_assemble.py` from the repo root
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from horovod_tpu.analysis import trace_merge  # noqa: E402


def scrape(url: str, timeout: float = 10.0):
    """GET one /traces endpoint → (spans, scrape-hop edge)."""
    t_send = time.time()
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        payload = json.load(resp)
    t_recv = time.time()
    spans = payload.get("spans", [])
    # the worker stamps host/pid/role onto records lazily; backfill
    # from the payload identity for anything that predates a set_role
    for rec in spans:
        rec.setdefault("host", payload.get("host", "?"))
        rec.setdefault("pid", payload.get("pid", 0))
        if payload.get("role"):
            rec.setdefault("role", payload["role"])
    edge = None
    if "recv_ts" in payload and "send_ts" in payload:
        offset, err = trace_merge.ntp_offset(
            t_send, float(payload["recv_ts"]),
            float(payload["send_ts"]), t_recv,
        )
        edge = {
            "a": (socket.gethostname(), os.getpid()),
            "b": (str(payload.get("host", "?")),
                  int(payload.get("pid", 0))),
            "offset": offset,
            "err": err,
        }
    return spans, edge


def load_file(path: str):
    spans = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                spans.append(json.loads(line))
    return spans


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Assemble per-worker span rings into one "
        "skew-corrected chrome://tracing JSON."
    )
    ap.add_argument(
        "--url", action="append", default=[],
        help="a worker's /traces endpoint (repeatable)",
    )
    ap.add_argument(
        "--file", action="append", default=[],
        help="a flight-recorder .spans JSON-lines file (repeatable)",
    )
    ap.add_argument(
        "--trace", default=None,
        help="assemble only this trace_id (default: everything)",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="list trace ids + span counts and exit",
    )
    ap.add_argument(
        "--out", default="fleet_trace.json",
        help="output path (chrome://tracing JSON)",
    )
    args = ap.parse_args(argv)
    if not args.url and not args.file:
        ap.error("need at least one --url or --file source")

    spans = []
    extra_edges = []
    for url in args.url:
        got, edge = scrape(url)
        spans.extend(got)
        if edge is not None:
            extra_edges.append(edge)
        print(f"{url}: {len(got)} spans")
    for path in args.file:
        got = load_file(path)
        spans.extend(got)
        print(f"{path}: {len(got)} spans")

    counts = trace_merge.traces_in(spans)
    if args.list:
        for tid, n in sorted(
            counts.items(), key=lambda kv: -kv[1]
        ):
            print(f"{tid}  {n} spans")
        return 0
    if args.trace:
        spans = trace_merge.filter_trace(spans, args.trace)
        if not spans:
            print(f"trace {args.trace} not found", file=sys.stderr)
            return 1

    corrected, offsets = trace_merge.assemble(spans, edges=extra_edges)
    chrome = trace_merge.to_chrome(corrected, offsets)
    with open(args.out, "w") as f:
        json.dump(chrome, f)
    procs = {trace_merge.proc_key(r) for r in corrected}
    print(
        f"assembled {len(corrected)} spans / {len(counts)} trace(s) "
        f"across {len(procs)} process(es) -> {args.out}"
    )
    for key, off in sorted(offsets.items()):
        print(f"  clock offset {key[0]}:{key[1]}: {off * 1e3:+.3f} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
