"""In-process conformance fake of the slice of the ray API that
``horovod_tpu.executor``'s ray backend consumes.

This is a CONFORMANCE SHIM, not a ray reimplementation (VERDICT r4
item 6): it exists so the real-ray code path — ``RayExecutor.start``'s
placement-group reservation, ``run``'s per-rank remote tasks + the
rank→IP registry actor, and ``RayHostDiscovery.
find_available_hosts_and_slots`` (ref: horovod/ray/runner.py,
horovod/ray/elastic.py [V]) — EXECUTES in CI on machines without ray,
instead of sitting behind a perpetual importorskip.

Fidelity choices that make it a real conformance check rather than a
mock:

* remote FUNCTIONS run in genuine subprocesses (``spawn`` context), so
  the executor's cross-process assumptions hold or fail for real: the
  task payload (fn + args) must survive cloudpickle, the actor handle
  riding in the args must be picklable, and each worker's
  ``os.environ`` mutations are isolated the way separate ray workers'
  are.
* ACTORS live in the parent behind a socket RPC
  (multiprocessing.connection), so worker subprocesses exercise true
  cross-process actor calls — the rank-registration barrier in
  ``_worker`` genuinely blocks until every rank has registered.
* ``ray.get``/``ray.kill``/placement-group lifecycle follow ray's
  calling conventions (futures, ``timeout=``, ``GetTimeoutError``).

What it does NOT fake: resource accounting (placement groups always
"fit"), multi-node topology (every task reports 127.0.0.1 — which is
also what a single-host ray cluster reports), and scheduling (tasks
all start immediately). Tests that need those still require real ray
(``@pytest.mark.ray``).

Usage::

    from horovod_tpu.testing import fake_ray
    with fake_ray.installed():
        ex = RayExecutor(num_workers=2, use_ray=True)
        ...

``install()`` refuses to shadow a real ray installation.
"""

from __future__ import annotations

import contextlib
import inspect
import multiprocessing as mp
import os
import sys
import threading
import time
import types
from multiprocessing.connection import Client, Listener

# Per-session RPC authkey (ADVICE r5, security-low): generated lazily
# from os.urandom so a loopback listener from one test session can never
# be driven by a stale/foreign client that knows a hard-coded constant.
# Worker subprocesses (fresh interpreters under spawn) can't re-derive
# it, so the key travels INSIDE the pickled ActorHandle.
_AUTHKEY = None
_mp = mp.get_context("spawn")


def _session_authkey() -> bytes:
    global _AUTHKEY
    if _AUTHKEY is None:
        _AUTHKEY = os.urandom(32)
    return _AUTHKEY


class GetTimeoutError(TimeoutError):
    """ray.exceptions.GetTimeoutError stand-in."""


# ----------------------------------------------------------------- futures


class _Immediate:
    """Already-completed object ref (actor calls resolve eagerly)."""

    def __init__(self, value):
        self.value = value


class _TaskFuture:
    """Object ref for a subprocess task."""

    def __init__(self, proc, conn):
        self._proc = proc
        self._conn = conn
        self._result = None
        self._done = False

    def _wait(self, timeout=None, deadline=None):
        """Block until done. ``deadline`` (time.monotonic-based) wins
        over ``timeout``: ray.get over a LIST applies its timeout as one
        overall deadline for the whole batch, not per element."""
        if self._done:
            return
        if deadline is not None:
            timeout = deadline - time.monotonic()
        if timeout is not None and not self._conn.poll(max(timeout, 0)):
            raise GetTimeoutError(
                "task did not complete within the timeout"
            )
        try:
            self._result = self._conn.recv()
        except EOFError:
            self._result = (
                "err",
                RuntimeError(
                    "worker subprocess died without reporting a result "
                    f"(exitcode={self._proc.exitcode})"
                ),
            )
        self._proc.join()
        self._done = True


# ------------------------------------------------------------------ actors


class _ActorServer:
    """Hosts one actor instance in the parent; serves method calls over
    a socket so handles work from worker subprocesses."""

    def __init__(self, instance):
        self._instance = instance
        self._lock = threading.Lock()  # actor = single logical thread
        self.authkey = _session_authkey()
        self._listener = Listener(("127.0.0.1", 0), authkey=self.authkey)
        self.address = self._listener.address
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while not self._closed:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                break
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        try:
            while True:
                name, args, kwargs = conn.recv()
                try:
                    with self._lock:
                        out = getattr(self._instance, name)(
                            *args, **kwargs
                        )
                    conn.send(("ok", out))
                except Exception as e:  # noqa: BLE001 — transported
                    try:
                        conn.send(("err", e))
                    except Exception:
                        conn.send(("err", RuntimeError(repr(e))))
        except (EOFError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass


_ACTORS = {}  # address -> _ActorServer (parent process only)


class _ActorMethod:
    def __init__(self, handle, name):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        conn = Client(self._handle._address, authkey=self._handle._authkey)
        try:
            conn.send((self._name, args, kwargs))
            status, value = conn.recv()
        finally:
            conn.close()
        if status == "err":
            raise value
        return _Immediate(value)


class ActorHandle:
    """Picklable handle: (address, authkey) — works from any process.
    The per-session authkey rides in the pickle because a spawned
    worker's fresh interpreter has no other way to learn it."""

    def __init__(self, address, authkey):
        self._address = address
        self._authkey = authkey

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ActorMethod(self, name)


class _ActorClass:
    def __init__(self, cls):
        self._cls = cls

    def options(self, **_ignored):
        return self

    def remote(self, *args, **kwargs):
        server = _ActorServer(self._cls(*args, **kwargs))
        _ACTORS[server.address] = server
        return ActorHandle(server.address, server.authkey)


# ------------------------------------------------------------- remote fns


def _pickler():
    """cloudpickle when available (closures travel by value — ray's own
    behavior); stdlib pickle otherwise (module-level functions only) —
    the same fallback executor._dump_payload uses."""
    try:
        import cloudpickle

        return cloudpickle
    except ImportError:
        import pickle

        return pickle


def _child_main(payload, conn):
    """Subprocess entry: a fresh interpreter (spawn), so the fake must
    be installed BEFORE the task body's own ``import ray`` runs."""
    install()
    fn, args, kwargs = _pickler().loads(payload)
    try:
        conn.send(("ok", fn(*args, **kwargs)))
    except Exception as e:  # noqa: BLE001 — transported to parent
        try:
            conn.send(("err", e))
        except Exception:
            conn.send(("err", RuntimeError(repr(e))))
    finally:
        conn.close()


class _RemoteFunction:
    def __init__(self, fn):
        self._fn = fn

    def options(self, **_ignored):  # scheduling strategies: accepted
        return self

    def remote(self, *args, **kwargs):
        payload = _pickler().dumps((self._fn, args, kwargs))
        parent_conn, child_conn = _mp.Pipe()
        proc = _mp.Process(
            target=_child_main, args=(payload, child_conn)
        )
        proc.start()
        child_conn.close()
        return _TaskFuture(proc, parent_conn)


def remote(obj=None, **_ray_opts):
    """@ray.remote — on a class yields an actor class, on a function a
    remote function; the decorator-with-options form returns itself."""
    if obj is None:
        return remote
    if inspect.isclass(obj):
        return _ActorClass(obj)
    return _RemoteFunction(obj)


# ---------------------------------------------------------------- core api

_initialized = False


def init(*_args, ignore_reinit_error=False, **_kwargs):
    global _initialized
    if _initialized and not ignore_reinit_error:
        raise RuntimeError("ray.init called twice")
    _initialized = True


def is_initialized():
    return _initialized


def shutdown():
    global _initialized
    _initialized = False
    for addr in list(_ACTORS):
        _ACTORS.pop(addr).stop()


def get(refs, timeout=None):
    # ray semantics: over a list, ``timeout`` is ONE overall deadline
    # for the whole batch (ADVICE r5) — thread a single monotonic
    # deadline through every element rather than restarting the clock
    # per ref.
    deadline = None if timeout is None else time.monotonic() + timeout
    return _get_by_deadline(refs, deadline)


def _get_by_deadline(refs, deadline):
    if isinstance(refs, (list, tuple)):
        return type(refs)(_get_by_deadline(r, deadline) for r in refs)
    if isinstance(refs, _Immediate):
        return refs.value
    if isinstance(refs, _TaskFuture):
        refs._wait(deadline=deadline)
        status, value = refs._result
        if status == "err":
            raise value
        return value
    return refs


def kill(handle, no_restart=True):  # noqa: ARG001 — ray signature
    server = _ACTORS.pop(getattr(handle, "_address", None), None)
    if server is not None:
        server.stop()


def nodes():
    return [
        {
            "Alive": True,
            "NodeManagerAddress": "127.0.0.1",
            "Resources": {"CPU": float(os.cpu_count() or 1)},
        }
    ]


# ----------------------------------------------------- placement groups


class PlacementGroup:
    def __init__(self, bundles, strategy):
        self.bundle_specs = list(bundles)
        self.strategy = strategy

    def ready(self):
        return _Immediate(self)


def placement_group(bundles, strategy="PACK", **_kwargs):
    return PlacementGroup(bundles, strategy)


def remove_placement_group(pg):  # noqa: ARG001 — resources aren't real
    pass


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group=None,
        placement_group_bundle_index=None,
        **kwargs,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.kwargs = kwargs


# -------------------------------------------------------------- install


def _build_modules():
    ray_mod = types.ModuleType("ray")
    ray_mod.__fake_ray__ = True
    ray_mod.remote = remote
    ray_mod.get = get
    ray_mod.kill = kill
    ray_mod.init = init
    ray_mod.is_initialized = is_initialized
    ray_mod.shutdown = shutdown
    ray_mod.nodes = nodes
    ray_mod.exceptions = types.ModuleType("ray.exceptions")
    ray_mod.exceptions.GetTimeoutError = GetTimeoutError

    util = types.ModuleType("ray.util")
    util.__fake_ray__ = True
    util.get_node_ip_address = lambda: "127.0.0.1"

    pg_mod = types.ModuleType("ray.util.placement_group")
    pg_mod.__fake_ray__ = True
    pg_mod.placement_group = placement_group
    pg_mod.remove_placement_group = remove_placement_group
    pg_mod.PlacementGroup = PlacementGroup

    ss_mod = types.ModuleType("ray.util.scheduling_strategies")
    ss_mod.__fake_ray__ = True
    ss_mod.PlacementGroupSchedulingStrategy = (
        PlacementGroupSchedulingStrategy
    )

    util.placement_group = pg_mod
    util.scheduling_strategies = ss_mod
    ray_mod.util = util
    return {
        "ray": ray_mod,
        "ray.exceptions": ray_mod.exceptions,
        "ray.util": util,
        "ray.util.placement_group": pg_mod,
        "ray.util.scheduling_strategies": ss_mod,
    }


def install():
    """Register the fake under ``sys.modules['ray']`` (+ submodules).
    No-op when already installed; refuses to shadow REAL ray."""
    existing = sys.modules.get("ray")
    if existing is not None:
        if getattr(existing, "__fake_ray__", False):
            return
        raise RuntimeError(
            "refusing to install fake_ray over a real ray import"
        )
    try:
        import ray  # noqa: F401 — probe for a real installation

        raise RuntimeError(
            "refusing to install fake_ray: real ray is importable"
        )
    except ImportError:
        pass
    sys.modules.update(_build_modules())


def uninstall():
    for name in (
        "ray",
        "ray.exceptions",
        "ray.util",
        "ray.util.placement_group",
        "ray.util.scheduling_strategies",
    ):
        mod = sys.modules.get(name)
        if mod is not None and getattr(mod, "__fake_ray__", False):
            del sys.modules[name]
    shutdown()


@contextlib.contextmanager
def installed():
    install()
    try:
        yield sys.modules["ray"]
    finally:
        uninstall()
