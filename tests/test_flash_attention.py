"""Flash-attention kernels vs the dense oracle: forward values and all
three input gradients, causal and bidirectional, odd block splits.
(The reference has no analog — its attention lives in torch/cuDNN; this
is the TPU-native hot-op kernel, ops/flash_attention.py.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.flash_attention import flash_attention


from conftest import dense_attention_oracle as dense_attention


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), jnp.float32
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq,block", [(64, 16), (96, 32)])
def test_forward_matches_dense(causal, seq, block):
    b, h, d = 2, 3, 8
    q = _rand((b, seq, h, d), 0)
    k = _rand((b, seq, h, d), 1)
    v = _rand((b, seq, h, d), 2)
    out = flash_attention(q, k, v, causal=causal, block_q=block,
                          block_k=block)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    b, seq, h, d = 1, 32, 2, 8
    q = _rand((b, seq, h, d), 3)
    k = _rand((b, seq, h, d), 4)
    v = _rand((b, seq, h, d), 5)
    w = _rand((b, seq, h, d), 6)  # fixed cotangent-shaping weights

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        return jnp.sum(o * w)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-5
        )


def test_block_autoshrink_short_sequence():
    # seq smaller than the default block: blocks shrink, output exact
    b, seq, h, d = 1, 8, 1, 4
    q = _rand((b, seq, h, d), 7)
    k = _rand((b, seq, h, d), 8)
    v = _rand((b, seq, h, d), 9)
    out = flash_attention(q, k, v, causal=True)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_bf16_inputs():
    b, seq, h, d = 1, 32, 2, 8
    q = _rand((b, seq, h, d), 10).astype(jnp.bfloat16)
    k = _rand((b, seq, h, d), 11).astype(jnp.bfloat16)
    v = _rand((b, seq, h, d), 12).astype(jnp.bfloat16)
    out = flash_attention(q, k, v, causal=False, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        False,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-2, atol=2e-2
    )


def test_untileable_seq_falls_back_to_dense():
    """ViT's 197 tokens (prime-ish) can't tile: uses_flash must gate it
    off so models never hand Mosaic an impossible block shape."""
    from horovod_tpu.models.transformer import TransformerConfig
    from horovod_tpu.ops.flash_attention import supports_seq

    assert supports_seq(512) and supports_seq(128) and supports_seq(4)
    assert not supports_seq(197)
    cfg = TransformerConfig(flash_attention=True)
    assert cfg.uses_flash(seq=512)
    assert not cfg.uses_flash(seq=197)


def test_vmem_footprint_gate():
    """The dK/dV backward kernel stages the whole q-head group
    whole-sequence, so big seq*(h/kv_h) products must gate the model
    off the flash path before Mosaic fails compilation (ADVICE r4)."""
    from horovod_tpu.models.transformer import TransformerConfig
    from horovod_tpu.ops.flash_attention import bwd_vmem_bytes, fits_vmem

    # bench configs stay comfortably inside the budget
    assert fits_vmem(512, 64, 1, 2)  # gpt2-medium
    assert fits_vmem(512, 64, 16, 2)  # gpt2-medium @ 1 kv head
    assert fits_vmem(8192, 128, 1, 2)  # ulysses auto-gate cap, MHA
    # the advisor's example: r=8, seq 4k, d=128, bf16 — ~25 MiB
    assert bwd_vmem_bytes(4096, 128, 8, 2) > 16 * 2**20
    assert not fits_vmem(4096, 128, 8, 2)

    # uses_flash applies the same gate from config geometry
    big = TransformerConfig(
        num_layers=1, d_model=1024, num_heads=8, num_kv_heads=1,
        causal=True, flash_attention=True,
    )
    assert big.uses_flash(seq=512)
    assert not big.uses_flash(seq=4096)

    # direct kernel calls warn (forward-only may still compile)
    import warnings

    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 4096, 8, 128)), jnp.bfloat16)
    kv = jnp.asarray(rng.normal(size=(1, 4096, 1, 128)), jnp.bfloat16)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        flash_attention(q, kv, kv, causal=True)
    assert any("VMEM budget" in str(x.message) for x in w)


def test_vit_forward_with_flash_forced_on():
    """The full ViT (seq 197) must run even with flash_attention=True —
    the dense fallback, not a Mosaic compile error."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.models.vit import ViT, ViTConfig

    cfg = ViTConfig.tiny()  # seq = (32/8)^2 + 1 = 17 — also untileable
    model = ViT(cfg)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 32, 32, 3)), jnp.float32
    )
    params = model.init(jax.random.PRNGKey(0), x, train=False)
    out = jax.jit(lambda p, x: model.apply(p, x, train=False))(params, x)
    assert out.shape == (2, cfg.num_classes)


@pytest.mark.parametrize("layout", ["compact", "broadcast"])
def test_lse_interchange_layouts_agree(layout, monkeypatch):
    """The width-1 lse interchange (ADVICE r3: 128x less bwd HBM
    traffic) and the legacy broadcast escape hatch must produce
    identical gradients."""
    if layout == "broadcast":
        monkeypatch.setenv("HOROVOD_FLASH_LSE_BROADCAST", "1")
    else:
        monkeypatch.delenv("HOROVOD_FLASH_LSE_BROADCAST", raising=False)
    b, seq, h, d = 1, 64, 2, 8
    q, k, v = (_rand((b, seq, h, d), s) for s in (7, 8, 9))

    def loss(q, k, v):
        return flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16
        ).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = dense_attention(q, k, v, True)
    gq_r, gk_r, gv_r = jax.grad(
        lambda q, k, v: dense_attention(q, k, v, True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want in ((gq, gq_r), (gk, gk_r), (gv, gv_r)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )


def _dense_padded(q, k, v, causal, lengths):
    """Dense oracle for right-padded batches: key-validity mask per
    sequence, zero outputs at padded query rows (the flash contract)."""
    b, t, h, d = q.shape
    valid = jnp.arange(t)[None, :] < lengths[:, None]  # [b, t]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / np.sqrt(d)
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    if causal:
        tri = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(tri[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return jnp.where(valid[:, None, :, None].transpose(0, 2, 1, 3), o, 0.0)


@pytest.mark.parametrize("causal", [False, True])
def test_padded_forward_matches_dense(causal):
    """lengths= masks keys past each sequence's length and zeroes
    padded query rows — vs the masked dense oracle."""
    b, seq, h, d = 3, 64, 2, 8
    q, k, v = (_rand((b, seq, h, d), s) for s in (10, 11, 12))
    lengths = jnp.asarray([64, 37, 9], jnp.int32)  # full, odd, short
    out = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, lengths=lengths
    )
    ref = _dense_padded(q, k, v, causal, lengths)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    # padded rows are exactly zero, not just close
    assert float(np.abs(np.asarray(out)[1, 37:]).max()) == 0.0
    assert float(np.abs(np.asarray(out)[2, 9:]).max()) == 0.0


@pytest.mark.parametrize("causal", [False, True])
def test_padded_gradients_match_dense(causal):
    """All three gradients through the padded kernels vs the masked
    dense oracle; grads at padded positions must be exactly zero and
    everywhere finite (the degenerate-lse inf·0 hazard)."""
    b, seq, h, d = 2, 32, 2, 8
    q, k, v = (_rand((b, seq, h, d), s) for s in (13, 14, 15))
    w = _rand((b, seq, h, d), 16)
    lengths = jnp.asarray([32, 11], jnp.int32)

    def loss(q, k, v):
        return (
            flash_attention(
                q, k, v, causal=causal, block_q=8, block_k=8,
                lengths=lengths,
            ) * w
        ).sum()

    def ref_loss(q, k, v):
        return (_dense_padded(q, k, v, causal, lengths) * w).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r in zip(got, want):
        g, r = np.asarray(g), np.asarray(r)
        assert np.isfinite(g).all()
        np.testing.assert_allclose(g, r, rtol=2e-4, atol=2e-4)
        assert float(np.abs(g[1, 11:]).max()) == 0.0


def _dense_gqa(q, k, v, causal, lengths=None):
    """Dense oracle for grouped-query attention: repeat kv heads."""
    t = q.shape[1]
    r = q.shape[2] // k.shape[2]
    kk, vv = jnp.repeat(k, r, axis=2), jnp.repeat(v, r, axis=2)
    if lengths is None:
        return dense_attention(q, kk, vv, causal)
    return _dense_padded(q, kk, vv, causal, lengths)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("use_lengths", [False, True])
def test_gqa_matches_dense(causal, use_lengths):
    """Grouped-query attention (kv_heads < heads): the kernels read
    shared kv rows via the p//r index maps — fwd and all three grads
    vs the repeat-heads dense oracle, with and without padding."""
    b, t, h, g, d = 2, 64, 8, 2, 16
    q = _rand((b, t, h, d), 20)
    k = _rand((b, t, g, d), 21)
    v = _rand((b, t, g, d), 22)
    lengths = jnp.asarray([64, 23], jnp.int32) if use_lengths else None

    out = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, lengths=lengths
    )
    ref = _dense_gqa(q, k, v, causal, lengths)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    got = jax.grad(
        lambda q, k, v: (flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16,
            lengths=lengths) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    want = jax.grad(
        lambda q, k, v: (_dense_gqa(q, k, v, causal, lengths) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, bb in zip(got, want):
        assert a.shape == bb.shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=2e-4, atol=2e-4
        )


def test_gqa_rejects_bad_head_ratio():
    q = _rand((1, 16, 6, 8), 0)
    kv = _rand((1, 16, 4, 8), 1)  # 4 does not divide 6
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, kv, kv)


def _dense_window(q, k, v, window, lengths=None):
    """Dense oracle for the causal sliding window: mask row-col >= W
    on top of causal (and optional right-padding)."""
    t = q.shape[1]
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    band = (rows >= cols) & (rows - cols < window)
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / np.sqrt(d)
    if lengths is not None:
        valid = jnp.arange(t)[None, :] < lengths[:, None]
        s = jnp.where(valid[:, None, None, :], s, -1e30)
    s = jnp.where(band[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    if lengths is not None:
        valid = jnp.arange(t)[None, :] < lengths[:, None]
        o = jnp.where(valid[:, :, None, None], o, 0.0)
    return o


@pytest.mark.parametrize("window", [8, 24, 64])
def test_sliding_window_matches_dense(window):
    """Mistral-style causal sliding window, in-kernel band masking with
    clamped block loops — fwd + all grads vs the banded dense oracle."""
    b, t, h, d = 2, 64, 2, 8
    q, k, v = (_rand((b, t, h, d), s) for s in (30, 31, 32))
    out = flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16, window=window
    )
    ref = _dense_window(q, k, v, window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    got = jax.grad(
        lambda q, k, v: (flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16,
            window=window) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    want = jax.grad(
        lambda q, k, v: (_dense_window(q, k, v, window) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, bb in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=2e-4, atol=2e-4
        )


def test_sliding_window_composes_with_gqa_and_lengths():
    """window + GQA + lengths all at once (the Mistral trifecta)."""
    b, t, h, g, d = 2, 64, 4, 2, 8
    q = _rand((b, t, h, d), 33)
    k = _rand((b, t, g, d), 34)
    v = _rand((b, t, g, d), 35)
    lengths = jnp.asarray([64, 29], jnp.int32)
    r = h // g
    out = flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16,
        lengths=lengths, window=16,
    )
    ref = _dense_window(
        q, jnp.repeat(k, r, axis=2), jnp.repeat(v, r, axis=2),
        16, lengths,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    g_ = jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16,
        lengths=lengths, window=16).sum())(q)
    assert np.isfinite(np.asarray(g_)).all()
    assert float(np.abs(np.asarray(g_)[1, 29:]).max()) == 0.0


def test_sliding_window_requires_causal():
    q = _rand((1, 16, 2, 8), 0)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, causal=False, window=8)
