"""ZeRO-1-style sharded weight update for data-parallel training.

Beyond-parity, TPU-first (the reference has no analog): instead of
allreducing gradients and running the optimizer replicated, each rank

1. **reduce-scatters** the gradients (each rank receives the reduced
   1/N shard — half the wire bytes of a ring allreduce),
2. runs the optimizer update on its shard only (optimizer state — Adam
   moments etc. — lives sharded, 1/N of the memory per rank), then
3. **all-gathers** the parameter updates (the other half of the bytes).

Total communication equals one ring allreduce; optimizer math and
state memory drop to 1/N. This is the XLA "automatic cross-replica
sharding of weight update" / ZeRO-1 recipe (PAPERS.md: Xu et al.,
arXiv:2004.13336 — pattern reference only) expressed with explicit
collectives so it composes with the rest of the shard_map stack.

Contract:

* ``opt = ShardedDistributedOptimizer(optax.adam(1e-3))``
* ``state = opt.init(params)`` — OUTSIDE jit/shard_map. Every state
  leaf gains a leading ``world`` axis (rank r's shard at index r;
  scalar leaves like Adam's ``count`` are broadcast), so the whole
  state threads through ``jax.shard_map`` with a uniform
  ``P(WORLD_AXIS)`` spec.
* ``updates, state = opt.update(grads, state, params)`` — INSIDE
  ``shard_map`` over the world axis, full (replicated-shape) grads and
  params in, full updates out.

Supported inner transforms: elementwise ones (sgd, momentum, adam,
adamw, rmsprop, ...). Norm-based transforms like
``clip_by_global_norm`` would compute shard-LOCAL norms inside the
sharded update and silently train wrong; apply gradient clipping to
the full gradients BEFORE this wrapper instead. Construction runs a
**differential probe** (VERDICT r3 #5): the inner transform is applied
to a fixed pytree both whole and shard-wise — a mismatch means the
update is not elementwise and raises ``ValueError`` with the
clip-before-wrapper recipe instead of letting training silently
diverge. ``HOROVOD_SHARDED_OPT_PROBE=0`` skips the probe (e.g. for a
deliberately stochastic transform that the probe cannot compare).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .common.topology import WORLD_AXIS
from .ops.reduction_ops import Average, ReduceOp, Sum, resolve_op


def _pad_to(flat, n):
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _shard_host(x, n, r):
    """Host-side shard r of array x (init path, outside jit)."""
    x = jnp.asarray(x)
    if x.ndim == 0:
        return x
    flat = _pad_to(x.reshape(-1), n)
    return flat.reshape(n, -1)[r]


def _shard_dyn(x, n, idx):
    """Traced shard selection by the rank's axis_index (update path)."""
    flat = _pad_to(x.reshape(-1), n)
    return jax.lax.dynamic_index_in_dim(
        flat.reshape(n, -1), idx, axis=0, keepdims=False
    )


def _probe_nonelementwise(inner: optax.GradientTransformation) -> bool:
    """Differential probe: does `inner` give different updates when its
    inputs are sharded? Applies the transform to a fixed two-leaf pytree
    (values chosen so a global-norm clip at any common max_norm actually
    fires) once whole and once split into 2 shards per leaf — exactly
    the flatten-and-split geometry `update` uses. Elementwise chains
    (sgd/momentum/adam/adamw/rmsprop/weight-decay/schedules) match to
    float tolerance; anything coupling elements across the tree
    (clip_by_global_norm, adaptive_grad_clip, centralization) does not.

    Returns True when a mismatch is detected; False when the transform
    matches or cannot be probed (an inner transform that rejects the
    probe shapes is left to the docstring contract).
    """
    # The (128, 128) leaf exists for SHAPE-GATED couplings: adafactor
    # factors its second moment only when both dims >= 128, and the
    # sharded path always flattens to 1-D (where it falls back to
    # unfactored RMS) — a tiny-leaf probe would let it through.
    _det = np.linspace(-1.0, 1.0, 128 * 128, dtype=np.float32)
    params = {
        "w": jnp.asarray([1.0, -2.0, 3.0, -4.0], jnp.float32),
        "b": jnp.asarray([0.5, 0.25], jnp.float32),
        "m": jnp.asarray(_det.reshape(128, 128)),
    }
    # THREE steps with shard-norm ratios that shift every step: a
    # one-step probe misses transforms whose first update is
    # scale-invariant (clip→adam: Adam's step-1 update is ~sign(g), so
    # shard-local clip factors cancel until the moments carry history).
    # Norms ~10 ensure any realistic clip threshold actually fires.
    gm = jnp.asarray((_det + 0.37).reshape(128, 128))
    # top/bottom row-halves land in different shards after the flatten
    half = jnp.concatenate(
        [
            jnp.full((64, 128), 0.05, jnp.float32),
            jnp.full((64, 128), 6.0, jnp.float32),
        ]
    )
    grad_steps = [
        {
            "w": jnp.asarray([6.0, -8.0, 0.5, 2.0], jnp.float32),
            "b": jnp.asarray([-3.0, 1.5], jnp.float32),
            "m": gm * 3.0,
        },
        {  # shard-norm pattern reversed vs step 1
            "w": jnp.asarray([0.1, 0.2, 9.0, -7.0], jnp.float32),
            "b": jnp.asarray([4.0, -0.05], jnp.float32),
            "m": gm * half,
        },
        {
            "w": jnp.asarray([-5.0, 0.3, 0.4, 6.0], jnp.float32),
            "b": jnp.asarray([0.2, -8.0], jnp.float32),
            "m": gm * half[::-1],
        },
    ]

    def _split(tree, r):
        return jax.tree_util.tree_map(
            lambda x: x.reshape(2, -1)[r], tree
        )

    try:
        full_state = inner.init(params)
        full_upds = []
        for g in grad_steps:
            u, full_state = inner.update(g, full_state, params)
            full_upds.append(u)
        shard_upds = [[] for _ in grad_steps]
        for r in range(2):
            p_r = _split(params, r)
            state_r = inner.init(p_r)
            for step, g in enumerate(grad_steps):
                u_r, state_r = inner.update(_split(g, r), state_r, p_r)
                shard_upds[step].append(u_r)
        recombined = [
            jax.tree_util.tree_map(
                lambda a, b: jnp.concatenate(
                    [a.reshape(-1), b.reshape(-1)]
                ),
                *pair,
            )
            for pair in shard_upds
        ]
    except Exception:
        return False  # unprobeable shapes: fall back to the documented contract
    for full_u, shard_u in zip(full_upds, recombined):
        leaves_f = jax.tree_util.tree_leaves(full_u)
        leaves_s = jax.tree_util.tree_leaves(shard_u)
        if any(
            not np.allclose(
                np.asarray(a, np.float32).reshape(-1),
                np.asarray(b, np.float32).reshape(-1),
                rtol=1e-5,
                atol=1e-6,
            )
            for a, b in zip(leaves_f, leaves_s)
        ):
            return True
    return False


class ShardedDistributedOptimizer:
    """Data-parallel optimizer with reduce-scatter/all-gather weight
    update and 1/world-sharded optimizer state (module docstring)."""

    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        op: Optional[ReduceOp] = None,
        average: Optional[bool] = None,
        axis_name: str = WORLD_AXIS,
        world: Optional[int] = None,
        overlap_buckets: Optional[int] = None,
        overlap_min_bytes: Optional[int] = None,
        grad_guard: Optional[bool] = None,
        guard_max_skips: Optional[int] = None,
    ):
        """``overlap_buckets=N`` buckets the exchange (ops/overlap.py):
        gradients reduce-scatter as N independent per-bucket collectives
        (member leaves' padded [n, ·] panes concatenated column-wise —
        elementwise identical to the per-leaf scatter, so the shard
        values are bit-exact) and parameter updates all-gather the same
        way. Because the inner transform is ELEMENTWISE (the probe
        enforces it), the single ``inner.update`` call decomposes into
        per-leaf dataflow: bucket k's update math depends only on
        bucket k's reduce-scatter output, so XLA overlaps the update
        compute with the tail of the exchange — the ZeRO-1 shard-by-
        shard interleave of arXiv 2004.13336, with state/checkpoint
        layout unchanged. ``None`` defers to ``HOROVOD_OVERLAP``/
        ``HOROVOD_OVERLAP_BUCKETS``; 0 keeps the per-leaf collectives.

        ``grad_guard=True`` (``None`` defers to ``HOROVOD_GUARD``)
        adds the non-finite skip-step sentinel (common/guard.py).
        Unlike the replicated optimizer the reduce-scattered shards
        DIVERGE per rank — a NaN lands in exactly one rank's shard —
        so the flag costs one extra 4-byte scalar ``psum`` per step
        (DeepSpeed/AMP's overflow-flag allreduce) to keep the skip
        decision uniform across the gang. Skip semantics are gated by
        ``where`` selects: bad steps feed the inner transform zeroed
        gradients, discard its state delta, and emit zero updates;
        the guard counters ride the state under a ``"guard"`` key —
        an OPT-IN layout change (``reshard_state`` carries it across
        world changes; unguarded jobs keep the flat layout)."""
        self._inner = optimizer
        self._op = resolve_op(op, average)
        if self._op not in (Sum, Average):
            raise NotImplementedError(
                "ShardedDistributedOptimizer supports op=Sum/Average "
                "(Adasum's recursive combine needs full gradients)"
            )
        self._axis = axis_name
        self._world = world
        from .ops import overlap as _overlap

        if overlap_buckets is None:
            overlap_buckets = _overlap.default_buckets()
        self._overlap_buckets = int(overlap_buckets)
        self._overlap_min_bytes = (
            _overlap.default_min_bytes()
            if overlap_min_bytes is None
            else int(overlap_min_bytes)
        )
        from .common import guard as _guard

        self._guard_on = (
            bool(grad_guard)
            if grad_guard is not None
            else _guard.default_enabled()
        )
        self._max_skips = int(
            guard_max_skips
            if guard_max_skips is not None
            else _guard.default_max_skips()
        )
        self._guard_src = _guard.new_source() if self._guard_on else 0
        import os

        if os.environ.get(
            "HOROVOD_SHARDED_OPT_PROBE", "1"
        ) not in ("0", "false") and _probe_nonelementwise(optimizer):
            raise ValueError(
                "ShardedDistributedOptimizer: the inner optax transform "
                "is not elementwise — its update changes when gradients "
                "are sharded (differential probe mismatch). Norm-based "
                "transforms (clip_by_global_norm, adaptive_grad_clip, "
                "...) would compute shard-LOCAL norms and silently train "
                "wrong. Apply clipping to the FULL gradients before this "
                "wrapper instead, e.g.:\n"
                "    clipped, _ = optax.clip_by_global_norm(c).update("
                "grads, None)\n"
                "    updates, state = sharded_opt.update(clipped, state, "
                "params)\n"
                "or set HOROVOD_SHARDED_OPT_PROBE=0 to accept the risk "
                "for a transform the probe cannot compare (e.g. "
                "stochastic noise)."
            )

    # -- init (outside jit) ------------------------------------------------
    def init(self, params):
        from .common import basics

        n = self._world or basics.size()
        self._world = n
        shard_states = [
            self._inner.init(
                jax.tree_util.tree_map(
                    lambda p: _shard_host(p, n, r), params
                )
            )
            for r in range(n)
        ]
        # stack rank-major: every leaf gets a leading world axis, so the
        # state rides shard_map with ONE spec: P(axis_name)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *shard_states,
        )
        if not self._guard_on:
            return stacked
        # guard counters ride the same rank-major convention ([world]
        # rows of replicated scalars) so the whole state still threads
        # through shard_map with the single P(axis) spec
        z = jnp.zeros((n,), jnp.int32)
        return {"state": stacked, "guard": {"skips": z, "streak": z, "step": z}}

    # -- update (inside shard_map over axis_name) --------------------------
    @staticmethod
    def _is_guarded_layout(state) -> bool:
        return isinstance(state, dict) and set(state) == {
            "state", "guard",
        }

    def update(self, grads, state, params):
        guard_rows = None
        if self._guard_on:
            if not self._is_guarded_layout(state):
                raise ValueError(
                    "grad_guard is on but the optimizer state has the "
                    "flat (unguarded) layout — it was created before "
                    "the guard was enabled. Migrate it once with "
                    "reshard_state(state, params, world) (which "
                    "synthesizes zero guard counters), or re-run "
                    "init(params)."
                )
            guard_rows = state["guard"]
            state = state["state"]
        elif self._is_guarded_layout(state):
            raise ValueError(
                "the optimizer state carries guard counters "
                "({'state','guard'} layout) but grad_guard is off — "
                "it was checkpointed by a GUARDED run. Re-enable the "
                "guard, or downgrade the state once with "
                "reshard_state(state, params, world) (which strips "
                "the counters when the guard is off)."
            )
        n = jax.lax.axis_size(self._axis)
        if self._world is not None and n != self._world:
            raise ValueError(
                f"world changed between init ({self._world}) and update "
                f"({n}): call reshard_state(state, params, {n}) after a "
                "topology change — it carries the optimizer moments "
                "over (re-running init would reset them)"
            )
        idx = jax.lax.axis_index(self._axis)
        # shard_map hands each rank its [1, ...] state slice
        local_state = jax.tree_util.tree_map(lambda x: x[0], state)

        # 0-d leaves (scalar temperature etc.) stay replicated — exactly
        # like init's _shard_host — so state shapes are stable step-over-
        # step (a shape flip would force a retrace and break donation)
        def rs(g):
            if g.ndim == 0:
                red = jax.lax.psum(g, self._axis)
                return red / n if self._op == Average else red
            flat = _pad_to(g.reshape(-1), n).reshape(n, -1)
            red = jax.lax.psum_scatter(
                flat, self._axis, scatter_dimension=0, tiled=False
            )
            if self._op == Average:
                red = red / n
            return red

        sched = None
        if self._overlap_buckets:
            from .ops import overlap as _overlap

            g_leaves, g_def = jax.tree_util.tree_flatten(grads)
            nonscalar = [i for i, g in enumerate(g_leaves) if g.ndim > 0]
            sched = _overlap.schedule_for(
                [g_leaves[i] for i in nonscalar], g_def,
                self._overlap_buckets, self._overlap_min_bytes,
            )
            g_sh = self._bucketed_rs(
                g_leaves, g_def, nonscalar, sched, n
            )
        else:
            g_sh = jax.tree_util.tree_map(rs, grads)
        finite = None
        if self._guard_on:
            from .ops.traced import tree_finite

            # the scattered shards DIVERGE per rank (a NaN lands in
            # exactly one shard), so the flag must be agreed: one
            # 4-byte scalar psum — the only collective the guard adds
            ok_local = tree_finite(g_sh)
            bad = jax.lax.psum(
                jnp.where(ok_local, 0.0, 1.0).astype(jnp.float32),
                self._axis,
            )
            finite = bad == 0
            # feed the inner transform clean zeros on a bad step; its
            # output and state delta are discarded below anyway, this
            # just keeps NaNs out of user transforms entirely
            g_sh = jax.tree_util.tree_map(
                lambda g: jnp.where(finite, g, jnp.zeros_like(g)), g_sh
            )
        p_sh = jax.tree_util.tree_map(
            lambda p: p if p.ndim == 0 else _shard_dyn(p, n, idx), params
        )
        upd_sh, new_local = self._inner.update(g_sh, local_state, p_sh)
        if self._guard_on:
            # skip-step semantics by selection: zero updates, state of
            # the last APPLIED step (where, not multiply — selects are
            # NaN-safe)
            upd_sh = jax.tree_util.tree_map(
                lambda u: jnp.where(finite, u, jnp.zeros_like(u)), upd_sh
            )
            new_local = jax.tree_util.tree_map(
                lambda nl, ol: jnp.where(finite, nl, ol),
                new_local, local_state,
            )

        def gather(u, p):
            if p.ndim == 0:
                return u
            full = jax.lax.all_gather(u, self._axis, axis=0).reshape(-1)
            return full[: p.size].reshape(p.shape).astype(u.dtype)

        if sched is not None:
            upd = self._bucketed_ag(upd_sh, params, nonscalar, sched, gather)
        else:
            upd = jax.tree_util.tree_map(gather, upd_sh, params)
        new_state = jax.tree_util.tree_map(
            lambda x: x[None], new_local
        )
        if not self._guard_on:
            return upd, new_state
        import functools

        from .common import guard as _guard

        skips = guard_rows["skips"][0]
        streak = guard_rows["streak"][0]
        step = guard_rows["step"][0]
        streak_next = streak + 1

        def _quiet(_):
            return jnp.int32(0)

        def _fire(_):
            # skip branch only: the healthy path never reaches the host
            jax.debug.callback(
                functools.partial(
                    _guard.record_skip, max_skips=self._max_skips,
                    source=self._guard_src,
                ),
                streak_next, step,
            )
            return jnp.int32(0)

        jax.lax.cond(finite, _quiet, _fire, operand=None)
        one = jnp.ones((), jnp.int32)
        zero = jnp.zeros((), jnp.int32)
        new_guard = {
            "skips": jnp.where(finite, skips, skips + one)[None],
            "streak": jnp.where(finite, zero, streak_next)[None],
            "step": (step + one)[None],
        }
        return upd, {"state": new_state, "guard": new_guard}

    # -- bucketed exchange (overlap_buckets) -------------------------------
    def _bucketed_rs(self, g_leaves, g_def, nonscalar, sched, n):
        """Per-bucket reduce-scatter: member leaves' padded [n, cols]
        panes concat column-wise, ONE psum_scatter per bucket, shard
        split back per leaf. Elementwise identical to the per-leaf
        scatter (same per-element cross-replica sums), but the compiled
        program carries len(sched.buckets) INDEPENDENT collectives."""
        out = [None] * len(g_leaves)
        for i, g in enumerate(g_leaves):
            if g.ndim == 0:
                red = jax.lax.psum(g, self._axis)
                out[i] = red / n if self._op == Average else red
        for idxs in sched.buckets:
            panes = [
                _pad_to(g_leaves[nonscalar[j]].reshape(-1), n).reshape(n, -1)
                for j in idxs
            ]
            cols = [p.shape[1] for p in panes]
            buf = panes[0] if len(panes) == 1 else jnp.concatenate(
                panes, axis=1
            )
            red = jax.lax.psum_scatter(
                buf, self._axis, scatter_dimension=0, tiled=False
            )
            if self._op == Average:
                red = red / n
            off = 0
            for j, c in zip(idxs, cols):
                out[nonscalar[j]] = red[off : off + c]
                off += c
        return jax.tree_util.tree_unflatten(g_def, out)

    def _bucketed_ag(self, upd_sh, params, nonscalar, sched, gather):
        """Per-bucket all-gather of the update shards: the dual of
        :meth:`_bucketed_rs` (concat shards → ONE all_gather per bucket
        → per-leaf columns → unpad/reshape). Falls back to the per-leaf
        gather for a bucket whose update dtypes diverged (an inner
        transform that changes dtype per leaf)."""
        u_leaves, u_def = jax.tree_util.tree_flatten(upd_sh)
        p_leaves = u_def.flatten_up_to(params)
        out = [None] * len(u_leaves)
        for i, (u, p) in enumerate(zip(u_leaves, p_leaves)):
            if p.ndim == 0:
                out[i] = u
        for idxs in sched.buckets:
            mem = [u_leaves[nonscalar[j]] for j in idxs]
            if len({m.dtype for m in mem}) > 1:
                for j in idxs:
                    out[nonscalar[j]] = gather(
                        u_leaves[nonscalar[j]], p_leaves[nonscalar[j]]
                    )
                continue
            cols = [m.shape[0] for m in mem]
            buf = mem[0] if len(mem) == 1 else jnp.concatenate(mem)
            full = jax.lax.all_gather(buf, self._axis, axis=0)  # [n, L]
            off = 0
            for j, c in zip(idxs, cols):
                i = nonscalar[j]
                p = p_leaves[i]
                flat = full[:, off : off + c].reshape(-1)
                out[i] = (
                    flat[: p.size]
                    .reshape(p.shape)
                    .astype(u_leaves[i].dtype)
                )
                off += c
        return jax.tree_util.tree_unflatten(u_def, out)

    def state_spec(self):
        """The single PartitionSpec for the whole state pytree in
        shard_map in_specs/out_specs."""
        from jax.sharding import PartitionSpec as P

        return P(self._axis)

    # -- elastic -----------------------------------------------------------
    def reshard_state(self, state, params, new_world: int):
        """Host-side elastic reshard: convert the [old_world, ...]
        stacked state into [new_world, ...] PRESERVING optimizer
        moments across a gang restart — the elastic alternative to
        the "re-run init(params)" error, which would reset Adam
        moments on every world change. Call OUTSIDE jit, with the
        restored full params, after the new gang forms::

            state = opt.reshard_state(state, params, hvd.size())

        Mechanics: every sharded leaf is the optimizer moment over the
        param's zero-padded flat vector, split rank-major; resharding
        concatenates the old shards and re-splits at the new padding
        (tail entries beyond the param's size are padding positions —
        zeros that no update ever reads back). Replicated leaves
        (scalars like Adam's ``count``; 0-d params) re-broadcast."""
        if new_world < 1:
            raise ValueError(f"new_world must be >= 1, got {new_world}")
        guard_rows = None
        if self._guard_on:
            if self._is_guarded_layout(state):
                # guarded layout: reshard the inner state, then
                # re-stack the (replicated) guard counters at the new
                # world size — skip totals and the escalation streak
                # survive the gang change just like the Adam moments
                guard_rows = state["guard"]
                state = state["state"]
            else:
                # legacy flat state under a NEWLY-enabled guard:
                # resharding is the migration point — synthesize zero
                # counters so the resumed job starts guarded instead
                # of crashing at its first update
                zero = np.zeros((1,), np.int64)
                guard_rows = {"skips": zero, "streak": zero, "step": zero}
        elif self._is_guarded_layout(state):
            # guard turned OFF against a guarded checkpoint: the same
            # migration point downgrades — strip the counters and
            # reshard the inner state alone
            state = state["state"]
        template = self._inner.init(
            jax.tree_util.tree_map(
                lambda p: _shard_host(p, new_world, 0), params
            )
        )
        old_leaves = jax.tree_util.tree_leaves(state)
        tmpl_leaves, treedef = jax.tree_util.tree_flatten(template)
        if len(old_leaves) != len(tmpl_leaves):
            raise ValueError(
                "state does not match this optimizer's structure "
                f"({len(old_leaves)} leaves vs {len(tmpl_leaves)})"
            )
        out = []
        for o, t in zip(old_leaves, tmpl_leaves):
            o = np.asarray(o)
            t = jnp.asarray(t)
            if t.ndim == 0:
                # replicated leaf, stacked [old_world] -> [new_world]
                out.append(
                    jnp.broadcast_to(
                        jnp.asarray(o.reshape(-1)[0]), (new_world,)
                    )
                )
                continue
            per_rank = t.size  # new shard length (new padding)
            full = o.reshape(-1)
            need = new_world * per_rank
            if full.size < need:  # new world pads more: extend zeros
                full = np.pad(full, (0, need - full.size))
            else:  # old world padded more: drop only padding tail
                full = full[:need]
            out.append(
                jnp.asarray(full.reshape(new_world, per_rank), t.dtype)
            )
        self._world = new_world
        resharded = jax.tree_util.tree_unflatten(treedef, out)
        if guard_rows is None:
            return resharded
        new_guard = {
            key: jnp.broadcast_to(
                jnp.asarray(np.asarray(val).reshape(-1)[0], jnp.int32),
                (new_world,),
            )
            for key, val in guard_rows.items()
        }
        return {"state": resharded, "guard": new_guard}
