"""Serve smoke gate (ci.sh): the inference plane end-to-end.

Starts a 2-worker serve fleet on a toy transformer (each worker a real
subprocess: its own engine, batcher, HTTP frontend, and rendezvous-KV
capacity announcements against a driver-hosted RendezvousServer), then:

1. routes concurrent prompts of MIXED lengths through the
   straggler-aware ``Router`` (reading live announcements from the KV)
   and asserts every completion, plus that the load actually spread
   across both workers;
2. scrapes each worker's live ``/metrics`` and asserts the TTFT/TPOT
   summary quantiles and the slot-occupancy/queue/page gauges;
3. sends a shared-prefix burst (same system prompt, distinct tails) to
   ONE worker and asserts ``hvd_serve_prefix_hits`` > 0 on its live
   ``/metrics`` scrape — the paged memory plane's prefix cache can't
   silently rot;
4. fires a burst of in-flight requests, SIGTERMs both workers
   mid-service, and asserts the drain contract: every ACCEPTED request
   completes with its full token budget, both workers exit 143.

Exit 0 on success; any assertion failure is a CI failure.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

# runnable as `python scripts/serve_smoke.py` from the repo root
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

GEN_TOKENS = 6
BURST_TOKENS = 16

WORKER = """\
import os, sys
sys.path.insert(0, os.getcwd())
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.models.transformer import Transformer, TransformerConfig

cfg = TransformerConfig(
    vocab_size=61, num_layers=1, d_model=16, num_heads=2, d_ff=32,
    max_len=64, causal=True, dtype=jnp.float32,
)
model = Transformer(cfg)
params = model.init(
    jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
)
handle = hvd.serve(
    model, params, port=0, slots=4, max_new_tokens=8,
    addr="127.0.0.1", advertise_addr="127.0.0.1",
)
print("SERVING", handle.port, flush=True)
handle.wait()  # SIGTERM: drain hook finishes accepted work, exit 143
"""


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


def _get_text(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from horovod_tpu.runner.rendezvous import RendezvousServer
    from horovod_tpu.serving.frontend import Router, read_announcements

    workdir = tempfile.mkdtemp(prefix="hvd-serve-smoke-")
    server = RendezvousServer()
    port = server.start()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = "127.0.0.1"
    env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)

    script = os.path.join(workdir, "worker.py")
    with open(script, "w") as f:
        f.write(WORKER)
    procs = []
    for rank in range(2):
        wenv = dict(env, HOROVOD_RANK=str(rank))
        procs.append(
            subprocess.Popen(
                [sys.executable, script],
                env=wenv,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    try:
        ports = {}
        for rank, proc in enumerate(procs):
            line = proc.stdout.readline()
            assert "SERVING" in line, (
                f"worker {rank} failed to start: {line!r}\n"
                f"{proc.stderr.read()[-2000:]}"
            )
            ports[rank] = int(line.split()[1])
        # both workers announced into the KV
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            anns = read_announcements(server.store)
            if set(anns) >= {0, 1}:
                break
            time.sleep(0.05)
        anns = read_announcements(server.store)
        assert set(anns) >= {0, 1}, f"announcements missing: {anns}"
        assert anns[0]["port"] == ports[0] and anns[1]["port"] == ports[1]

        router = Router(server.store)

        # ---- phase 1: concurrent mixed-length prompts via the router
        prompts = [
            [3, 5, 7],
            [4, 6, 8, 10, 12, 14],
            [9] * 17,
            list(range(1, 31)),
            [11, 13, 15, 17, 19],
            [2] * 9,
        ]
        results = [None] * len(prompts)

        def route_one(i):
            results[i] = router.route(
                prompts[i], max_tokens=GEN_TOKENS, timeout=120
            )

        threads = [
            threading.Thread(target=route_one, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for i, res in enumerate(results):
            assert res is not None, f"request {i} never completed"
            assert res["status"] == "done", res
            assert len(res["tokens"]) == GEN_TOKENS, res
            assert res["ttft_ms"] > 0, res
        per_worker = {}
        for rank, p in ports.items():
            stats = _get_json(f"http://127.0.0.1:{p}/stats")
            per_worker[rank] = stats["prefills"]
        assert sum(per_worker.values()) == len(prompts), per_worker
        assert all(v > 0 for v in per_worker.values()), (
            f"routing did not spread: {per_worker}"
        )
        print(f"phase 1 OK: {len(prompts)} completions, "
              f"spread {per_worker}")

        # ---- phase 2: SLO quantiles + slot/page gauges on the live scrape
        for rank, p in ports.items():
            text = _get_text(f"http://127.0.0.1:{p}/metrics")
            for needle in (
                'serve_ttft_ms{quantile="0.5"}',
                'serve_ttft_ms{quantile="0.95"}',
                'serve_tpot_ms{quantile="0.5"}',
                'serve_tpot_ms{quantile="0.95"}',
                "hvd_serve_slots_total 4",
                "hvd_serve_slots_free",
                "hvd_serve_queue_depth",
                "hvd_serve_tokens_out",
                "hvd_serve_pages_total",
                "hvd_serve_pages_free",
            ):
                assert needle in text, (
                    f"worker {rank} /metrics missing {needle!r}:\n"
                    + text[:800]
                )
            assert "NaN" not in text
        # /healthz carries the page headroom the Router now prefers
        h = _get_json(f"http://127.0.0.1:{ports[0]}/healthz")
        assert "free_pages" in h and h["pages_total"] > 0, h
        print("phase 2 OK: TTFT/TPOT quantiles + slot/page gauges scraped")

        # ---- phase 2.5: shared-prefix burst → prefix-cache hits
        # (all to ONE worker so the shared pages are actually local)
        sys_prefix = [7, 11, 13, 17, 19, 23, 29, 31] * 2  # one full page
        tails = [[41, 43], [47, 53, 2], [3, 5]]
        for tail in tails:
            body = json.dumps(
                {"tokens": sys_prefix + tail, "max_tokens": 4}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ports[0]}/generate",
                data=body, method="POST",
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = json.load(resp)
            assert out["status"] == "done", out
        text = _get_text(f"http://127.0.0.1:{ports[0]}/metrics")
        hits = 0.0
        for line in text.splitlines():
            if line.startswith("hvd_serve_prefix_hits "):
                hits = float(line.split()[1])
        assert hits > 0, (
            "shared-prefix burst produced no prefix hits:\n"
            + "\n".join(
                ln for ln in text.splitlines() if "prefix" in ln
            )
        )
        print(f"phase 2.5 OK: shared-prefix burst hit the prefix cache "
              f"({int(hits)} pages attached)")

        # ---- phase 3: SIGTERM drain — every accepted request finishes
        burst = [[5, 6], [7, 8, 9], [1] * 12, [2, 3, 4, 5]]
        burst_results = [None] * len(burst)

        def burst_one(i):
            # split the burst across the two workers directly — the
            # drain contract is per-worker, and routing is phase 1's
            rank = i % 2
            body = json.dumps(
                {"tokens": burst[i], "max_tokens": BURST_TOKENS}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ports[rank]}/generate",
                data=body, method="POST",
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                burst_results[i] = json.load(resp)

        bthreads = [
            threading.Thread(target=burst_one, args=(i,))
            for i in range(len(burst))
        ]
        for t in bthreads:
            t.start()
        # SIGTERM only once every burst request is ACCEPTED (in a slot
        # or queued) — a drain may legitimately 503 un-submitted work
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            accepted = 0
            for rank, p in ports.items():
                h = _get_json(f"http://127.0.0.1:{p}/healthz")
                accepted += (
                    h["slots_total"] - h["free_slots"] + h["queue_depth"]
                )
            if accepted >= len(burst):
                break
            time.sleep(0.02)
        for proc in procs:
            proc.send_signal(signal.SIGTERM)
        for t in bthreads:
            t.join(timeout=120)
        for i, res in enumerate(burst_results):
            assert res is not None, f"burst request {i} lost in drain"
            assert res["status"] == "done", res
            assert len(res["tokens"]) == BURST_TOKENS, res
        rcs = [proc.wait(timeout=120) for proc in procs]
        assert rcs == [143, 143], f"worker exit codes: {rcs}"
        print(f"phase 3 OK: drain completed {len(burst)}/{len(burst)} "
              f"in-flight requests, workers exited {rcs}")
        print("serve-smoke OK")
        return 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
