"""``python -m horovod_tpu.runner`` — the hvdrun entry point
(ref: the ``horovodrun`` console script, horovod/runner/launch.py [V])."""

from .launch import main

main()
