#!/usr/bin/env bash
# Round-5 chip work, part b: the three NEW microbench harnesses, queued
# behind part a's capture roster (VERDICT r4 items 3/5/8):
#   * bench_fusion.py — eager fused-vs-unfused dispatch + GP autotune
#     validation (the fusion engine's premise, measured on chip)
#   * bench_int8.py — quantized_allreduce kernel-side tax vs plain psum
#   * bench_seq.py  — flash kernel seq sweep 1k/2k/4k/8k vs dense
# Same discipline as part a (skip-if-done, probe gate, HOLD file).
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r05

echo "=== chipwork_r05b start $(date -u +%F' '%H:%M)" >&2

while pgrep -f "chipwork_r05a.sh" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce|_fusion|_int8|_seq)?.py" >/dev/null 2>&1; do
  sleep 120
done

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}

wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}

hold_gate() {
  while [ -e scripts/CHIP_HOLD ]; do
    echo "=== CHIP_HOLD present; waiting $(date -u +%H:%M)" >&2
    sleep 60
  done
}

run_one() {  # multi-line JSON harnesses: keep EVERY json line
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}

cap() {
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

wait_backend

cap fusion_dispatch   python bench_fusion.py
cap int8_tax          python bench_int8.py
cap attn_seq_sweep    python bench_seq.py

echo "=== chipwork_r05b complete $(date -u +%F' '%H:%M)" >&2
