#!/usr/bin/env bash
# Unattended capture of the round-3 artifacts that the chip-claim wedge
# blocked (docs/perf.md "Backend outage note"): retry each bench with
# long patience — a failed claim takes ~20 min to report UNAVAILABLE,
# which doubles as the backoff. Never kill a claiming process: kills
# are what wedge the chip in the first place.

set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

try_capture() {
  local name="$1" attempts="$2"; shift 2
  local out="bench_results/${name}_r03.json"
  for i in $(seq 1 "$attempts"); do
    echo "=== $name attempt $i -> $out" >&2
    "$@" > "$out".tmp 2> "bench_results/${name}_r03.err"
    if grep -qE '^\{' "$out".tmp; then
      grep -E '^\{' "$out".tmp > "$out"
      rm -f "$out".tmp "bench_results/${name}_r03.err"
      echo "captured $name" >&2
      return 0
    fi
    rm -f "$out".tmp
    sleep 120
  done
  echo "GAVE UP: $name" >&2
  return 1
}

# gpt2_medium_r03.json stays the DEFAULT configuration (batch 8, remat
# on) — the config every doc cites; a fresh capture also adds the
# harness's new remat field. Exploratory variants get their own files.
try_capture gpt2_medium 6 env BENCH_MODEL=gpt2_medium python bench_lm.py
try_capture gpt2_medium_noremat 2 env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
try_capture allreduce 4 python bench_allreduce.py
try_capture vit_b16 2 env BENCH_INNER=1 BENCH_MODEL=vit_b16 python bench.py
echo "remaining-matrix done" >&2
