"""int8 quantized-allreduce compute-tax microbenchmark (VERDICT r4
item 5 / Weak #4).

`traced.quantized_allreduce`'s wire claim ("true ~4x fewer bytes on
ICI") is a byte model; single-chip hardware can't prove busbw, but the
KERNEL-SIDE cost — two stochastic-rounding quantize stages (Pallas
`int8_quantize`), dequant-sum, and the optional error-feedback residual
— is measurable today and decides whether the wire win survives at
real link speeds. This harness times, per payload size:

  * plain  — `traced.allreduce` (psum; folds to a copy at world=1)
  * quant  — `traced.quantized_allreduce`
  * quant_ef — the same with `return_residual=True` (EF carry)

and prints per size one JSON line:
  {"metric": "int8_compute_tax", "bytes": N, "value": quant_ms/plain_ms,
   "plain_ms": ..., "quant_ms": ..., "quant_ef_ms": ..., "ef_over_quant": ...}

Abort criterion for the docs (docs/perf.md): at a v5e-class ICI rate,
int8 wins only if (quant_ms − plain_ms) < 0.75 · wire_time_fp32(bytes)
· ring_factor — the tax must undercut the bytes it saves.

A/B leg for the quantized FUSED wire (ISSUE 2): `ab_fused` runs a
realistic multi-tensor composition through the eager int8 wire twice —
per-tensor (threshold=1: every entry dispatches alone, paying the
quantize tax N times) vs fused (one batch: quantize once over the
packed buffer, ONE dispatch) — and emits one JSON artifact per leg
under BENCH_ARTIFACT_DIR (default bench_results/int8), reporting
ms/step, dispatches/step and wire bytes saved. BENCH_DRYRUN=1 is the
CI smoke configuration (tiny sizes; harness-correctness only).

Env: BENCH_SIZES (bytes, comma-sep; default 1,4,16,64,256 MiB),
BENCH_ITERS (default 20), BENCH_FUSED_N (composition size, default 40),
BENCH_PLATFORM=cpu for the simulated mesh (sim lines carry the
quarantine note).
"""

import json
import os
import time

from _benchlib import stamp as _stamp
from functools import partial

_SIM_NOTE = (
    "logic-validation only (CPU simulation); NOT a TPU kernel-cost "
    "number"
)


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from _benchlib import sync as _sync
    from horovod_tpu.common.topology import WORLD_AXIS
    from horovod_tpu.ops import traced
    from horovod_tpu.ops.reduction_ops import Average

    devices = jax.devices()
    world = len(devices) if devices[0].platform != "tpu" else 1
    mesh = Mesh(np.array(devices[:world]), (WORLD_AXIS,))
    platform = devices[0].platform
    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    iters = int(os.environ.get("BENCH_ITERS", "2" if dryrun else "20"))
    sizes_env = os.environ.get("BENCH_SIZES")
    if sizes_env:
        sizes = [int(s) for s in sizes_env.split(",")]
    elif dryrun:
        sizes = [1 << 14]
    else:
        sizes = [1 << 20, 4 << 20, 16 << 20, 64 << 20, 256 << 20]

    def timed(step, x):
        x = step(step(x))  # compile fresh + committed-input variants
        _sync(x)
        t0 = time.perf_counter()
        for _ in range(iters):
            x = step(x)
        _sync(x)
        return (time.perf_counter() - t0) / iters * 1e3

    for nbytes in sizes:
        n = max(nbytes // 4, 1)

        def shmap(fn):
            return jax.jit(
                partial(
                    jax.shard_map,
                    mesh=mesh,
                    in_specs=P(WORLD_AXIS),
                    out_specs=P(WORLD_AXIS),
                    check_vma=False,
                )(fn)
            )

        plain = shmap(
            lambda x: traced.allreduce(x[0], op=Average)[None]
        )
        quant = shmap(
            lambda x: traced.quantized_allreduce(x[0], op=Average)[None]
        )

        def _ef(x):
            out, res = traced.quantized_allreduce(
                x[0], op=Average, return_residual=True
            )
            # fold the residual back in the way the EF optimizer does —
            # the carry must stay live, not be DCE'd
            return (out + 1e-6 * res)[None]

        quant_ef = shmap(_ef)

        x0 = jnp.asarray(
            np.random.default_rng(0)
            .normal(size=(world, n))
            .astype(np.float32)
        )
        ms_plain = timed(plain, x0)
        ms_quant = timed(quant, x0)
        ms_ef = timed(quant_ef, x0)
        line = {
            "metric": "int8_compute_tax",
            "bytes": nbytes,
            "world": world,
            "value": round(ms_quant / ms_plain, 3),
            "unit": "x",
            "plain_ms": round(ms_plain, 3),
            "quant_ms": round(ms_quant, 3),
            "quant_ef_ms": round(ms_ef, 3),
            "ef_over_quant": round(ms_ef / ms_quant, 3),
            "platform": platform,
        }
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)

    _ab_fused(world, platform, dryrun, iters)


def _ab_fused(world, platform, dryrun, iters):
    """A/B: the same multi-tensor composition through the eager int8
    wire per-tensor (threshold=1) vs fused (one batch, quantize once).
    The delta is the amortized per-dispatch quant tax the fused wire
    exists to remove."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu.common import basics
    from horovod_tpu.ops.compression import Compression

    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "int8")
    )
    os.makedirs(artifact_dir, exist_ok=True)
    if dryrun:
        n_tensors = int(os.environ.get("BENCH_FUSED_N", "6"))
        elems = 512
    else:
        n_tensors = int(os.environ.get("BENCH_FUSED_N", "40"))
        elems = (1 << 18) // 4  # 256 KiB each
    hvd.init()
    fusion = basics._state.fusion
    world = hvd.size()
    rng = np.random.default_rng(0)
    # Host arrays: the eager layer stages numpy to fresh device
    # buffers, so default-on donation can never consume a buffer a
    # later leg still reads (see bench_fusion.py).
    # Realistic composition: mixed sizes around the mean, like a
    # transformer block's parameter list.
    comp = [
        max(elems // 2 + (i * elems) // n_tensors, 8)
        for i in range(n_tensors)
    ]
    bufs = [
        rng.normal(size=(world, n)).astype(np.float32) for n in comp
    ]

    def step():
        handles = [
            hvd.allreduce_async(
                b, op=hvd.Average, name=f"qt{i}",
                compression=Compression.int8,
            )
            for i, b in enumerate(bufs)
        ]
        return [h.wait() for h in handles]

    def run(threshold):
        fusion.threshold_bytes = int(threshold)
        fusion.cycle_time_ms = 1e9
        step()  # warm: compile
        d0 = fusion.dispatches
        s0 = fusion.wire_bytes_saved_total
        t0 = time.perf_counter()
        for _ in range(iters):
            outs = step()
        _sync(sum(jnp.sum(o) for o in outs))
        ms = (time.perf_counter() - t0) / iters * 1e3
        return ms, {
            "dispatches_per_step": (fusion.dispatches - d0) // iters,
            "wire_saved_per_step": (fusion.wire_bytes_saved_total - s0)
            // iters,
        }

    total_bytes = sum(n * 4 for n in comp)

    def emit(mode, ms, extra):
        line = {
            "metric": "int8_fused_ab",
            "mode": mode,
            "n_tensors": n_tensors,
            "total_bytes": total_bytes,
            "world": world,
            "value": round(ms, 3),
            "unit": "ms",
            "platform": platform,
        }
        line.update(extra)
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)
        with open(
            os.path.join(artifact_dir, "int8_ab_fused.json"), "a"
        ) as f:
            f.write(json.dumps(_stamp(line)) + "\n")
        return ms

    ms_serial, extra = run(1)
    emit("per_tensor", ms_serial, extra)
    ms_fused, extra = run(1 << 40)
    extra["speedup_vs_per_tensor"] = round(ms_serial / ms_fused, 3)
    emit("fused", ms_fused, extra)


if __name__ == "__main__":
    main()
