"""HTTP frontend + fleet routing: ``hvd.serve(model, params, port=…)``.

The MetricsServer mold (common/telemetry.py): a stdlib
``ThreadingHTTPServer`` per worker, no new dependencies.

Routes:

* ``POST /generate`` — body ``{"tokens": [...], "max_tokens"?,
  "deadline_ms"?, "temperature"?, "top_k"?, "seed"?}``; blocks until
  the request completes (the handler
  thread parks on the request's event; the batcher's decode thread
  does the work) and replies the result JSON (tokens, status, TTFT,
  generation wall). 503 while draining; 429 when rejected.
* ``GET /healthz`` — liveness + capacity JSON (free slots, queue
  depth): the router's direct probe and the LB health check.
* ``GET /metrics`` — the registry render (common/telemetry.py) with
  the TTFT/TPOT families as real Prometheus summaries prepended, so a
  fleet scraper needs only this one port per worker.
* ``GET /stats`` — engine + batcher counters as JSON.

**Fleet plane:** each worker announces ``{rank, addr, port, free_slots,
queue_depth, ts}`` — plus, under the paged memory plane,
``free_pages`` / ``pages_total`` / ``prefix_hit_rate`` — into the
rendezvous KV (scope ``serve``) on a timer — the same channel
heartbeats ride. ``Router`` reads those announcements plus the
heartbeat straggler ledger (``runner.rendezvous.read_heartbeat_stats``
→ ``StallInspector.straggler_ranks``) and directs each request to the
least-loaded worker whose rank is NOT flagged — the PR 4 ledger driving
traffic, not just logs. Page headroom outranks slot headroom when both
are announced (pages are what admission actually gates on); old
``free_slots``-only blobs keep parsing, so mixed fleets mid-rollout
stay routable.

**Disaggregated fleets** (``HOROVOD_SERVE_ROLE``, docs/serving.md):
announcements carry ``role`` and — on decode workers — the
``transfer_port`` of the KV-ingest endpoint (serving/kv_transfer.py).
The Router sends ``/generate`` traffic to PREFILL workers when any
exist (unified workers otherwise) and NEVER to decode workers — their
requests arrive as streamed KV pages, not prompts. Blobs with no
``role`` field at all (old workers mid-rollout) parse as ``unified``
and stay routable.

**Drain:** ``serve()`` registers the frontend's drain with
``preemption.register_drain``, so a SIGTERM under ``GracefulShutdown``
(or the handler ``serve()`` installs itself) finishes every accepted
request, lets the in-flight HTTP responses flush, and only then lets
the worker leave the gang. With ``HOROVOD_SERVE_DRAIN_DEADLINE_S``
set, sequences still in flight past the deadline are LIVE-MIGRATED to
a reserved peer over the kv_transfer wire instead of run to completion
— the preemption grace window is honored without dropping a request.

**Crash-safe routing** (docs/robustness.md "serving failure model"):
the Router keeps each request's full submission (it IS the journal —
prompt, sampling knobs, client request_id) and, when a worker dies
mid-call, transparently REPLAYS it on a live worker
(``serve.replays``), tombstoning the dead worker's announcement for
one freshness period so the stale blob can't re-attract the next
request. Workers dedupe by client ``request_id`` in a bounded TTL
cache (``serve.replay_dedupe_hits``), so a router-side timeout retry
returns the cached result instead of recomputing. The driver's
dead-host set (scope ``serve`` key ``dead_hosts``,
runner/rendezvous.py) evicts announcements immediately — routing never
waits out the freshness window on a host the control plane already
declared dead. ``HOROVOD_SERVE_HEDGE_MS`` arms tail-latency hedging:
a backup request fires after the delay, first writer wins
(``serve.hedges``).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..common import tracing as _tracing
from ..common.logging import TRACE as _TRACE, get_logger
from ..common.metrics import registry as _metrics
from ..common.telemetry import (
    PROM_CONTENT_TYPE,
    hub as _telemetry_hub,
    render_prometheus,
)
from .batcher import ContinuousBatcher, Rejected

_log = get_logger("serve.frontend")

SERVE_SCOPE = "serve"
DEFAULT_ANNOUNCE_INTERVAL_S = 1.0
# announcements older than this are a dead/partitioned worker as far
# as routing is concerned
DEFAULT_ANNOUNCE_TTL_S = 10.0
# completed-result dedupe cache bound (entries): TTL prunes first, this
# caps worst-case memory under a flood of unique request_ids
DEDUPE_MAX_ENTRIES = 1024


def put_announcement(client, rank: int, payload: dict) -> None:
    """Worker side of the capacity ledger (KVStore or RendezvousClient
    surface — the same duality as heartbeats)."""
    client.put(SERVE_SCOPE, str(int(rank)), json.dumps(payload).encode())


def read_announcements(store_or_client) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for key in store_or_client.keys(SERVE_SCOPE):
        raw = store_or_client.get(SERVE_SCOPE, key)
        if raw is None:
            continue
        try:
            rank = int(key)
            obj = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(obj, dict) and "port" in obj:
            out[rank] = obj
    return out


class ServeFrontend:
    def __init__(
        self,
        batcher: ContinuousBatcher,
        port: int = 0,
        addr: str = "0.0.0.0",
        advertise_addr: str = "127.0.0.1",
        rank: Optional[int] = None,
        announce_client=None,
        announce_interval_s: float = DEFAULT_ANNOUNCE_INTERVAL_S,
        transfer_server=None,
    ) -> None:
        self.batcher = batcher
        # KVTransferServer on decode-role workers: its port travels in
        # the capacity blob, its unexpired reservations debit the
        # announced page headroom
        self.transfer_server = transfer_server
        self.advertise_addr = advertise_addr
        self.rank = self._resolve_rank(rank)
        self._announce_client = announce_client
        self._announce_interval = float(announce_interval_s)
        self._announce_stop = threading.Event()
        self._announce_thread: Optional[threading.Thread] = None
        self._draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # completed-result dedupe cache: client request_id → (result,
        # expiry). A Router replay or client retry of work this worker
        # already finished returns the cached result — the idempotency
        # half of crash-safe serving (a retry after a router-side
        # timeout must not recompute, and MUST answer even mid-drain).
        self._dedupe: "OrderedDict[str, tuple]" = OrderedDict()
        self._dedupe_lock = threading.Lock()
        # client-visible status mix (/generate replies only): the
        # failure ladder counts replays/fallbacks, this counts what the
        # CLIENT saw (docs/robustness.md runbook row)
        self._status_lock = threading.Lock()
        self._status_counts = {2: 0, 4: 0, 5: 0}
        # live-migration coordinator, built lazily on the first
        # deadline-bounded drain (unified workers have no transfer
        # coordinator wired otherwise)
        self._migrator = None
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                _log.log(_TRACE, "http " + fmt, *args)

            def _reply(
                self, code, body: bytes, ctype: str, headers=None,
            ) -> None:
                self._last_code = code
                if getattr(self, "_count_status", False):
                    outer._note_status(code)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code, obj, headers=None) -> None:
                self._reply(
                    code, json.dumps(obj).encode(), "application/json",
                    headers=headers,
                )

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    return self._json(200, outer.capacity())
                if path == "/stats":
                    stats = dict(outer.batcher.stats())
                    stats.update(outer.batcher.engine.stats())
                    stats["slo"] = outer.batcher.recorder.summaries()
                    return self._json(200, stats)
                if path == "/metrics":
                    hub = _telemetry_hub()
                    body = "\n".join(
                        outer.batcher.recorder
                        .render_prometheus_summaries()
                    ) + "\n" + render_prometheus(
                        _metrics.snapshot(), hub.percentiles()
                    )
                    return self._reply(
                        200, body.encode(), PROM_CONTENT_TYPE
                    )
                if path == "/traces":
                    # span ring + identity + clock stamps (same payload
                    # as the MetricsServer route): serve workers run
                    # their own HTTP plane, and trace_assemble must be
                    # able to scrape them live — the scrape itself is
                    # an NTP edge for the skew-corrected assembly
                    recv_ts = time.time()
                    rec = _tracing.recorder()
                    return self._json(200, {
                        "spans": rec.spans(),
                        "capacity": rec.capacity,
                        "host": rec.host,
                        "pid": rec.pid,
                        "role": rec.role,
                        "recv_ts": recv_ts,
                        "send_ts": time.time(),
                    })
                return self._reply(
                    404, b"not found\n", "text/plain; charset=utf-8"
                )

            def do_POST(self):
                # read the body FIRST: HTTP/1.1 keep-alive means an
                # early reply that leaves body bytes on the socket
                # desynchronizes the connection's next request
                recv_ts = time.time()
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                path = self.path.split("?", 1)[0]
                if path != "/generate":
                    return self._reply(
                        404, b"not found\n", "text/plain; charset=utf-8"
                    )
                # client-visible status mix: counted for the request
                # surface only, never the scrape GETs
                self._count_status = True
                # trace plane: adopt the incoming traceparent (or mint
                # a root when tracing is on and the client brought
                # none); every reply echoes X-Trace-Id plus the
                # recv/send clock stamps the assembler's skew
                # estimation feeds on. tctx None (the default) costs
                # nothing downstream.
                tctx = _tracing.adopt(
                    self.headers.get(_tracing.TRACEPARENT_HEADER)
                )
                span = _tracing.start_span("http.generate", tctx)
                hdrs = None
                if tctx is not None:
                    hdrs = _tracing.server_stamps(recv_ts)
                    hdrs[_tracing.TRACE_ID_HEADER] = tctx.trace_id
                try:
                    return self._generate(body, span, hdrs)
                finally:
                    self._count_status = False
                    if span is not None:
                        span.end(code=getattr(self, "_last_code", 0))

            def _generate(self, body, span, hdrs):
                trace_ctx = span.ctx if span is not None else None
                try:
                    payload = json.loads(body or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError(
                            f"body must be a JSON object, got "
                            f"{type(payload).__name__}"
                        )
                    tokens = payload["tokens"]
                except (json.JSONDecodeError, KeyError, ValueError) as e:
                    return self._json(
                        400, {"error": f"bad request: {e}"}, headers=hdrs
                    )
                request_id = str(payload.get("request_id") or "")
                if span is not None and request_id:
                    span.tag(request_id=request_id)
                if request_id:
                    # the dedupe check runs BEFORE the draining gate: a
                    # retry for work this worker already completed must
                    # get its cached answer even mid-drain — that's the
                    # whole point of keying results by request_id
                    hit = outer._dedupe_get(request_id)
                    if hit is not None:
                        _metrics.counter("serve.replay_dedupe_hits")
                        if span is not None:
                            span.tag(outcome="dedupe_hit")
                        return self._json(200, hit, headers=hdrs)
                if outer.draining:
                    return self._json(
                        503, {"error": "draining", "retry": True},
                        headers=hdrs,
                    )
                with outer._inflight_lock:
                    outer._inflight += 1
                try:
                    try:
                        req = outer.batcher.submit(
                            tokens,
                            max_new_tokens=payload.get("max_tokens"),
                            deadline_ms=payload.get("deadline_ms"),
                            temperature=float(
                                payload.get("temperature", 0.0)
                            ),
                            top_k=int(payload.get("top_k", 0)),
                            seed=payload.get("seed"),
                            trace=trace_ctx,
                        )
                    except Rejected as e:
                        # draining (planned or crash) is the WORKER's
                        # state -> 503 so the Router fails over; 429 is
                        # reserved for requests that can never fit
                        code = 503 if outer.draining else 429
                        return self._json(
                            code, {"error": str(e)}, headers=hdrs
                        )
                    except (TypeError, ValueError) as e:
                        # well-formed JSON, malformed fields (string
                        # tokens, non-numeric budgets): the client's
                        # fault, so the client gets told — not a torn
                        # socket the router misreads as a dead worker
                        return self._json(
                            400, {"error": f"bad request: {e}"},
                            headers=hdrs,
                        )
                    req.wait()
                    # "error" = the scheduler crashed under this
                    # request (batcher._abort_all): a worker fault,
                    # 500 so the router fails over instead of the
                    # client treating it as a completion
                    code = 500 if req.status == "error" else 200
                    result = req.result()
                    if span is not None:
                        span.tag(outcome=req.status)
                    if request_id and code == 200:
                        outer._dedupe_put(request_id, result)
                    return self._json(code, result, headers=hdrs)
                finally:
                    with outer._inflight_lock:
                        outer._inflight -= 1

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((addr, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _resolve_rank(rank: Optional[int]) -> int:
        if rank is not None:
            return int(rank)
        from ..common import basics

        if basics.is_initialized():
            return basics.rank()
        cfg = basics.live_config()
        return cfg.rank if cfg.rank is not None else 0

    # ------------------------------------------------------------ lifecycle

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def draining(self) -> bool:
        """Planned drain OR the batcher's crash-drain: either way this
        worker takes no new requests, and every surface (503s,
        /healthz, the KV announcement) must say so consistently."""
        return self._draining or self.batcher.draining

    def capacity(self) -> dict:
        mgr = self.batcher.engine.manager.stats()
        draining = self.draining
        cap = {
            "ok": not draining,
            "rank": self.rank,
            "addr": self.advertise_addr,
            "port": self.port,
            "role": getattr(self.batcher, "role", "unified"),
            "free_slots": mgr["slots_free"],
            "slots_total": mgr["slots_total"],
            "queue_depth": self.batcher.queue_depth(),
            "draining": draining,
            # the driver's dead-host set names HOSTS (its blacklist
            # unit); announcing ours lets the Router match either way
            "host": socket.gethostname(),
            "ts": time.time(),
        }
        if self.transfer_server is not None:
            cap["transfer_port"] = self.transfer_server.port
        if "pages_total" in mgr:
            # paged memory plane: page headroom is the truthful
            # capacity signal (admission is gated on it, not on
            # slots). free_pages is watermark-adjusted — what
            # admission may actually spend — and a SATURATED pool
            # flips the slot capacity to 0 too, so even a
            # slots-only/legacy Router steers away from a worker
            # that would only queue the request.
            manager = self.batcher.engine.manager
            free_pages = manager.admission_headroom()
            if self.transfer_server is not None:
                # pages promised to in-flight transfers are spoken for:
                # two senders must not both be told the same headroom
                free_pages = max(
                    free_pages - self.transfer_server.reserved_pages(), 0
                )
            cap["free_pages"] = free_pages
            cap["pages_total"] = mgr["pages_total"]
            cap["prefix_hit_rate"] = round(mgr["prefix_hit_rate"], 4)
            if free_pages <= 0:
                cap["free_slots"] = 0
            if cap["free_slots"] <= 0:
                # the symmetric clamp: admission needs a slot AND
                # pages, so a slot-saturated worker must not look
                # page-rich to a Router that prefers page headroom
                cap["free_pages"] = 0
        return cap

    def start(self) -> int:
        if self._thread is not None:
            return self.port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="hvd-serve-frontend",
            daemon=True,
        )
        self._thread.start()
        client = self._resolve_announce_client()
        if client is not None:
            self._announce_client = client
            self._announce_stop.clear()
            self._announce_thread = threading.Thread(
                target=self._announce_loop,
                name="hvd-serve-announce",
                daemon=True,
            )
            self._announce_thread.start()
        _log.info(
            "serve frontend on port %d (rank %d)", self.port, self.rank
        )
        return self.port

    def _resolve_announce_client(self):
        if self._announce_client is not None:
            return self._announce_client
        from ..common import basics

        cfg = basics.live_config()
        if not cfg.rendezvous_addr or not cfg.rendezvous_port:
            return None
        from ..runner.rendezvous import _client_from_cfg

        return _client_from_cfg(cfg)

    def _announce_loop(self) -> None:
        while not self._announce_stop.is_set():
            self.announce()
            self._announce_stop.wait(self._announce_interval)

    def announce(self) -> None:
        """One capacity PUT into the rendezvous KV (scope ``serve``)."""
        if self._announce_client is None:
            return
        try:
            put_announcement(
                self._announce_client, self.rank, self.capacity()
            )
        except (OSError, RuntimeError) as e:
            _log.debug("serve announce failed: %s", e)

    def drain(
        self, timeout: float = 30.0,
        migrate_after: Optional[float] = None,
    ) -> bool:
        """SIGTERM half of the lifecycle: refuse new work, finish the
        accepted work, let the in-flight responses flush. Announces the
        drained state so the router stops sending traffic.

        ``migrate_after`` (default: ``HOROVOD_SERVE_DRAIN_DEADLINE_S``;
        0 = off) bounds how long in-flight sequences may keep decoding
        locally: past it, they are live-migrated to a reserved peer
        over the kv_transfer wire and finish there — the preemption
        grace window is honored without dropping a request."""
        self._draining = True
        self.announce()
        if migrate_after is None:
            from ..common import basics

            deadline_s = basics.live_config().serve_drain_deadline_s
            migrate_after = deadline_s if deadline_s > 0 else None
        if migrate_after is not None and self.batcher.engine.paged:
            ok = self.batcher.drain(
                timeout=timeout,
                migrate_after=float(migrate_after),
                on_deadline=self._migrate_inflight,
            )
        else:
            ok = self.batcher.drain(timeout=timeout)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.01)
        self.announce()
        return ok

    def _resolve_migrator(self):
        """The TransferCoordinator the deadline drain streams through:
        a prefill worker reuses the batcher's wired coordinator; other
        roles build one lazily against the same announcement channel."""
        if self.batcher.transfer is not None:
            return self.batcher.transfer
        if self._migrator is None:
            from .kv_transfer import TransferCoordinator

            self._migrator = TransferCoordinator(
                self.batcher.engine,
                client_factory=self._resolve_announce_client,
            )
        return self._migrator

    def _migrate_inflight(self, records) -> None:
        """batcher.drain's on_deadline hook: stream every exported
        in-flight record to a reserved peer; a record that can't go
        anywhere falls back to the local queue (the drain keeps
        stepping it inline). Never raises — a migration failure must
        degrade to the classic run-to-completion drain, not kill the
        drain thread."""
        try:
            coord = self._resolve_migrator()
        except Exception as e:  # noqa: BLE001 — degrade, don't die
            _log.warning(
                "no migration coordinator (%s); draining %d sequence(s) "
                "locally", e, len(records),
            )
            coord = None
        for rec in records:
            if coord is None:
                self.batcher.requeue_fallback(
                    rec["req"], rec["kept"], rec["length"]
                )
                continue
            try:
                coord.migrate(self.batcher, rec)
            except Exception as e:  # noqa: BLE001 — per-record fallback
                _log.warning(
                    "migration of request %d failed at export (%s); "
                    "falling back to local decode", rec["req"].id, e,
                )
                self.batcher.requeue_fallback(
                    rec["req"], rec["kept"], rec["length"]
                )

    def _note_status(self, code: int) -> None:
        """Per-reply status accounting on the request surface:
        ``serve.http_2xx/4xx/5xx`` counters plus the derived
        ``serve.http_error_rate`` gauge (non-2xx fraction of every
        /generate reply this worker ever sent)."""
        klass = int(code) // 100
        if klass not in (2, 4, 5):
            klass = 5 if klass > 5 else 4
        with self._status_lock:
            self._status_counts[klass] += 1
            counts = dict(self._status_counts)
        _metrics.counter(f"serve.http_{klass}xx")
        total = sum(counts.values())
        if total:
            _metrics.gauge(
                "serve.http_error_rate",
                (counts[4] + counts[5]) / total,
            )

    # ----------------------------------------------------------- dedupe cache

    def _dedupe_get(self, request_id: str) -> Optional[dict]:
        with self._dedupe_lock:
            hit = self._dedupe.get(request_id)
            if hit is None:
                return None
            result, expiry = hit
            if time.monotonic() >= expiry:
                del self._dedupe[request_id]
                return None
            return result

    def _dedupe_put(self, request_id: str, result: dict) -> None:
        from ..common import basics

        ttl = float(basics.live_config().serve_dedupe_ttl_s)
        if ttl <= 0:
            return
        now = time.monotonic()
        with self._dedupe_lock:
            for k in [
                k for k, (_, exp) in self._dedupe.items() if exp <= now
            ]:
                del self._dedupe[k]
            self._dedupe[request_id] = (result, now + ttl)
            self._dedupe.move_to_end(request_id)
            while len(self._dedupe) > DEDUPE_MAX_ENTRIES:
                self._dedupe.popitem(last=False)

    def stop(self) -> None:
        self._announce_stop.set()
        if self._announce_thread is not None:
            self._announce_thread.join(timeout=5)
            self._announce_thread = None
        if self._thread is None:
            self._httpd.server_close()
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        self._thread = None


class Router:
    """Thin fleet router over the rendezvous KV: capacity announcements
    in, straggler ledger in, pick-and-forward out. Stateless apart from
    a local free-slot debit so a burst routed between two announcement
    refreshes spreads instead of piling onto one worker."""

    def __init__(
        self,
        store_or_client,
        straggler_factor: Optional[float] = None,
        announce_ttl_s: float = DEFAULT_ANNOUNCE_TTL_S,
    ) -> None:
        self._store = store_or_client
        self._ttl = float(announce_ttl_s)
        from ..common.stall_inspector import StallInspector

        self._inspector = StallInspector(
            straggler_factor=(
                3.0 if straggler_factor is None else straggler_factor
            )
        )
        self._debits: Dict[int, int] = {}
        # rank -> (last announced ts value, local monotonic stamp of
        # when it last CHANGED): freshness is judged in the router's
        # clock domain, so cross-host wall-clock skew can't silently
        # drop a live worker (or keep a dead one) from routing
        self._seen_ts: Dict[int, tuple] = {}
        # rank -> (announced ts at failure, monotonic expiry): a worker
        # that failed a live call is tombstoned for one freshness
        # period — its pre-crash announcement must not re-attract the
        # NEXT request; a ts ADVANCE (the worker actually announcing
        # again) clears it early
        self._tombstones: Dict[int, tuple] = {}
        self._lock = threading.Lock()

    def snapshot(self) -> Dict[int, dict]:
        """Live worker view: non-draining announcements whose ts keeps
        ADVANCING, freshness judged on the router's own monotonic
        clock. First sight of a rank has no change history, so the
        announced wall ts is the tiebreak there (a wall-stale leftover
        from a dead worker stays out); after that only advancement
        counts, so a live worker with a skewed clock re-qualifies on
        its next announce instead of being silently unroutable."""
        now = time.monotonic()
        out = {}
        dead_hosts, dead_ranks = self._dead_set()
        with self._lock:
            for rank, ann in read_announcements(self._store).items():
                if ann.get("draining"):
                    continue
                if (
                    rank in dead_ranks
                    or str(ann.get("host") or "") in dead_hosts
                    or str(ann.get("addr") or "") in dead_hosts
                ):
                    # the driver already declared this host dead: evict
                    # NOW instead of waiting out the freshness window
                    continue
                ts = float(ann.get("ts", 0))
                tomb = self._tombstones.get(rank)
                if tomb is not None:
                    if ts == tomb[0] and now < tomb[1]:
                        # the same blob the worker announced before it
                        # failed a live call: a pre-crash leftover
                        continue
                    # ts advanced (the worker is actually alive) or
                    # the tombstone aged out: forgive
                    del self._tombstones[rank]
                prev = self._seen_ts.get(rank)
                if prev is None:
                    # wall tiebreak, once: mark wall-stale first sights
                    # as already-expired; they revive on any advance
                    wall_fresh = abs(time.time() - ts) <= self._ttl
                    stamp = now if wall_fresh else now - self._ttl - 1
                    self._seen_ts[rank] = (ts, stamp)
                    if wall_fresh:
                        out[rank] = ann
                elif prev[0] != ts:
                    self._seen_ts[rank] = (ts, now)
                    out[rank] = ann
                elif now - prev[1] <= self._ttl:
                    out[rank] = ann
        return out

    def _dead_set(self):
        """The driver's published dead/quarantined set (scope ``serve``
        key ``dead_hosts``): hostnames + the serve ranks mapped onto
        them at publication. Empty on any read failure — the dead set
        accelerates eviction, it never blocks routing."""
        from ..runner.rendezvous import read_dead_hosts

        try:
            dead = read_dead_hosts(self._store)
        except (OSError, RuntimeError, ValueError):
            return set(), set()
        return (
            {str(h) for h in dead.get("hosts", ())},
            {int(r) for r in dead.get("ranks", ())},
        )

    def tombstone(self, rank: int, ann: Optional[dict] = None) -> None:
        """Mark a worker that failed a LIVE call: its current
        announcement stays unroutable for one freshness period (or
        until the worker announces a newer ts — proof of life)."""
        with self._lock:
            self._tombstones[int(rank)] = (
                float((ann or {}).get("ts", 0.0)),
                time.monotonic() + self._ttl,
            )

    def straggler_ranks(self) -> List[int]:
        """The PR 4 ledger, read fleet-side: feed every heartbeat's
        piggybacked step stats into a StallInspector and flag the slow
        ranks — the routing table's deny list."""
        from ..runner.rendezvous import read_heartbeat_stats

        try:
            stats = read_heartbeat_stats(self._store)
        except (OSError, RuntimeError):
            return []
        for rank, payload in stats.items():
            self._inspector.record_heartbeat(
                rank,
                ts=payload.get("ts"),
                step=payload.get("step"),
                step_ms_p50=payload.get("step_ms_p50"),
                last_step_ts=payload.get("last_step_ts"),
            )
        return self._inspector.straggler_ranks()

    def pick(self, exclude=()) -> Optional[dict]:
        """The least-loaded live worker whose rank is not flagged by
        the straggler ledger; flagged workers are only used when they
        are ALL that is left (degraded beats down). ``exclude`` drops
        ranks a caller already failed against in this routing round."""
        from .kv_transfer import worker_role

        workers = self.snapshot()
        for rank in exclude:
            workers.pop(rank, None)
        # role split: decode workers take KV transfers, never prompts —
        # they are not /generate candidates. When prefill workers exist
        # they take every fresh admission (that IS the disaggregation);
        # unified workers carry the traffic otherwise. worker_role()
        # maps blobs with NO role field (old workers mid-rollout) to
        # "unified", so a mixed-version fleet keeps routing.
        workers = {
            r: w for r, w in workers.items()
            if worker_role(w) != "decode"
        }
        prefill = {
            r: w for r, w in workers.items()
            if worker_role(w) == "prefill"
        }
        workers = prefill or workers
        if not workers:
            return None
        flagged = set(self.straggler_ranks())
        healthy = {r: w for r, w in workers.items() if r not in flagged}
        pool = healthy or workers
        if not healthy:
            _log.warning(
                "all serve workers flagged as stragglers (%s); routing "
                "to flagged rank anyway", sorted(flagged),
            )
        with self._lock:
            def load(item):
                rank, w = item
                # page headroom gates admission on the paged plane, but
                # every admission ALSO needs a slot — min() folds both
                # into request-capacity units, so a page-rich worker
                # with one free slot can't outrank an idle slab worker,
                # and the 1-per-route debit below subtracts in the same
                # unit. Old announcements carrying only free_slots keep
                # routing exactly as before — mixed fleets mid-rollout
                # stay routable.
                pages = w.get("free_pages")
                slots_free = w.get("free_slots", 0)
                if pages is None:
                    free = slots_free
                else:
                    free = min(int(slots_free), int(pages))
                free -= self._debits.get(rank, 0)
                return (-free, w.get("queue_depth", 0), rank)

            rank, ann = min(pool.items(), key=load)
            self._debits[rank] = self._debits.get(rank, 0) + 1
            return dict(ann, rank=rank)

    def credit(self, rank: int) -> None:
        """Return a debit after a routed request completes."""
        with self._lock:
            if self._debits.get(rank, 0) > 0:
                self._debits[rank] -= 1

    def _post_generate(self, ann: dict, body: bytes,
                       timeout: float, span=None) -> dict:
        """One /generate POST against one worker — the routing unit
        every path (sequential, replay, hedge arm) shares. With a leg
        ``span``, the traceparent header carries its context to the
        worker and the reply's clock-stamp echo is tagged onto it (the
        NTP edge the skew-corrected assembly estimates offsets from)."""
        import urllib.request

        url = (
            f"http://{ann.get('addr', '127.0.0.1')}:{ann['port']}"
            f"/generate"
        )
        headers = {"Content-Type": "application/json"}
        if span is not None:
            headers[_tracing.TRACEPARENT_HEADER] = (
                span.ctx.to_traceparent()
            )
        req = urllib.request.Request(
            url, data=body, headers=headers, method="POST",
        )
        t_send = time.time()
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read().decode())
            _tracing.tag_hop(span, t_send, time.time(), resp.headers)
        return out

    def _note_failure(self, ann: dict, err: Exception, span=None) -> None:
        """Classify a failed live call. A 503 is an ORDERLY refusal
        (draining/rejected before admission) — plain failover, the
        worker's own announcement will say so. Everything else (5xx,
        transport fault, torn response) means the worker went dark with
        the request possibly in flight: the retry on the next candidate
        is a REPLAY (``serve.replays``) and the dark worker's stale
        announcement is tombstoned so it can't re-attract traffic.
        The leg ``span``, when traced, closes tagged with the same
        classification."""
        import urllib.error

        _metrics.counter("serve.route_failover")
        if isinstance(err, urllib.error.HTTPError) and err.code == 503:
            if span is not None:
                span.end(outcome="failover", code=503)
            return
        _metrics.counter("serve.replays")
        if span is not None:
            span.end(
                outcome="replayed",
                error=f"{type(err).__name__}: {err}",
            )
        self.tombstone(ann["rank"], ann)

    def route(
        self,
        tokens,
        max_tokens: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        timeout: float = 60.0,
        attempts: int = 3,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: Optional[int] = None,
        request_id: Optional[str] = None,
        hedge_ms: Optional[float] = None,
        trace=None,
    ) -> dict:
        """POST /generate on the picked worker; a dead or draining pick
        fails over to the next candidate — the full submission below IS
        the durability journal, so a worker that dies mid-call gets the
        request transparently REPLAYED on a live one, idempotent by
        ``request_id`` (generated here when the client brings none; the
        workers' dedupe cache keys on it). Sampling knobs ride the
        payload verbatim (temperature 0 = greedy; a caller-pinned seed
        keeps a replayed request reproducible on whichever worker
        serves it). ``hedge_ms`` (default ``HOROVOD_SERVE_HEDGE_MS``,
        0 = off) fires a backup request on a second worker after the
        delay — first writer wins, the loser is discarded."""
        import urllib.error

        payload: dict = {"tokens": list(map(int, tokens))}
        if max_tokens is not None:
            payload["max_tokens"] = int(max_tokens)
        if deadline_ms is not None:
            payload["deadline_ms"] = float(deadline_ms)
        if temperature:
            payload["temperature"] = float(temperature)
        if top_k:
            payload["top_k"] = int(top_k)
        if seed is not None:
            payload["seed"] = int(seed)
        payload["request_id"] = str(request_id or uuid.uuid4().hex)
        body = json.dumps(payload).encode()
        last_err: Optional[Exception] = None
        failed: set = set()
        # trace plane: the routing side mints the request's root
        # context (or adopts the caller's); every leg below — first
        # try, replay, hedge arm — is a SIBLING span under it tagged
        # with its outcome, and the traceparent header carries the
        # leg's context to the worker it hits.
        tctx = trace if trace is not None else _tracing.mint()
        root = _tracing.root_span(
            "route", tctx, request_id=payload["request_id"]
        )
        try:
            if hedge_ms is None:
                from ..common import basics

                hedge_ms = basics.live_config().serve_hedge_ms
            if hedge_ms and float(hedge_ms) > 0:
                out, failed, last_err = self._route_hedged(
                    body, timeout, float(hedge_ms) / 1e3, tctx=tctx,
                )
                if out is not None:
                    if root is not None:
                        root.tag(outcome="ok")
                        out.setdefault("trace_id", tctx.trace_id)
                    return out
                # both arms dark: fall through to the sequential replay
                # loop with the failed ranks already excluded
            for _ in range(max(int(attempts), 1)):
                ann = self.pick(exclude=failed)
                if ann is None:
                    if failed:
                        raise RuntimeError(
                            f"routing failed: every live worker errored "
                            f"({sorted(failed)}): {last_err}"
                        )
                    raise RuntimeError("no live serve workers announced")
                leg = _tracing.start_span(
                    "route.attempt", tctx, rank=int(ann["rank"]),
                    mode="replay" if failed else "first",
                )
                try:
                    out = self._post_generate(
                        ann, body, timeout, span=leg
                    )
                    if leg is not None:
                        leg.end(outcome="ok")
                        out.setdefault("trace_id", tctx.trace_id)
                    if root is not None:
                        root.tag(outcome="ok")
                    return out
                except urllib.error.HTTPError as e:
                    if e.code == 503 or e.code >= 500:
                        # draining / server fault: the WORKER's problem,
                        # fail over to the next candidate
                        last_err = e
                        failed.add(ann["rank"])
                        self._note_failure(ann, e, span=leg)
                        continue
                    # 4xx: the REQUEST's problem — every worker would
                    # say the same thing; surface the actionable error
                    # instead of burning the fleet and masking it as
                    # 'all dead'
                    if leg is not None:
                        leg.end(outcome="rejected", code=e.code)
                    try:
                        detail = json.loads(
                            e.read().decode()
                        ).get("error", "")
                    except (ValueError, OSError):
                        detail = ""
                    raise RuntimeError(
                        f"request rejected by rank {ann['rank']} "
                        f"(HTTP {e.code}): {detail or e.reason}"
                    ) from e
                except (OSError, ValueError) as e:
                    last_err = e
                    failed.add(ann["rank"])
                    self._note_failure(ann, e, span=leg)
                    continue
                finally:
                    self.credit(ann["rank"])
            raise RuntimeError(
                f"routing failed after {attempts} attempts: {last_err}"
            )
        finally:
            if root is not None:
                if "outcome" not in root.tags:
                    root.tag(outcome="error")
                root.end()

    def _route_hedged(
        self, body: bytes, timeout: float, hedge_s: float, tctx=None,
    ):
        """Primary fires immediately; if no result lands within
        ``hedge_s`` a backup fires on a second worker
        (``serve.hedges``). First writer wins — the losing arm's
        response is discarded when it eventually lands. Returns
        ``(result_or_None, failed_ranks, last_err)``; the caller's
        sequential loop finishes the job when every arm went dark. Each
        arm gets its own ``route.attempt`` sibling span under ``tctx``
        tagged ``hedge=primary|backup`` — won/discarded/error outcomes
        make the race legible in the assembled trace."""
        primary = self.pick()
        if primary is None:
            return None, set(), None
        cv = threading.Condition()
        box: dict = {"errors": []}

        def arm(ann, hedge_tag):
            leg = _tracing.start_span(
                "route.attempt", tctx,
                rank=int(ann["rank"]), hedge=hedge_tag,
            )
            try:
                out = self._post_generate(ann, body, timeout, span=leg)
            except Exception as e:  # noqa: BLE001 — arm failure is data
                with cv:
                    box["errors"].append((ann, e, leg))
                    cv.notify_all()
            else:
                with cv:
                    won = "result" not in box
                    box.setdefault("result", out)
                    cv.notify_all()
                if leg is not None:
                    leg.end(outcome="ok" if won else "discarded")
            finally:
                self.credit(ann["rank"])

        threading.Thread(
            target=arm, args=(primary, "primary"),
            name="hvd-route-primary", daemon=True,
        ).start()
        arms = 1
        deadline = time.monotonic() + timeout
        with cv:
            cv.wait(timeout=hedge_s)
            if "result" not in box and not box["errors"]:
                backup = self.pick(exclude={primary["rank"]})
                if backup is not None:
                    _metrics.counter("serve.hedges")
                    arms = 2
                    threading.Thread(
                        target=arm, args=(backup, "backup"),
                        name="hvd-route-hedge", daemon=True,
                    ).start()
            while "result" not in box and len(box["errors"]) < arms:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not cv.wait(timeout=remaining):
                    break
            errors = list(box["errors"])
            result = box.get("result")
        failed: set = set()
        last_err: Optional[Exception] = None
        for ann, err, leg in errors:
            failed.add(ann["rank"])
            last_err = err
            self._note_failure(ann, err, span=leg)
        return result, failed, last_err


class ServeHandle:
    """What ``hvd.serve`` returns: the running plane + its lifecycle."""

    def __init__(
        self, engine, batcher, frontend, shutdown_ctx=None,
        transfer_server=None,
    ):
        self.engine = engine
        self.batcher = batcher
        self.frontend = frontend
        self.transfer_server = transfer_server
        self._shutdown_ctx = shutdown_ctx
        self._stopped = threading.Event()

    @property
    def port(self) -> int:
        return self.frontend.port

    def drain(self, timeout: float = 30.0) -> bool:
        return self.frontend.drain(timeout=timeout)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until stop() — the serve-worker main thread parks
        here (SIGTERM interrupts via the drain hook + process exit)."""
        return self._stopped.wait(timeout)

    def stop(self) -> None:
        from .. import preemption

        preemption.unregister_drain(self._drain_hook)
        self.frontend.stop()
        self.batcher.stop()
        if self.transfer_server is not None:
            self.transfer_server.stop()
        if self._shutdown_ctx is not None:
            self._shutdown_ctx.__exit__(None, None, None)
            self._shutdown_ctx = None
        self._stopped.set()

    # bound per-handle so unregister removes exactly this plane's hook
    def _drain_hook(self) -> None:
        self.frontend.drain()


def serve(
    model,
    params,
    port: Optional[int] = None,
    *,
    slots: Optional[int] = None,
    max_len: Optional[int] = None,
    max_new_tokens: Optional[int] = None,
    deadline_ms: Optional[float] = None,
    max_admit_per_step: Optional[int] = None,
    eos_id: Optional[int] = None,
    policy: str = "continuous",
    addr: str = "0.0.0.0",
    advertise_addr: str = "127.0.0.1",
    rank: Optional[int] = None,
    announce_client=None,
    mesh=None,
    handle_sigterm: bool = True,
    role: Optional[str] = None,
    kv_wire: Optional[str] = None,
    transfer_port: Optional[int] = None,
    **engine_kwargs,
) -> ServeHandle:
    """Start the inference plane on this worker: engine + continuous
    batcher + HTTP frontend, drain-wired into the preemption path.

    The Horovod-paper API shape (arXiv 1802.05799: bolt distributed
    execution onto an existing model with minimal surface): ``model`` is
    the same flax module you trained, ``params`` the tree you
    checkpointed — ``hvd.serve(model, params, port=8500)`` and the
    worker serves. Env defaults: ``HOROVOD_SERVE_PORT``,
    ``_SERVE_KV_SLOTS``, ``_SERVE_MAX_BATCH``, ``_SERVE_MAX_TOKENS``,
    ``_SERVE_DEADLINE_MS`` (docs/env_vars.md).

    ``handle_sigterm=True`` (default) installs a
    ``preemption.GracefulShutdown(None)`` so a bare serve worker drains
    on SIGTERM and exits 143; pass False when composing with your own
    ``GracefulShutdown`` — the drain hook this function registers via
    ``preemption.register_drain`` makes YOUR shutdown drain the serving
    plane first, before telemetry/checkpoint.
    """
    from ..common import basics
    from .. import preemption
    from .engine import InferenceEngine

    cfg = basics.live_config()
    # Label this worker's spans with its serving role so the trace
    # assembler gets one row per (host, role) without guessing.
    _tracing.set_role(role or cfg.serve_role)
    if port is None:
        port = cfg.serve_port
    if slots is None:
        slots = cfg.serve_kv_slots
    if max_new_tokens is None:
        max_new_tokens = cfg.serve_max_tokens
    if deadline_ms is None:
        deadline_ms = cfg.serve_deadline_ms
    if max_admit_per_step is None:
        max_admit_per_step = cfg.serve_max_batch
    if role is None:
        role = cfg.serve_role
    if kv_wire is None:
        kv_wire = cfg.serve_kv_wire
    else:
        # Validate here even though only prefill workers build the
        # TransferCoordinator — a typo'd wire on a decode/unified worker
        # must fail at serve() time, not when the fleet is re-roled.
        from .kv_transfer import WIRE_FORMATS

        if kv_wire not in WIRE_FORMATS:
            raise ValueError(
                f"kv wire must be one of {WIRE_FORMATS}, got {kv_wire!r}"
            )
    if transfer_port is None:
        transfer_port = cfg.serve_transfer_port
    if max_len is None:
        model_cfg = getattr(model, "cfg", None)
        max_len = getattr(model_cfg, "max_len", None)
        if max_len is None:
            raise TypeError(
                "max_len= is required when the model carries no "
                ".cfg.max_len to derive the KV capacity from"
            )
    engine = InferenceEngine(
        model, params, slots=slots, max_len=max_len, mesh=mesh,
        role=role, **engine_kwargs,
    )
    batcher = ContinuousBatcher(
        engine,
        max_admit_per_step=max_admit_per_step,
        default_max_new_tokens=max_new_tokens,
        default_deadline_ms=deadline_ms,
        eos_id=eos_id,
        policy=policy,
        role=role,
    )
    transfer_server = None
    if role == "decode" or (role == "unified" and engine.paged):
        # decode workers take prefill handoffs; paged unified workers
        # run the server too so a draining peer can live-migrate its
        # in-flight sequences here (the `migrate` frame) — a
        # single-role fleet is still evacuable
        from .kv_transfer import KVTransferServer

        transfer_server = KVTransferServer(
            batcher, port=transfer_port, addr=addr
        )
        transfer_server.start()
    frontend = ServeFrontend(
        batcher, port=port, addr=addr,
        advertise_addr=advertise_addr, rank=rank,
        announce_client=announce_client,
        transfer_server=transfer_server,
    )
    if role == "prefill":
        from .kv_transfer import TransferCoordinator

        # the coordinator reads the same serve-scope announcements the
        # frontend publishes into — resolved lazily so a fleet-less
        # prefill worker (no rendezvous) just decodes locally
        batcher.transfer = TransferCoordinator(
            engine, wire=kv_wire,
            client_factory=frontend._resolve_announce_client,
        )
    shutdown_ctx = None
    if handle_sigterm:
        shutdown_ctx = preemption.GracefulShutdown(None)
        shutdown_ctx.__enter__()
    handle = ServeHandle(
        engine, batcher, frontend, shutdown_ctx,
        transfer_server=transfer_server,
    )
    preemption.register_drain(handle._drain_hook)
    batcher.start()
    frontend.start()
    return handle
