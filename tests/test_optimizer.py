"""DistributedOptimizer / tape / broadcast-state tests.

Reference model: test/parallel/test_torch.py's DistributedOptimizer
step-equivalence-vs-manual-allreduce and broadcast_optimizer_state
round-trip tests [V] (SURVEY.md §4.1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod


def rank_major(fn, dtype=np.float32):
    return np.stack([np.asarray(fn(r), dtype=dtype) for r in range(8)])


def spmd(hvd, fn, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(
            fn,
            mesh=hvd.mesh(),
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
    )


def test_distributed_optimizer_equals_manual_allreduce(hvd):
    """One step of DistributedOptimizer(sgd) == sgd step on pmean'd grads."""
    opt = hvd_mod.DistributedOptimizer(optax.sgd(0.1))
    params = {"w": jnp.ones(4), "b": jnp.zeros(2)}
    grads_rm = {
        "w": rank_major(lambda r: np.full(4, float(r))),
        "b": rank_major(lambda r: np.full(2, 2.0 * r)),
    }

    def step(g):
        state = opt.init(params)
        updates, _ = opt.update(g, state, params)
        return optax.apply_updates(params, updates)

    out = spmd(
        hvd,
        lambda g: jax.tree_util.tree_map(
            lambda x: x[None], step(jax.tree_util.tree_map(lambda x: x[0], g))
        ),
        (P(hvd_mod.WORLD_AXIS),),
        jax.tree_util.tree_map(lambda _: P(hvd_mod.WORLD_AXIS), params),
    )(grads_rm)
    # mean grad w = 3.5, b = 7.0 → params - 0.1*mean
    np.testing.assert_allclose(np.asarray(out["w"][0]), np.full(4, 1 - 0.35))
    np.testing.assert_allclose(
        np.asarray(out["b"][3]), np.full(2, -0.7), rtol=1e-6
    )
    # all ranks identical
    np.testing.assert_allclose(np.asarray(out["w"][5]), np.asarray(out["w"][0]))


@pytest.mark.parametrize("avg_agg", [False, True])
def test_backward_passes_per_step_accumulates(hvd, avg_agg):
    """k=2: first micro-step is a no-op; the second applies the SUM of the
    micro-grads (reference default) or the mean with
    average_aggregated_gradients=True."""
    opt = hvd_mod.DistributedOptimizer(
        optax.sgd(1.0),
        backward_passes_per_step=2,
        average_aggregated_gradients=avg_agg,
    )
    params = jnp.zeros(3)
    g1 = rank_major(lambda r: np.full(3, 1.0))
    g2 = rank_major(lambda r: np.full(3, 3.0))

    def run(both):
        ga, gb = both

        def body(g_pair):
            a, b = g_pair
            state = opt.init(params)
            u1, state = opt.update(a, state, params)
            p1 = optax.apply_updates(params, u1)
            u2, state = opt.update(b, state, p1)
            p2 = optax.apply_updates(p1, u2)
            return p1[None], p2[None]

        return body((ga[0], gb[0]))

    p1, p2 = spmd(
        hvd,
        run,
        ((P(hvd_mod.WORLD_AXIS), P(hvd_mod.WORLD_AXIS)),),
        (P(hvd_mod.WORLD_AXIS), P(hvd_mod.WORLD_AXIS)),
    )((g1, g2))
    np.testing.assert_allclose(np.asarray(p1[0]), np.zeros(3))  # no step yet
    # boundary: sum of micro-grads = 1+3 = 4 (mean = 2 when averaging)
    expected = -2.0 if avg_agg else -4.0
    np.testing.assert_allclose(np.asarray(p2[0]), np.full(3, expected))


def test_gradient_predivide_factor(hvd):
    """predivide f: sum(g/(n f)) * f == average — numerically equal path."""
    opt = hvd_mod.DistributedOptimizer(
        optax.sgd(1.0), gradient_predivide_factor=2.0
    )
    params = jnp.zeros(2)
    g = rank_major(lambda r: np.full(2, float(r)))

    def step(gr):
        state = opt.init(params)
        updates, _ = opt.update(gr[0], state, params)
        return optax.apply_updates(params, updates)[None]

    out = spmd(hvd, step, (P(hvd_mod.WORLD_AXIS),), P(hvd_mod.WORLD_AXIS))(g)
    np.testing.assert_allclose(np.asarray(out[0]), np.full(2, -3.5), rtol=1e-6)


def test_predivide_requires_average():
    with pytest.raises(ValueError):
        hvd_mod.DistributedOptimizer(
            optax.sgd(0.1), gradient_predivide_factor=2.0, op=hvd_mod.Sum
        )


def test_distributed_optimizer_adasum(hvd, rng):
    """op=Adasum runs and produces identical params on every rank."""
    opt = hvd_mod.DistributedOptimizer(optax.sgd(0.5), op=hvd_mod.Adasum)
    params = jnp.ones(4)
    g = rank_major(lambda r: rng.normal(size=4))

    def step(gr):
        state = opt.init(params)
        updates, _ = opt.update(gr[0], state, params)
        return optax.apply_updates(params, updates)[None]

    out = spmd(hvd, step, (P(hvd_mod.WORLD_AXIS),), P(hvd_mod.WORLD_AXIS))(g)
    for r in range(1, 8):
        np.testing.assert_allclose(
            np.asarray(out[r]), np.asarray(out[0]), rtol=1e-5, atol=1e-6
        )


def test_compression_fp16_roundtrip_in_optimizer(hvd):
    opt = hvd_mod.DistributedOptimizer(
        optax.sgd(1.0), compression=hvd_mod.Compression.fp16
    )
    params = jnp.zeros(3)
    g = rank_major(lambda r: np.full(3, float(r)))

    def step(gr):
        state = opt.init(params)
        updates, _ = opt.update(gr[0], state, params)
        p = optax.apply_updates(params, updates)
        return p[None]

    out = spmd(hvd, step, (P(hvd_mod.WORLD_AXIS),), P(hvd_mod.WORLD_AXIS))(g)
    assert out.dtype == jnp.float32  # decompressed back
    np.testing.assert_allclose(np.asarray(out[0]), np.full(3, -3.5), rtol=1e-3)


def test_value_and_grad_tape(hvd):
    """hvd.value_and_grad == DistributedGradientTape: grads averaged."""

    def loss(w, x):
        return jnp.sum(w * x)

    vg = hvd_mod.value_and_grad(loss)
    w = jnp.ones(3)
    x = rank_major(lambda r: np.full(3, float(r)))

    def step(xr):
        val, g = vg(w, xr[0])
        return val[None], g[None]

    vals, grads = spmd(
        hvd,
        step,
        (P(hvd_mod.WORLD_AXIS),),
        (P(hvd_mod.WORLD_AXIS), P(hvd_mod.WORLD_AXIS)),
    )(x)
    np.testing.assert_allclose(np.asarray(grads[0]), np.full(3, 3.5))
    np.testing.assert_allclose(np.asarray(grads[7]), np.full(3, 3.5))


def test_broadcast_parameters_replicates(hvd):
    params = {"w": np.arange(6.0, dtype=np.float32).reshape(2, 3)}
    out = hvd_mod.broadcast_parameters(params, root_rank=0)
    assert out["w"].sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(out["w"]), params["w"])


def test_broadcast_optimizer_state_roundtrip(hvd):
    opt = optax.adam(1e-3)
    params = {"w": jnp.ones((3, 3))}
    state = opt.init(params)
    out = hvd_mod.broadcast_optimizer_state(state)
    leaves_in = jax.tree_util.tree_leaves(state)
    leaves_out = jax.tree_util.tree_leaves(out)
    assert len(leaves_in) == len(leaves_out)
    for a, b in zip(leaves_in, leaves_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_broadcast_object_single_controller(hvd):
    obj = {"step": 7, "note": "hello"}
    assert hvd_mod.broadcast_object(obj) is obj
