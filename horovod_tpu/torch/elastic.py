"""Elastic state for the torch shim: ``TorchState``.

Parity target: ``horovod.torch.elastic.state.TorchState`` [V]
(SURVEY.md §2.5 "Elastic worker API") — wrap a torch module +
optimizer (+ scalars like epoch/batch) so elastic training can
``commit()`` (host snapshot), ``restore()`` (roll back to the last
commit after a failure), and ``sync()`` (broadcast from the new rank 0
after a membership change). Reuses the shim's
``broadcast_parameters`` / ``broadcast_optimizer_state`` /
``broadcast_object`` for the sync leg and the base ``ObjectState``
machinery for scalar attributes; use with ``hvd.elastic.run`` exactly
like ``JaxState``.
"""

from __future__ import annotations

import copy
from typing import Any

from ..elastic.state import ObjectState, State  # noqa: F401 — re-export
from ..elastic.worker import run  # noqa: F401 — hvd.torch.elastic.run


class _Ineligible(Exception):
    """A tensor the native packed snapshot cannot stage (non-CPU
    device, numpy-unsupported dtype like bfloat16)."""


class _PackedLeaf:
    """Marker in a packed-snapshot skeleton: tensor #index of the
    block, restored to torch dtype ``dtype``."""

    __slots__ = ("index", "dtype")

    def __init__(self, index: int, dtype) -> None:
        self.index = index
        self.dtype = dtype


class _PackedStateDict:
    """A state dict snapshotted into ONE contiguous native block
    (``loader.PackedSnapshot``) — the adapter_v2-style native half of
    the commit: tensor bytes reach C through the buffer protocol, the
    staging memcpy runs without the GIL, and restore materializes
    zero-copy views (``load_state_dict`` does the one unavoidable copy
    back into the live storages)."""

    def __init__(self, skeleton: Any, snap) -> None:
        self._skeleton = skeleton
        self._snapshot = snap

    @property
    def nbytes(self) -> int:
        return self._snapshot.nbytes

    def materialize(self, copy_tensors: bool = False) -> Any:
        """State dict over zero-copy views into the block; with
        ``copy_tensors`` every tensor is an owned clone (required when
        the consumer may keep references that are later mutated in
        place — see TorchState.restore's optimizer leg)."""
        import torch

        def build(v):
            if isinstance(v, _PackedLeaf):
                t = torch.from_numpy(
                    self._snapshot.view(v.index)
                ).view(v.dtype)
                return t.clone() if copy_tensors else t
            if isinstance(v, dict):
                return {k: build(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return type(v)(build(x) for x in v)
            return copy.deepcopy(v)

        return build(self._skeleton)


class TorchState(ObjectState):
    """Commit/restore/sync over a torch model + optimizer
    (ref: horovod/torch/elastic/state.py TorchState [V]). Commits
    prefer the native packed snapshot (one block, GIL-released staging
    — csrc/cext.cc); per-tensor clones remain the fallback when the
    native layer is off or a tensor is ineligible."""

    def __init__(self, model=None, optimizer=None, **kwargs: Any) -> None:
        self.model = model
        self.optimizer = optimizer
        self._saved_model_state: Any = None
        self._saved_optimizer_state: Any = None
        super().__init__(**kwargs)
        self.save()

    @staticmethod
    def _clone_state_dict(sd):
        import torch

        def clone(v):
            if isinstance(v, torch.Tensor):
                return v.detach().clone()
            if isinstance(v, dict):
                return {k: clone(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return type(v)(clone(x) for x in v)
            return copy.deepcopy(v)

        return clone(sd)

    @staticmethod
    def _pack_state_dict(sd):
        """Native packed snapshot of ``sd``; None when any tensor is
        ineligible or the native layer is unavailable."""
        import torch

        from .._native import loader as _native_loader

        leaves: list = []

        def strip(v):
            if isinstance(v, torch.Tensor):
                t = v.detach()
                if t.device.type != "cpu":
                    raise _Ineligible
                try:
                    leaves.append(t.contiguous().numpy())
                except (RuntimeError, TypeError):
                    raise _Ineligible  # bfloat16 & friends
                return _PackedLeaf(len(leaves) - 1, v.dtype)
            if isinstance(v, dict):
                return {k: strip(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return type(v)(strip(x) for x in v)
            return copy.deepcopy(v)

        try:
            skeleton = strip(sd)
        except _Ineligible:
            return None
        snap = _native_loader.snapshot_arrays(leaves)
        if snap is None:
            return None
        return _PackedStateDict(skeleton, snap)

    def _snapshot(self, sd):
        packed = self._pack_state_dict(sd)
        if packed is not None:
            return packed
        return self._clone_state_dict(sd)

    def save(self) -> None:
        if self.model is not None:
            self._saved_model_state = self._snapshot(
                self.model.state_dict()
            )
        if self.optimizer is not None:
            self._saved_optimizer_state = self._snapshot(
                self.optimizer.state_dict()
            )
        super().save()

    def restore(self) -> None:
        # Module.load_state_dict copies into the live param storages
        # (copy_), so the model leg can consume zero-copy views. But
        # Optimizer.load_state_dict SHALLOW-copies state tensors
        # (torch>=2.x: ``.to()`` on a matching device/dtype returns the
        # same tensor) — handing it views/clones it keeps would let the
        # next opt.step() mutate the committed snapshot in place, so the
        # optimizer leg always gets owned copies.
        if self.model is not None and self._saved_model_state is not None:
            saved = self._saved_model_state
            if isinstance(saved, _PackedStateDict):
                saved = saved.materialize()
            self.model.load_state_dict(saved)
        if (
            self.optimizer is not None
            and self._saved_optimizer_state is not None
        ):
            saved = self._saved_optimizer_state
            if isinstance(saved, _PackedStateDict):
                saved = saved.materialize(copy_tensors=True)
            else:
                saved = self._clone_state_dict(saved)
            self.optimizer.load_state_dict(saved)
        super().restore()

    def sync(self) -> None:
        from . import broadcast_optimizer_state, broadcast_parameters

        if self.model is not None:
            broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            broadcast_optimizer_state(self.optimizer, root_rank=0)
        super().sync()  # scalar attributes via broadcast_object
        self.save()


class ElasticSampler:
    """Distributed sampler that supports mid-epoch membership changes
    (ref: horovod/torch/elastic/sampler.py ElasticSampler [V]).

    Contract (same as the reference): iterate your rank's shard;
    ``record_batch`` after each step marks those samples processed; on a
    host change call ``sampler.sync()`` — it UNIONS every rank's
    processed set (allgather, the reference's sampler state handler
    semantics) and re-shards the remainder over the new world, so no
    sample is dropped or repeated within the epoch. NOTE:
    ``TorchState.sync`` alone is NOT enough — its broadcast would
    overwrite survivors' progress with rank 0's; call the sampler's own
    ``sync()`` after it. ``state_dict``/``load_state_dict`` ride an
    elastic State object so commits capture progress; ``set_epoch``
    reshuffles and clears the processed set.

    Duck-typed to torch's Sampler protocol (``__iter__``/``__len__``) —
    usable as ``DataLoader(..., sampler=ElasticSampler(ds))`` without
    importing torch here.
    """

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0,
                 num_replicas=None, rank=None):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set = set()
        # explicit overrides pin the identity (tests / manual sharding);
        # None = re-read from the runtime on every reset (the elastic
        # membership-change behavior)
        self._fixed_replicas = num_replicas
        self._fixed_rank = rank
        self.reset()

    # -- lifecycle -----------------------------------------------------
    def reset(self) -> None:
        """Re-shard the unprocessed remainder over the CURRENT world
        (rank/size re-read — this is the membership-change hook)."""
        from ..common import basics
        import numpy as np

        self.num_replicas = (
            self._fixed_replicas
            if self._fixed_replicas is not None
            else basics.size()
        )
        self.rank = (
            self._fixed_rank if self._fixed_rank is not None else basics.rank()
        )
        n = len(self.dataset)
        remaining = np.array(
            sorted(set(range(n)) - self.processed_indices), dtype=np.int64
        )
        if self.shuffle and len(remaining):
            rng = np.random.default_rng((self.seed, self.epoch))
            remaining = remaining[rng.permutation(len(remaining))]
        # equal shards via wrap-around padding (SPMD step-count parity,
        # same discipline as data.ShardedIndexSampler)
        per = -(-len(remaining) // self.num_replicas) if len(remaining) else 0
        total = per * self.num_replicas
        if total > len(remaining) and len(remaining):
            remaining = np.resize(remaining, total)
        self.indices = remaining[self.rank :: self.num_replicas].tolist()
        self.num_samples = len(self.indices)

    def sync(self) -> None:
        """Union every rank's processed set, then re-shard the
        remainder over the CURRENT world — the membership-change hook
        (ref: the sampler state-sync handler unions processed indices
        across workers [V]; a plain broadcast would drop the progress
        of every rank but the root)."""
        from . import allgather_object

        for other in allgather_object(sorted(self.processed_indices)):
            self.processed_indices.update(int(i) for i in other)
        self.reset()

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark the samples of batch ``batch_idx`` (into this rank's
        current index list) as processed."""
        sl = self.indices[
            batch_idx * batch_size : (batch_idx + 1) * batch_size
        ]
        self.processed_indices.update(int(i) for i in sl)

    # -- elastic State integration ------------------------------------
    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "processed_indices": sorted(self.processed_indices),
        }

    def load_state_dict(self, sd: dict) -> None:
        self.epoch = int(sd["epoch"])
        self.processed_indices = set(sd["processed_indices"])
        self.reset()

    # -- sampler protocol ---------------------------------------------
    def __iter__(self):
        return iter(self.indices)

    def __len__(self) -> int:
        return self.num_samples
