"""Typed configuration backed by the ``HOROVOD_*`` environment-variable contract.

TPU-native re-design of the reference's two-tier config system
(ref: horovod/common/utils/env_parser.cc + horovod/runner/launch.py [V] —
see SURVEY.md §5.6; the reference mount was empty, citations are structural).

The reference parses ~30 HOROVOD_* env vars scattered across C++ and Python.
Here the full behavioral surface lives in one frozen dataclass, parsed once at
``hvd.init()`` time, while keeping the env-var names so existing launch scripts
keep working.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

# Default fusion threshold matches the reference: 64 MB
# (ref: horovod/common/fusion_buffer_manager.cc [V]).
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024
# Background-cycle batching window, milliseconds
# (ref: HOROVOD_CYCLE_TIME in horovod/common/operations.cc [V]).
DEFAULT_CYCLE_TIME_MS = 1.0
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECONDS = 60.0
DEFAULT_STALL_SHUTDOWN_SECONDS = 0.0  # 0 = never shut down
DEFAULT_ELASTIC_DISCOVERY_INTERVAL = 1.0
# Control-plane retry/backoff defaults — the ONE home for these
# numbers: the Config fields below and RetryPolicy.from_env
# (common/retry.py) both read them, so the typed mirror and the
# pre-init env path cannot drift apart.
DEFAULT_RETRY_ATTEMPTS = 3
DEFAULT_RETRY_BACKOFF_MS = 100.0
DEFAULT_RETRY_BACKOFF_MAX_MS = 2000.0
DEFAULT_RETRY_DEADLINE_S = 60.0
DEFAULT_RETRY_ATTEMPT_TIMEOUT_S = 30.0
DEFAULT_RETRY_CIRCUIT_THRESHOLD = 3
DEFAULT_RETRY_CIRCUIT_COOLDOWN_S = 30.0
DEFAULT_STRAGGLER_QUARANTINE_POLLS = 3
# Training-state integrity plane (common/guard.py, audit.py): the
# non-finite skip-step guard escalates to HorovodInternalError after
# this many CONSECUTIVE skipped steps (the elastic restore contract),
# and the parameter audit runs every N optimizer steps (0 = off).
DEFAULT_GUARD_MAX_SKIPS = 3
DEFAULT_AUDIT_STEPS = 0
# Serving plane (horovod_tpu/serving/): decode-slot count (concurrent
# sequences), admissions per decode step, default per-request token
# budget/deadline, and the frontend port (0 = ephemeral).
DEFAULT_SERVE_PORT = 0
DEFAULT_SERVE_KV_SLOTS = 8
DEFAULT_SERVE_MAX_BATCH = 4
DEFAULT_SERVE_MAX_TOKENS = 64
DEFAULT_SERVE_DEADLINE_MS = 0.0  # 0 = no deadline
# Expert wire (parallel/moe.py, PR 12): dispatch/return wire format,
# ICI-leg format under a two-level split, block-scale granularity of
# the int8 alltoall, and the default capacity factor (the static
# per-destination buffer size; CapacityTuner can drive it per step
# harness instead of leaving it hand-set).
DEFAULT_MOE_WIRE = "fp32"
DEFAULT_MOE_INTRA_WIRE = "fp32"
DEFAULT_MOE_WIRE_BLOCK = 512
DEFAULT_MOE_CAPACITY_FACTOR = 1.25
# Serving memory plane (serving/paged_kv.py): tokens per KV page, pool
# size in pages (0 = auto: full backing, slots × max_len ÷ page_tokens
# — undersubscribe explicitly to make HBM scale with tokens in
# flight), prefix-cache toggle, and the admission reserve watermark
# (-1 = auto: 0 at full backing, one page per slot otherwise).
DEFAULT_SERVE_PAGE_TOKENS = 16
DEFAULT_SERVE_PAGES = 0
DEFAULT_SERVE_PREFIX_CACHE = True
DEFAULT_SERVE_PAGE_WATERMARK = -1
# Disaggregated prefill/decode fleet (serving/kv_transfer.py): the
# worker's role in the fleet (unified = classic single-engine worker,
# the default — single-worker deployments are untouched), the KV-page
# wire format for prefill→decode transfers (int8 = block-scaled
# quantized pages, the headline; fp32 = lossless pool-dtype
# passthrough, the bit-parity reference; bf16 = the middle ground),
# and the decode worker's transfer-ingest port (0 = ephemeral,
# announced through the capacity blobs either way).
DEFAULT_SERVE_ROLE = "unified"
DEFAULT_SERVE_KV_WIRE = "int8"
DEFAULT_SERVE_TRANSFER_PORT = 0
# Paged-attention kernel read (ops/paged_attention.py): auto = fuse the
# pool read on real TPU backends and keep the gather read (the numerics
# oracle) elsewhere; on = force the kernel (interpret-mode on CPU —
# what the parity tests and the A/B bench run); off = always gather.
DEFAULT_SERVE_PAGED_ATTN = "auto"
# Crash-safe serving (serving/frontend.py Router + drain path): hedge
# delay in ms before the Router fires a first-writer-wins backup
# request (0 = off), the SIGTERM drain deadline in seconds past which
# in-flight sequences are live-migrated to a peer instead of run to
# completion (0 = run to completion, the classic drain), and the TTL of
# the completed-result dedupe cache that makes client retries by
# request_id idempotent.
DEFAULT_SERVE_HEDGE_MS = 0.0
DEFAULT_SERVE_DRAIN_DEADLINE_S = 0.0
DEFAULT_SERVE_DEDUPE_TTL_S = 120.0


def _env_bool(name: str, default: bool = False) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return int(val)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {val!r}")


def _env_choice(name: str, default: str, choices) -> str:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    val = val.strip().lower()
    if val not in choices:
        raise ValueError(
            f"{name} must be one of {'/'.join(choices)}, got {val!r}"
        )
    return val


def _env_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return float(val)
    except ValueError:
        raise ValueError(f"{name} must be a float, got {val!r}")


@dataclasses.dataclass(frozen=True)
class Config:
    """Snapshot of every knob the framework honors.

    Field groups mirror the reference's env surface (SURVEY.md §5.6) plus
    TPU-specific additions prefixed ``mesh_*``.
    """

    # --- fusion / eager dispatch ---
    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    batch_d2d_memcopies: bool = True
    # in-JIT pack/unpack: one donated executable per fused batch
    # (ops/fusion.py; off = pre-rework host-side pack, the A/B baseline)
    fusion_injit: bool = True
    # power-of-two byte bucketing of the fused buffer (executor-cache
    # stability under batch-composition churn)
    fusion_buckets: bool = True
    # donate fused-batch inputs so the fusion buffer aliases them
    # (None = auto: on where the backend supports aliasing — TPU/GPU)
    fusion_donate: Optional[bool] = None
    # promote a batch composition to its own exact executable after
    # this many sightings (before that, churn rides the bucket tier)
    fusion_promote_after: int = 2
    # wire format of the fused buffer's collective: fp32 (payload
    # width), bf16 (half-width cast wire), int8 (block-scaled
    # quantized wire, EQuARX-style), or auto (per-bucket online choice
    # by goodput — common/autotune.py WireTuner)
    fusion_wire: str = "fp32"
    # elements per block scale on the int8 fused wire
    fusion_wire_block: int = 512
    # hierarchical wire: bf16 on the intra-host (ICI) stage, int8 on
    # the cross-host (DCN) stage (needs HOROVOD_HIERARCHICAL_ALLREDUCE
    # topology stages to be non-degenerate)
    fusion_wire_hier: bool = False
    # auto mode never tries int8 below this fused-buffer byte size
    # (the per-dispatch quant tax dominates tiny buffers)
    fusion_wire_min_bytes: int = 64 * 1024

    # --- reduction behavior ---
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False

    # --- two-level topology (common/topology.py hierarchy_stages) ---
    # HOROVOD_HIERARCHICAL: route the fused eager batch, the overlap
    # buckets and the ZeRO-2/3 exchange legs through the two-level
    # (intra-slice ICI / inter-slice DCN) recipe. "auto" (default)
    # engages it exactly when a real inter axis exists (multi-slice
    # detection, or an explicit HOROVOD_INTRA_SIZE); "on" forces it
    # wherever a non-degenerate split is resolvable; "off" keeps every
    # wire flat. The legacy HOROVOD_HIERARCHICAL_ALLREDUCE=1 is read
    # as "on".
    hierarchical: str = "auto"
    # explicit chips-per-slice override for the slice-boundary
    # detection (None = detect from JAX device slice_index / process
    # structure). Must divide the world; a non-dividing value degrades
    # to gcd(intra, world) so an elastic reshard (8 -> 6) keeps a
    # valid two-level split instead of crashing.
    intra_size: Optional[int] = None
    # axis NAME the two-level world mesh uses for the cross-slice
    # (DCN) dimension; the intra axis is always "intra"
    inter_axis: str = "inter"
    # straggler-aware scheduling (elastic/driver.py): publish per-rank
    # micro-batch weights into the rendezvous KV, down-weighting ranks
    # whose step p50 STAYS flagged by the straggler ledger, instead of
    # only logging them. Workers read the weights via
    # hvd.elastic.rebalance_weight().
    rebalance: bool = False
    # local-SGD mode (horovod_tpu/local_sgd.py): slices train
    # independently on their ICI-only wire for K micro-steps, then
    # reconcile parameter deltas across the inter (DCN) axis with
    # hierarchical Adasum on the int8 inter wire. 1 (default) = the
    # existing every-step sync path; the mode engages at K > 1.
    # Explicit local_sgd_steps= per optimizer always wins.
    local_sgd_steps: int = 1

    # --- ZeRO sharding stage (sharded_optimizer.py) ---
    # default zero_stage for ShardedDistributedOptimizer(zero_stage=None):
    # 1 = optimizer-state sharding only, 2 = + gradient shards (bucketed
    # reduce-scatter straight into shard storage), 3 = + parameter shards
    # (forward-interleaved per-bucket all-gather). Explicit zero_stage=
    # per optimizer always wins.
    zero_stage: int = 1
    # wire format of the SHARDED exchange legs (reduce-scatter /
    # all-gather) when the optimizer passes wire=None. Deliberately a
    # SEPARATE knob from fusion_wire: HOROVOD_FUSION_WIRE governs the
    # eager fused allreduce wire, and inheriting it here would silently
    # change sharded-optimizer numerics (and its state layout) for
    # deployments that set it long before ZeRO-2/3 existed.
    zero_wire: str = "fp32"

    # --- backward-interleaved gradient exchange (ops/overlap.py) ---
    # master switch: when on, DistributedOptimizer / value_and_grad /
    # ShardedDistributedOptimizer default to the bucketed exchange
    # (N independent per-bucket collectives XLA overlaps with backprop)
    # unless the caller passes overlap_buckets= explicitly
    overlap: bool = False
    # bucket count of the default schedule (explicit overlap_buckets=
    # always wins). For a measured choice, the step harness can sweep
    # candidates through common/autotune.py's OverlapTuner — a bucket
    # count is a compile-time property of the step, so tuning happens
    # across recompiles at the loop level (bench_overlap.py shows the
    # pattern), never inside one compiled step
    overlap_buckets: int = 4
    # buckets below this byte size merge forward: per-collective launch
    # overhead outweighs any overlap win under the floor
    overlap_min_bytes: int = 1 << 20

    # --- expert wire (parallel/moe.py) ---
    # dispatch/return wire of the MoE alltoall: fp32 (payload width),
    # bf16, int8 (block-scaled quantized, ops/traced.py
    # quantized_alltoall), or auto (trace-time choice through the
    # shared WireTuner's (alltoall, hop) keys). Under a two-level
    # split (HOROVOD_HIERARCHICAL) this names the INTER (DCN) hop.
    moe_wire: str = DEFAULT_MOE_WIRE
    # ICI-leg format of the two-level expert dispatch (never int8 —
    # the quant tax cannot pay for itself inside a slice)
    moe_intra_wire: str = DEFAULT_MOE_INTRA_WIRE
    # elements per block scale on the int8 expert wire
    moe_wire_block: int = DEFAULT_MOE_WIRE_BLOCK
    # default capacity factor of the switch-MoE dispatch buffer
    # (explicit capacity_factor= per call wins)
    moe_capacity_factor: float = DEFAULT_MOE_CAPACITY_FACTOR

    # --- autotune ---
    autotune: bool = False
    autotune_log: Optional[str] = None
    # directory for persistent tuner state (common/autotune.py):
    # WireTuner / OverlapTuner / CapacityTuner observations serialize
    # here keyed by (tuner name, topology fingerprint) and warm-start
    # exploration across runs. None = in-memory only.
    tuner_cache: Optional[str] = None
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8

    # --- timeline ---
    timeline: Optional[str] = None
    timeline_mark_cycles: bool = False

    # --- telemetry (common/telemetry.py) ---
    # flight-recorder ring size: the last N closed StepStats records
    telemetry_steps: int = 256
    # JSON-lines path the ring is dumped to on exit/SIGTERM (None = off)
    flight_recorder: Optional[str] = None
    # per-worker /metrics + /telemetry scrape port (0 = no server)
    metrics_port: int = 0
    # straggler threshold: flag ranks whose heartbeat-reported step_ms
    # p50 exceeds this multiple of the gang median
    straggler_factor: float = 3.0

    # --- trace plane (common/tracing.py) ---
    # master switch for cross-host request/step spans; off by default so
    # the decode hot path carries zero tracing cost
    trace: bool = False
    # fraction of minted root contexts that are sampled (descendant
    # spans inherit the root's decision, so a trace is all-or-nothing)
    trace_sample: float = 1.0
    # per-worker span ring size; oldest spans are evicted first
    trace_spans: int = 2048

    # --- stall inspector ---
    stall_check_disable: bool = False
    stall_warning_seconds: float = DEFAULT_STALL_WARNING_SECONDS
    stall_shutdown_seconds: float = DEFAULT_STALL_SHUTDOWN_SECONDS

    # --- control-plane retry/backoff (common/retry.py) ---
    # Typed mirror of the HOROVOD_RETRY_* contract; the live consumer
    # is RetryPolicy.from_env, which shares these defaults and parsers
    # (policies are built before hvd.init(), so they cannot depend on
    # an initialized Config instance).
    # attempts per cross-host hop (rendezvous KV, signed RPC,
    # heartbeats, discovery); 1 = the old single-attempt behavior
    retry_attempts: int = DEFAULT_RETRY_ATTEMPTS
    # first backoff delay, doubled per retry with +/-25% jitter
    retry_backoff_ms: float = DEFAULT_RETRY_BACKOFF_MS
    retry_backoff_max_ms: float = DEFAULT_RETRY_BACKOFF_MAX_MS
    # overall deadline across one hop's attempts (0 = unbounded)
    retry_deadline_s: float = DEFAULT_RETRY_DEADLINE_S
    # per-attempt socket/urlopen timeout hint
    retry_attempt_timeout_s: float = DEFAULT_RETRY_ATTEMPT_TIMEOUT_S
    # consecutive exhausted rounds against one peer before its circuit
    # opens (fail-fast CircuitOpenError instead of a full backoff
    # ladder per touch); 0 disables the breaker
    retry_circuit_threshold: int = DEFAULT_RETRY_CIRCUIT_THRESHOLD
    retry_circuit_cooldown_s: float = DEFAULT_RETRY_CIRCUIT_COOLDOWN_S
    # deterministic fault-injection plan (testing/chaos.py syntax, or
    # @/path/to/file); None = chaos off
    fault_plan: Optional[str] = None
    # self-healing driver: quarantine a host after its rank is flagged
    # as a straggler for this many CONSECUTIVE fresh heartbeat
    # observations (proactive gang-restart excluding it); 0 disables
    straggler_quarantine_polls: int = DEFAULT_STRAGGLER_QUARANTINE_POLLS

    # --- training-state integrity (common/guard.py, audit.py) ---
    # non-finite sentinel: when on, DistributedOptimizer /
    # ShardedDistributedOptimizer fold a per-bucket finiteness
    # reduction into the compiled update and SKIP the step (zero
    # update, optimizer state and EF residuals untouched) when the
    # reduced gradients carry a NaN/Inf, instead of silently poisoning
    # every parameter. Explicit grad_guard= per optimizer always wins.
    guard: bool = False
    # consecutive skipped steps before the guard escalates to
    # HorovodInternalError (-> hvd.elastic.run restores the last
    # commit); 0 = skip forever, never escalate
    guard_max_skips: int = DEFAULT_GUARD_MAX_SKIPS
    # cross-rank parameter audit cadence: hvd.audit_maybe(tree, step)
    # digests every N steps (0 = off). Digest mismatches across ranks
    # surface through the rendezvous KV as a `divergence` restart.
    audit_steps: int = DEFAULT_AUDIT_STEPS
    # collective-schedule audit (analysis/sched_audit.py): every eager
    # fused dispatch folds (op kind, composition, wire, pset) into a
    # per-rank rolling fingerprint, published beside the parameter
    # digests on the HOROVOD_AUDIT_STEPS cadence; the driver flags a
    # rank whose compiled collective schedule diverges (reason
    # `sched_divergence`) before the mismatch becomes a hang. The fold
    # is a sub-microsecond hash per DISPATCH (not per step), so it is
    # on by default; 0 disables recording and publication.
    sched_audit: bool = True

    # --- serving plane (horovod_tpu/serving/) ---
    # hvd.serve frontend port (0 = ephemeral, announced over the
    # rendezvous KV either way)
    serve_port: int = DEFAULT_SERVE_PORT
    # decode slots = concurrent in-flight sequences per worker (the
    # fixed decode-batch shape; also the KV cache's batch dimension)
    serve_kv_slots: int = DEFAULT_SERVE_KV_SLOTS
    # prefill admissions between two decode steps — the TTFT-vs-TPOT
    # interleaving policy knob (serving/batcher.py)
    serve_max_batch: int = DEFAULT_SERVE_MAX_BATCH
    # default per-request new-token budget (per-request max_tokens wins)
    serve_max_tokens: int = DEFAULT_SERVE_MAX_TOKENS
    # default per-request deadline in ms (0 = none; per-request wins)
    serve_deadline_ms: float = DEFAULT_SERVE_DEADLINE_MS
    # paged KV memory plane: tokens per page, pool pages (0 = full
    # backing), prefix-cache toggle, admission watermark (-1 = auto)
    serve_page_tokens: int = DEFAULT_SERVE_PAGE_TOKENS
    serve_pages: int = DEFAULT_SERVE_PAGES
    serve_prefix_cache: bool = DEFAULT_SERVE_PREFIX_CACHE
    serve_page_watermark: int = DEFAULT_SERVE_PAGE_WATERMARK
    # disaggregated fleet: worker role, KV transfer wire format, and
    # the transfer-ingest port (serving/kv_transfer.py)
    serve_role: str = DEFAULT_SERVE_ROLE
    serve_kv_wire: str = DEFAULT_SERVE_KV_WIRE
    serve_transfer_port: int = DEFAULT_SERVE_TRANSFER_PORT
    # paged-attention kernel read: auto / on / off
    serve_paged_attn: str = DEFAULT_SERVE_PAGED_ATTN
    # crash-safe serving: Router hedge delay (ms, 0 = off), SIGTERM
    # drain deadline before live migration (s, 0 = run to completion),
    # completed-result dedupe cache TTL (s)
    serve_hedge_ms: float = DEFAULT_SERVE_HEDGE_MS
    serve_drain_deadline_s: float = DEFAULT_SERVE_DRAIN_DEADLINE_S
    serve_dedupe_ttl_s: float = DEFAULT_SERVE_DEDUPE_TTL_S

    # --- logging ---
    log_level: str = "warning"
    log_timestamp: bool = True

    # --- rank / rendezvous contract (set by the runner for each worker) ---
    rank: Optional[int] = None
    size: Optional[int] = None
    local_rank: Optional[int] = None
    local_size: Optional[int] = None
    cross_rank: Optional[int] = None
    cross_size: Optional[int] = None
    controller: str = "tpu"
    cpu_operations: str = "xla"
    rendezvous_addr: Optional[str] = None
    rendezvous_port: Optional[int] = None
    gloo_timeout_seconds: float = 30.0
    # jax.distributed coordination service (set by the runner; replaces
    # the reference's MPI_Init / Gloo rendezvous bootstrap — SURVEY §5.8)
    coordinator_addr: Optional[str] = None
    coordinator_port: Optional[int] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    secret_key_hex: Optional[str] = None

    # --- elastic ---
    elastic_discovery_interval: float = DEFAULT_ELASTIC_DISCOVERY_INTERVAL
    # persistent executable cache root (common/exe_cache.py): serialized
    # AOT executables keyed by (topology fp, HLO fp, wire, donation);
    # None = disk tier off everywhere
    exe_cache: Optional[str] = None
    # warm-standby hosts the elastic driver holds OUT of the gang,
    # pre-initialized (rendezvous-registered, executables deserialized,
    # params staged) so restarts/scale-ups swap one in instead of
    # cold-starting; 0 = off
    warm_standby: int = 0

    # --- TPU mesh ---
    mesh_shape: Optional[str] = None  # e.g. "dp=8" or "dp=4,tp=2"
    num_streams: int = 1

    @staticmethod
    def from_env() -> "Config":
        env = os.environ
        rendezvous_port = env.get("HOROVOD_GLOO_RENDEZVOUS_PORT")
        return Config(
            fusion_threshold_bytes=_env_int(
                "HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD
            ),
            cycle_time_ms=_env_float("HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_TIME_MS),
            cache_capacity=_env_int("HOROVOD_CACHE_CAPACITY", DEFAULT_CACHE_CAPACITY),
            batch_d2d_memcopies=_env_bool("HOROVOD_BATCH_D2D_MEMCOPIES", True),
            fusion_injit=_env_bool("HOROVOD_FUSION_INJIT", True),
            fusion_buckets=_env_bool("HOROVOD_FUSION_BUCKETS", True),
            fusion_donate=(
                None
                if env.get("HOROVOD_FUSION_DONATE", "auto").strip().lower()
                in ("auto", "")
                else _env_bool("HOROVOD_FUSION_DONATE")
            ),
            fusion_promote_after=_env_int("HOROVOD_FUSION_PROMOTE_AFTER", 2),
            fusion_wire=_env_choice(
                "HOROVOD_FUSION_WIRE",
                "fp32",
                ("fp32", "bf16", "int8", "auto"),
            ),
            fusion_wire_block=_env_int("HOROVOD_FUSION_WIRE_BLOCK", 512),
            fusion_wire_hier=_env_bool("HOROVOD_FUSION_WIRE_HIER"),
            fusion_wire_min_bytes=_env_int(
                "HOROVOD_FUSION_WIRE_MIN_BYTES", 64 * 1024
            ),
            hierarchical_allreduce=_env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE"),
            hierarchical_allgather=_env_bool("HOROVOD_HIERARCHICAL_ALLGATHER"),
            hierarchical=_env_choice(
                "HOROVOD_HIERARCHICAL", "auto", ("auto", "on", "off")
            ),
            intra_size=(
                _env_int("HOROVOD_INTRA_SIZE", 0)
                if env.get("HOROVOD_INTRA_SIZE", "").strip()
                else None
            ),
            inter_axis=env.get("HOROVOD_INTER_AXIS", "inter").strip()
            or "inter",
            rebalance=_env_bool("HOROVOD_REBALANCE"),
            local_sgd_steps=_env_int("HOROVOD_LOCAL_SGD_STEPS", 1),
            zero_stage=int(
                _env_choice("HOROVOD_ZERO_STAGE", "1", ("1", "2", "3"))
            ),
            zero_wire=_env_choice(
                "HOROVOD_ZERO_WIRE",
                "fp32",
                ("fp32", "bf16", "int8", "auto"),
            ),
            overlap=_env_bool("HOROVOD_OVERLAP"),
            overlap_buckets=_env_int("HOROVOD_OVERLAP_BUCKETS", 4),
            overlap_min_bytes=_env_int(
                "HOROVOD_OVERLAP_MIN_BYTES", 1 << 20
            ),
            moe_wire=_env_choice(
                "HOROVOD_MOE_WIRE",
                DEFAULT_MOE_WIRE,
                ("fp32", "bf16", "int8", "auto"),
            ),
            moe_intra_wire=_env_choice(
                "HOROVOD_MOE_INTRA_WIRE",
                DEFAULT_MOE_INTRA_WIRE,
                ("fp32", "bf16"),
            ),
            moe_wire_block=_env_int(
                "HOROVOD_MOE_WIRE_BLOCK", DEFAULT_MOE_WIRE_BLOCK
            ),
            moe_capacity_factor=_env_float(
                "HOROVOD_MOE_CAPACITY_FACTOR", DEFAULT_MOE_CAPACITY_FACTOR
            ),
            autotune=_env_bool("HOROVOD_AUTOTUNE"),
            autotune_log=env.get("HOROVOD_AUTOTUNE_LOG"),
            tuner_cache=env.get("HOROVOD_TUNER_CACHE") or None,
            autotune_warmup_samples=_env_int("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3),
            autotune_steps_per_sample=_env_int(
                "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10
            ),
            autotune_bayes_opt_max_samples=_env_int(
                "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20
            ),
            autotune_gaussian_process_noise=_env_float(
                "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8
            ),
            timeline=env.get("HOROVOD_TIMELINE"),
            timeline_mark_cycles=_env_bool("HOROVOD_TIMELINE_MARK_CYCLES"),
            telemetry_steps=_env_int("HOROVOD_TELEMETRY_STEPS", 256),
            flight_recorder=env.get("HOROVOD_FLIGHT_RECORDER") or None,
            metrics_port=_env_int("HOROVOD_METRICS_PORT", 0),
            straggler_factor=_env_float("HOROVOD_STRAGGLER_FACTOR", 3.0),
            trace=_env_bool("HOROVOD_TRACE"),
            trace_sample=_env_float("HOROVOD_TRACE_SAMPLE", 1.0),
            trace_spans=_env_int("HOROVOD_TRACE_SPANS", 2048),
            stall_check_disable=_env_bool("HOROVOD_STALL_CHECK_DISABLE"),
            stall_warning_seconds=_env_float(
                "HOROVOD_STALL_CHECK_TIME_SECONDS", DEFAULT_STALL_WARNING_SECONDS
            ),
            stall_shutdown_seconds=_env_float(
                "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", DEFAULT_STALL_SHUTDOWN_SECONDS
            ),
            retry_attempts=_env_int(
                "HOROVOD_RETRY_ATTEMPTS", DEFAULT_RETRY_ATTEMPTS
            ),
            retry_backoff_ms=_env_float(
                "HOROVOD_RETRY_BACKOFF_MS", DEFAULT_RETRY_BACKOFF_MS
            ),
            retry_backoff_max_ms=_env_float(
                "HOROVOD_RETRY_BACKOFF_MAX_MS", DEFAULT_RETRY_BACKOFF_MAX_MS
            ),
            retry_deadline_s=_env_float(
                "HOROVOD_RETRY_DEADLINE_S", DEFAULT_RETRY_DEADLINE_S
            ),
            retry_attempt_timeout_s=_env_float(
                "HOROVOD_RETRY_ATTEMPT_TIMEOUT_S",
                DEFAULT_RETRY_ATTEMPT_TIMEOUT_S,
            ),
            retry_circuit_threshold=_env_int(
                "HOROVOD_RETRY_CIRCUIT_THRESHOLD",
                DEFAULT_RETRY_CIRCUIT_THRESHOLD,
            ),
            retry_circuit_cooldown_s=_env_float(
                "HOROVOD_RETRY_CIRCUIT_COOLDOWN_S",
                DEFAULT_RETRY_CIRCUIT_COOLDOWN_S,
            ),
            fault_plan=env.get("HOROVOD_FAULT_PLAN") or None,
            straggler_quarantine_polls=_env_int(
                "HOROVOD_STRAGGLER_QUARANTINE_POLLS",
                DEFAULT_STRAGGLER_QUARANTINE_POLLS,
            ),
            guard=_env_bool("HOROVOD_GUARD"),
            guard_max_skips=_env_int(
                "HOROVOD_GUARD_MAX_SKIPS", DEFAULT_GUARD_MAX_SKIPS
            ),
            audit_steps=_env_int(
                "HOROVOD_AUDIT_STEPS", DEFAULT_AUDIT_STEPS
            ),
            sched_audit=_env_bool("HOROVOD_SCHED_AUDIT", True),
            serve_port=_env_int("HOROVOD_SERVE_PORT", DEFAULT_SERVE_PORT),
            serve_kv_slots=_env_int(
                "HOROVOD_SERVE_KV_SLOTS", DEFAULT_SERVE_KV_SLOTS
            ),
            serve_max_batch=_env_int(
                "HOROVOD_SERVE_MAX_BATCH", DEFAULT_SERVE_MAX_BATCH
            ),
            serve_max_tokens=_env_int(
                "HOROVOD_SERVE_MAX_TOKENS", DEFAULT_SERVE_MAX_TOKENS
            ),
            serve_deadline_ms=_env_float(
                "HOROVOD_SERVE_DEADLINE_MS", DEFAULT_SERVE_DEADLINE_MS
            ),
            serve_page_tokens=_env_int(
                "HOROVOD_SERVE_PAGE_TOKENS", DEFAULT_SERVE_PAGE_TOKENS
            ),
            serve_pages=_env_int(
                "HOROVOD_SERVE_PAGES", DEFAULT_SERVE_PAGES
            ),
            serve_prefix_cache=_env_bool(
                "HOROVOD_SERVE_PREFIX_CACHE", DEFAULT_SERVE_PREFIX_CACHE
            ),
            serve_page_watermark=_env_int(
                "HOROVOD_SERVE_PAGE_WATERMARK",
                DEFAULT_SERVE_PAGE_WATERMARK,
            ),
            serve_role=_env_choice(
                "HOROVOD_SERVE_ROLE", DEFAULT_SERVE_ROLE,
                ("unified", "prefill", "decode"),
            ),
            serve_kv_wire=_env_choice(
                "HOROVOD_SERVE_KV_WIRE", DEFAULT_SERVE_KV_WIRE,
                ("fp32", "bf16", "int8"),
            ),
            serve_transfer_port=_env_int(
                "HOROVOD_SERVE_TRANSFER_PORT",
                DEFAULT_SERVE_TRANSFER_PORT,
            ),
            serve_paged_attn=_env_choice(
                "HOROVOD_SERVE_PAGED_ATTN", DEFAULT_SERVE_PAGED_ATTN,
                ("auto", "on", "off"),
            ),
            serve_hedge_ms=_env_float(
                "HOROVOD_SERVE_HEDGE_MS", DEFAULT_SERVE_HEDGE_MS
            ),
            serve_drain_deadline_s=_env_float(
                "HOROVOD_SERVE_DRAIN_DEADLINE_S",
                DEFAULT_SERVE_DRAIN_DEADLINE_S,
            ),
            serve_dedupe_ttl_s=_env_float(
                "HOROVOD_SERVE_DEDUPE_TTL_S", DEFAULT_SERVE_DEDUPE_TTL_S
            ),
            log_level=env.get("HOROVOD_LOG_LEVEL", "warning").lower(),
            log_timestamp=_env_bool("HOROVOD_LOG_TIMESTAMP", True),
            rank=_env_int("HOROVOD_RANK", -1) if "HOROVOD_RANK" in env else None,
            size=_env_int("HOROVOD_SIZE", -1) if "HOROVOD_SIZE" in env else None,
            local_rank=(
                _env_int("HOROVOD_LOCAL_RANK", -1)
                if "HOROVOD_LOCAL_RANK" in env
                else None
            ),
            local_size=(
                _env_int("HOROVOD_LOCAL_SIZE", -1)
                if "HOROVOD_LOCAL_SIZE" in env
                else None
            ),
            cross_rank=(
                _env_int("HOROVOD_CROSS_RANK", -1)
                if "HOROVOD_CROSS_RANK" in env
                else None
            ),
            cross_size=(
                _env_int("HOROVOD_CROSS_SIZE", -1)
                if "HOROVOD_CROSS_SIZE" in env
                else None
            ),
            controller=env.get("HOROVOD_CONTROLLER", "tpu").lower(),
            cpu_operations=env.get("HOROVOD_CPU_OPERATIONS", "xla").lower(),
            rendezvous_addr=env.get("HOROVOD_GLOO_RENDEZVOUS_ADDR"),
            rendezvous_port=int(rendezvous_port) if rendezvous_port else None,
            gloo_timeout_seconds=_env_float("HOROVOD_GLOO_TIMEOUT_SECONDS", 30.0),
            coordinator_addr=env.get("HOROVOD_COORDINATOR_ADDR"),
            coordinator_port=(
                int(env["HOROVOD_COORDINATOR_PORT"])
                if env.get("HOROVOD_COORDINATOR_PORT")
                else None
            ),
            num_processes=(
                _env_int("HOROVOD_NUM_PROCESSES", -1)
                if "HOROVOD_NUM_PROCESSES" in env
                else None
            ),
            process_id=(
                _env_int("HOROVOD_PROCESS_ID", -1)
                if "HOROVOD_PROCESS_ID" in env
                else None
            ),
            secret_key_hex=env.get("HOROVOD_SECRET_KEY"),
            elastic_discovery_interval=_env_float(
                "HOROVOD_ELASTIC_DISCOVERY_INTERVAL",
                DEFAULT_ELASTIC_DISCOVERY_INTERVAL,
            ),
            exe_cache=env.get("HOROVOD_EXE_CACHE") or None,
            warm_standby=_env_int("HOROVOD_WARM_STANDBY", 0),
            mesh_shape=env.get("HOROVOD_TPU_MESH"),
            num_streams=_env_int("HOROVOD_NUM_STREAMS", 1),
        )
