"""Synthetic ResNet-50 throughput benchmark.

Parity with the reference's headline harness
(ref: examples/pytorch/pytorch_synthetic_benchmark.py [V]): synthetic
ImageNet-shaped batches, timed windows, prints img/sec per device and
total, plus the allreduce-efficiency figure the reference's scaling
tables are built from (docs/benchmarks.rst [V], BASELINE.md).

Run (TPU, the real measurement): python examples/synthetic_benchmark.py
Run (CPU smoke): BENCH_PLATFORM=cpu python examples/synthetic_benchmark.py \
    --model mnist --batch-size 8 --num-iters 2 --num-batches-per-iter 2
"""

import argparse
import os
import time
from functools import partial

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50",
                        choices=("resnet50", "mnist"))
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-warmup-batches", type=int, default=3)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    args = parser.parse_args()

    import jax

    # The sandbox's sitecustomize force-selects the TPU platform even
    # when JAX_PLATFORMS=cpu is in the env, so honor both env vars
    # explicitly via jax.config (like the sibling examples do) — this is
    # what keeps the example tests off the real chip.
    plat = os.environ.get("BENCH_PLATFORM") or os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()

    if args.model == "resnet50":
        from horovod_tpu.models import ResNet50

        model = ResNet50(dtype=jnp.bfloat16)
        sample = jnp.zeros((args.batch_size, 224, 224, 3), jnp.bfloat16)
    else:
        from horovod_tpu.models import MNISTConvNet

        model = MNISTConvNet()
        sample = jnp.zeros((args.batch_size, 28, 28, 1), jnp.float32)

    rngs = {"params": jax.random.PRNGKey(0)}
    if args.model == "mnist":
        rngs["dropout"] = jax.random.PRNGKey(1)
    variables = jax.jit(lambda: model.init(rngs, sample, train=False))()
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), op=hvd.Average
    )

    if "batch_stats" in variables:
        params, batch_stats = variables["params"], variables["batch_stats"]
    else:
        params, batch_stats = variables, None
    opt_state = opt.init(params)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    def train_step(params, batch_stats, opt_state, x, y):
        x, y = x[0], y[0]

        def loss_fn(p):
            if batch_stats is not None:
                logits, mut = model.apply(
                    {"params": p, "batch_stats": batch_stats},
                    x, train=True, mutable=["batch_stats"],
                )
                new_stats = mut["batch_stats"]
            else:
                logits = model.apply(
                    p, x, train=True,
                    rngs={"dropout": jax.random.PRNGKey(0)},
                )
                new_stats = None
            onehot = jax.nn.one_hot(y, logits.shape[-1])
            return (
                optax.softmax_cross_entropy(
                    logits.astype(jnp.float32), onehot
                ).mean(),
                new_stats,
            )

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if new_stats is None:
            new_stats = batch_stats
        return params, new_stats, opt_state, jax.lax.pmean(
            loss, hvd.WORLD_AXIS
        )

    step = jax.jit(train_step)
    rng = np.random.default_rng(0)
    shape = (world,) + sample.shape
    x = jnp.asarray(
        rng.uniform(size=shape).astype(np.float32), sample.dtype
    )
    y = jnp.asarray(rng.integers(0, 10, size=shape[:2]), jnp.int32)

    def run_batches(k):
        nonlocal params, batch_stats, opt_state
        loss = None
        for _ in range(k):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, x, y
            )
        # Host transfer, not block_until_ready: the loss chains through
        # every step's params, and a value dependency is the only sync
        # some PJRT tunnels honor (observed on axon; see _benchlib.sync).
        if loss is not None:
            float(np.asarray(loss).ravel()[0])

    run_batches(args.num_warmup_batches)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        run_batches(args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        img_sec = args.batch_size * args.num_batches_per_iter / dt
        if hvd.rank() == 0:
            print(f"Iter #{i}: {img_sec:.1f} img/sec per device")
        img_secs.append(img_sec)

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per device: {mean:.1f} +- {conf:.1f}")
        print(
            f"Total img/sec on {world} device(s): "
            f"{mean * world:.1f} +- {conf * world:.1f}"
        )


if __name__ == "__main__":
    main()
