#!/usr/bin/env bash
# Round-4 chip work, part h: captures that part g ran BEFORE the flash
# default flipped 128->512 (commit a651b5a landed mid-chain). These
# re-run the affected configs at the new default so the perf tables
# can show the block-512 column for every config, plus the b16-noremat
# best-config candidate at 512. Same discipline as part c/g.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

while pgrep -f "chipwork_r04g.sh" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce)?.py" >/dev/null 2>&1; do
  sleep 60
done

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}
wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}
run_one() {
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}
cap() {
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

# bert default config at the new block default (part g captured it at
# 128; part g's bert_noremat_b16 runs after the flip and covers the
# b16-noremat-512 cell already)
cap bert_blk512            env BENCH_MODEL=bert_large python bench_lm.py
# b16-noremat at 512 (part g's gpt2_noremat_b16 ran at 128)
cap gpt2_noremat_b16_blk512 env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py

echo "=== chipwork_r04h complete $(date -u +%H:%M)" >&2
