"""Elastic tests — the reference's model (SURVEY.md §4.2/§4.3):
driver logic in-process against fake scripted discovery; integration via
real localhost gangs with file-mutation membership changes and failing
workers."""

import os
import sys
import time
from typing import List

import numpy as np
import pytest

import horovod_tpu as hvd_mod
from horovod_tpu.elastic import (
    ElasticDriver,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
    JaxState,
    ObjectState,
)
from horovod_tpu.elastic.worker import notification_manager, run as elastic_run
from horovod_tpu.common.basics import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from horovod_tpu.runner.hosts import HostInfo


class FakeDiscovery(HostDiscovery):
    """Scripted host sequences — the reference's fake-discovery test
    pattern (test_elastic_driver.py [V])."""

    def __init__(self, hosts: List[HostInfo]):
        self.hosts = list(hosts)

    def find_available_hosts_and_slots(self):
        return list(self.hosts)


class TestDiscovery:
    def test_script_discovery(self, tmp_path):
        listing = tmp_path / "hosts.txt"
        listing.write_text("a:2\nb:2\n")
        disc = HostDiscoveryScript(f"cat {listing}")
        assert disc.find_available_hosts_and_slots() == [
            HostInfo("a", 2),
            HostInfo("b", 2),
        ]
        # membership driven by mutating the file — §4.3's mechanism
        listing.write_text("a:2\n")
        assert disc.find_available_hosts_and_slots() == [HostInfo("a", 2)]

    def test_script_failure_means_no_hosts(self):
        assert HostDiscoveryScript("exit 1").find_available_hosts_and_slots() == []

    def test_default_slots(self, tmp_path):
        listing = tmp_path / "hosts.txt"
        listing.write_text("a\n")
        disc = HostDiscoveryScript(f"cat {listing}", default_slots=4)
        assert disc.find_available_hosts_and_slots() == [HostInfo("a", 4)]

    def test_host_manager_blacklist(self):
        disc = FakeDiscovery([HostInfo("a", 2), HostInfo("b", 2)])
        mgr = HostManager(disc)
        assert mgr.refresh() is True
        assert [h.hostname for h in mgr.current_hosts()] == ["a", "b"]
        mgr.blacklist("a")
        assert mgr.is_blacklisted("a")
        assert [h.hostname for h in mgr.current_hosts()] == ["b"]
        # blacklisted host keeps being filtered on refresh
        mgr.refresh()
        assert [h.hostname for h in mgr.current_hosts()] == ["b"]

    def test_refresh_reports_change(self):
        disc = FakeDiscovery([HostInfo("a", 2)])
        mgr = HostManager(disc)
        assert mgr.refresh() is True
        assert mgr.refresh() is False
        disc.hosts.append(HostInfo("b", 2))
        assert mgr.refresh() is True


class TestAssignment:
    def _driver(self, disc, **kw):
        kw.setdefault("min_np", 1)
        return ElasticDriver(disc, ["true"], **kw)

    def test_below_min_np_is_none(self):
        d = self._driver(FakeDiscovery([HostInfo("a", 2)]), min_np=4)
        d.host_manager.refresh()
        assert d.compute_assignment() is None

    def test_max_np_clamps(self):
        d = self._driver(
            FakeDiscovery([HostInfo("a", 4), HostInfo("b", 4)]), max_np=6
        )
        d.host_manager.refresh()
        a = d.compute_assignment()
        assert a.world_size == 6
        # ranks dense, reference numbering
        assert [s.rank for s in a.slots] == list(range(6))

    def test_failure_then_reassignment(self):
        d = self._driver(FakeDiscovery([HostInfo("a", 2), HostInfo("b", 2)]))
        d.host_manager.refresh()
        assert d.compute_assignment().world_size == 4
        d.handle_host_failure("a")
        a = d.compute_assignment()
        assert a.world_size == 2
        assert a.hostnames == ["b"]

    def test_slots_per_host_override(self):
        d = self._driver(
            FakeDiscovery([HostInfo("a", 1)]), slots_per_host=4
        )
        d.host_manager.refresh()
        assert d.compute_assignment().world_size == 4


class TestState:
    def test_object_state_commit_restore(self):
        s = ObjectState(step=0, best=1.5)
        s.step = 10
        s.commit()
        s.step = 99
        s.restore()
        assert s.step == 10 and s.best == 1.5

    def test_object_state_initial_save(self):
        s = ObjectState(step=5)
        s.step = 7
        s.restore()  # never committed → back to construction values
        assert s.step == 5

    def test_jax_state_tree_commit_restore(self, hvd):
        import jax.numpy as jnp

        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros(4)}
        s = JaxState(params=params, step=0)
        s.params = {"w": jnp.full((4, 4), 2.0), "b": jnp.ones(4)}
        s.step = 3
        s.commit()
        s.params = {"w": jnp.full((4, 4), -1.0), "b": jnp.ones(4)}
        s.step = 8
        s.restore()
        assert s.step == 3
        np.testing.assert_allclose(np.asarray(s.params["w"]), 2.0)
        np.testing.assert_allclose(np.asarray(s.params["b"]), 1.0)

    def test_jax_state_sync_replicates(self, hvd):
        import jax
        import jax.numpy as jnp

        s = JaxState(params={"w": jnp.arange(8.0)})
        s.sync()
        leaf = s.params["w"]
        assert isinstance(leaf, jax.Array)
        assert leaf.sharding.is_fully_replicated
        np.testing.assert_allclose(np.asarray(leaf), np.arange(8.0))


class TestRunWrapper:
    def test_internal_error_restores_and_retries(self, hvd):
        calls = []

        class S(ObjectState):
            def sync(self):
                calls.append("sync")

        state = S(step=0)
        attempts = {"n": 0}

        @elastic_run
        def train(st):
            attempts["n"] += 1
            if attempts["n"] == 1:
                st.step = 50  # uncommitted progress, must be rolled back
                raise HorovodInternalError("peer died")
            return st.step

        assert train(state) == 0  # rolled back to initial commit
        assert attempts["n"] == 2
        assert calls == ["sync", "sync"]  # re-synced after restore

    def test_hosts_updated_keeps_state(self, hvd):
        state = ObjectState(step=0)
        attempts = {"n": 0}

        @elastic_run
        def train(st):
            attempts["n"] += 1
            if attempts["n"] == 1:
                st.step = 7
                raise HostsUpdatedInterrupt()
            return st.step

        assert train(state) == 7  # progress preserved on membership change
        assert attempts["n"] == 2

    def test_commit_raises_on_pending_update(self, hvd):
        state = ObjectState(step=0)
        notification_manager._updated.set()
        with pytest.raises(HostsUpdatedInterrupt):
            state.commit()
        # flag consumed
        state.commit()


class TestNotificationEndToEnd:
    def test_driver_notifies_worker_manager(self, monkeypatch):
        """Worker manager registers in the KV; driver pings it; the flag
        surfaces as HostsUpdatedInterrupt."""
        from horovod_tpu.elastic.worker import WorkerNotificationManager
        from horovod_tpu.runner.rendezvous import RendezvousServer
        from horovod_tpu.runner.service import BasicClient

        import horovod_tpu.runner.secret as secret_mod

        key = secret_mod.make_secret_key()
        server = RendezvousServer(secret_key=key)
        port = server.start()
        try:
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
            monkeypatch.setenv("HOROVOD_SECRET_KEY", key.hex())
            monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH", "0")
            monkeypatch.setenv("HOROVOD_PROCESS_ID", "0")
            monkeypatch.setenv("HOROVOD_HOSTNAME", "localhost")
            mgr = WorkerNotificationManager()
            mgr.init()
            try:
                addr = server.store.get("workers.0", "0")
                assert addr is not None
                host, _, sport = addr.decode().partition(":")
                out = BasicClient(host, int(sport), key).request(
                    {"type": "hosts_updated", "epoch": 0}
                )
                assert out["ok"] is True
                with pytest.raises(HostsUpdatedInterrupt):
                    mgr.raise_if_updated()
            finally:
                mgr.shutdown()
        finally:
            server.stop()


def _clean_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""
    return env


@pytest.mark.slow
class TestDriverIntegration:
    """Real localhost gangs (§4.3's chaos style, scaled to CI)."""

    def test_gang_success(self, monkeypatch):
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        d = ElasticDriver(
            FakeDiscovery([HostInfo("localhost", 2)]),
            [sys.executable, "-c", "import os; assert os.environ['HOROVOD_SIZE']=='2'"],
            min_np=2,
            discovery_interval=0.2,
        )
        try:
            d.host_manager.refresh()
            assert d.run() == 0
        finally:
            d.shutdown()

    def test_worker_failure_blacklists_and_exhausts(self, monkeypatch):
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        d = ElasticDriver(
            FakeDiscovery([HostInfo("localhost", 1)]),
            [sys.executable, "-c", "raise SystemExit(5)"],
            min_np=1,
            discovery_interval=0.1,
            start_timeout=0.5,
        )
        try:
            d.host_manager.refresh()
            rc = d.run()
            assert rc != 0
            assert d.host_manager.is_blacklisted("localhost")
        finally:
            d.shutdown()

    def test_membership_shrink_restarts_gang(self, monkeypatch, tmp_path):
        """World of 2 sleeps; discovery shrinks to 1; restarted world of
        1 exits 0 — the §3.4 restart-on-change path with a live gang."""
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['HOROVOD_SIZE'] == '1':\n"
            "    sys.exit(0)\n"
            "time.sleep(120)\n"
        )
        listing = tmp_path / "hosts.txt"
        listing.write_text("localhost:2\n")
        d = ElasticDriver(
            HostDiscoveryScript(f"cat {listing}"),
            [sys.executable, str(script)],
            min_np=1,
            discovery_interval=0.2,
        )
        try:
            d.host_manager.refresh()
            import threading

            result = {}
            t = threading.Thread(target=lambda: result.update(rc=d.run()))
            t.start()
            time.sleep(1.5)  # let epoch-0 gang come up
            listing.write_text("localhost:1\n")  # shrink membership
            t.join(timeout=60)
            assert not t.is_alive(), "driver did not converge"
            assert result["rc"] == 0
        finally:
            d.shutdown()

    def test_worker_sigkill_triggers_gang_restart(self, monkeypatch,
                                                  tmp_path):
        """§4.3's fault injection: SIGKILL a live worker PID mid-run;
        the driver must detect the dead gang, reset, relaunch, and the
        job must still complete (the reference's integration tests kill
        worker PIDs exactly like this [V])."""
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        flag = tmp_path / "second_epoch"
        script = tmp_path / "w.py"
        # epoch 0: sleep forever (to be killed); epoch 1+: exit 0
        script.write_text(
            "import os, sys, time, pathlib\n"
            f"flag = pathlib.Path({str(flag)!r})\n"
            "if int(os.environ.get('HOROVOD_ELASTIC_EPOCH', '0')) >= 1:\n"
            "    sys.exit(0)\n"
            "flag.write_text('up')\n"
            "time.sleep(120)\n"
        )
        # Two "hosts" (both local): the failed worker's host gets
        # blacklisted, the surviving host carries the epoch-1 gang —
        # the reference's kill-and-survive scenario shape [V].
        d = ElasticDriver(
            FakeDiscovery(
                [HostInfo("localhost", 1), HostInfo("127.0.0.1", 1)]
            ),
            [sys.executable, str(script)],
            min_np=1,
            discovery_interval=0.2,
        )
        try:
            d.host_manager.refresh()
            import signal as _signal
            import threading

            result = {}
            t = threading.Thread(target=lambda: result.update(rc=d.run()))
            t.start()
            deadline = time.monotonic() + 20
            while not flag.exists() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert flag.exists(), "epoch-0 worker never came up"
            with d._lock:
                procs = list(d._procs)
            assert procs
            procs[0].send_signal(_signal.SIGKILL)
            t.join(timeout=60)
            assert not t.is_alive(), "driver did not recover from SIGKILL"
            assert result["rc"] == 0  # epoch-1 relaunch exited clean
        finally:
            d.shutdown()


@pytest.mark.slow
class TestComposedElasticPath:
    """The composed elastic story as ONE scenario (VERDICT r5 item 6):
    the pieces — gang restart on SIGKILL, ZeRO-1 ``reshard_state``
    across a world change, ``DurableJaxState`` restore from the Orbax
    checkpoint — are individually tested elsewhere; this chains them
    the way a real preempted job experiences them (the reference's
    elastic integration tests tell the same end-to-end story,
    test/integration/test_elastic_torch.py [V])."""

    def test_sigkill_reshard_restore_chain(self, monkeypatch, tmp_path,
                                           hvd):
        import signal as _signal
        import threading

        import jax
        import jax.numpy as jnp
        import optax
        from functools import partial
        from jax.sharding import Mesh, PartitionSpec as P

        from horovod_tpu.checkpoint import DurableJaxState

        rng = np.random.default_rng(0)
        w0 = rng.normal(size=(5, 3)).astype(np.float32)
        params = {
            "w": jnp.asarray(w0),
            "b": jnp.zeros((3,), jnp.float32),
        }
        x = jnp.asarray(rng.normal(size=(8, 16, 5)), jnp.float32)
        y = jnp.asarray(
            np.einsum("wbi,io->wbo", np.asarray(x), w0), jnp.float32
        )

        def _loss(p, xb, yb):
            return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

        def make_step(opt, mesh):
            @partial(
                jax.shard_map, mesh=mesh,
                in_specs=(P(), opt.state_spec(),
                          P(hvd_mod.WORLD_AXIS), P(hvd_mod.WORLD_AXIS)),
                out_specs=(P(), opt.state_spec(), P()),
                check_vma=False,
            )
            def step(p, st, xb, yb):
                loss, g = jax.value_and_grad(_loss)(p, xb[0], yb[0])
                u, st = opt.update(g, st, p)
                return optax.apply_updates(p, u), st, jax.lax.pmean(
                    loss, hvd_mod.WORLD_AXIS
                )

            return jax.jit(step)

        # ---- phase A: epoch-0 training at world 8, durable commits
        ckdir = str(tmp_path / "ck")
        opt = hvd_mod.ShardedDistributedOptimizer(optax.adam(1e-2))
        ostate = opt.init(params)
        state = DurableJaxState(
            checkpoint_dir=ckdir, params=params, opt_state=ostate,
            step=0,
        )
        step8 = make_step(opt, hvd_mod.mesh())
        losses = []
        for i in range(3):
            state.params, state.opt_state, loss = step8(
                state.params, state.opt_state, x, y
            )
            state.step = i + 1
            losses.append(float(loss))
        state.commit()
        state.wait_until_finished()
        moments_before = [
            np.concatenate(
                [np.asarray(l).reshape(-1)]
            )
            for l in jax.tree_util.tree_leaves(
                jax.device_get(state.opt_state)
            )
        ]
        state.close()

        # ---- phase B: the gang dies (SIGKILL), membership shrinks to
        # 6 slots, the driver restarts; epoch-1 workers report their
        # world size — the size phase C reshards to
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        flag = tmp_path / "epoch0_up"
        size_file = tmp_path / "epoch1_size"
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time, pathlib\n"
            f"flag = pathlib.Path({str(flag)!r})\n"
            f"size_file = pathlib.Path({str(size_file)!r})\n"
            "if int(os.environ.get('HOROVOD_ELASTIC_EPOCH', '0')) >= 1:\n"
            "    if os.environ.get('HOROVOD_RANK') == '0':\n"
            "        size_file.write_text(os.environ['HOROVOD_SIZE'])\n"
            "    sys.exit(0)\n"
            "flag.write_text('up')\n"
            "time.sleep(120)\n"
        )
        d = ElasticDriver(
            FakeDiscovery(
                [HostInfo("127.0.0.1", 2), HostInfo("localhost", 6)]
            ),
            [sys.executable, str(script)],
            min_np=1,
            discovery_interval=0.2,
        )
        try:
            d.host_manager.refresh()
            result = {}
            t = threading.Thread(target=lambda: result.update(rc=d.run()))
            t.start()
            deadline = time.monotonic() + 20
            while not flag.exists() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert flag.exists(), "epoch-0 gang never came up"
            with d._lock:
                procs = list(d._procs)
            procs[0].send_signal(_signal.SIGKILL)
            t.join(timeout=60)
            assert not t.is_alive(), "driver did not recover"
            assert result["rc"] == 0
        finally:
            d.shutdown()
        new_world = int(size_file.read_text())
        assert new_world == 6  # the blacklisted host's 2 slots are gone

        # ---- phase C: the restarted job restores from the durable
        # checkpoint and reshards the ZeRO-1 state 8 -> new_world,
        # carrying the Adam moments exactly, then keeps learning
        fresh = DurableJaxState(
            checkpoint_dir=ckdir,
            params=jax.tree_util.tree_map(jnp.zeros_like, params),
            opt_state=jax.tree_util.tree_map(jnp.zeros_like, ostate),
            step=0,
        )
        assert fresh.resume_latest()
        assert fresh.step == 3
        r_params = jax.tree_util.tree_map(
            np.asarray, jax.device_get(fresh.params)
        )
        r_ostate = opt.reshard_state(
            jax.device_get(fresh.opt_state), r_params, new_world
        )
        fresh.close()
        moments_after = [
            np.asarray(l).reshape(-1)
            for l in jax.tree_util.tree_leaves(jax.device_get(r_ostate))
        ]
        # moment mass is carried exactly (reshard moves, never resets):
        # sharded leaves keep every nonzero entry, replicated scalars
        # (Adam's count) re-broadcast to the new world unchanged
        for b, a in zip(moments_before, moments_after):
            if np.unique(b).size == 1:
                assert np.unique(a).size == 1 and a.flat[0] == b.flat[0]
            else:
                np.testing.assert_allclose(
                    np.sort(b[np.abs(b) > 0]),
                    np.sort(a[np.abs(a) > 0]),
                    rtol=0, atol=0,
                )

        mesh6 = Mesh(
            np.asarray(jax.devices()[:new_world]),
            (hvd_mod.WORLD_AXIS,),
        )
        step6 = make_step(opt, mesh6)
        p6 = jax.tree_util.tree_map(jnp.asarray, r_params)
        s6 = jax.tree_util.tree_map(jnp.asarray, r_ostate)
        x6, y6 = x[:new_world], y[:new_world]
        for _ in range(5):
            p6, s6, loss = step6(p6, s6, x6, y6)
            losses.append(float(loss))
        assert losses[-1] < losses[2], losses  # still learning post-chain
