"""Stall detection: cycle-latency watchdog + cross-process heartbeats.

TPU-native rebuild of horovod/common/stall_inspector.cc/.h [V]
(SURVEY.md §2.1). The reference warns when some ranks have submitted a
tensor and others haven't for >60s. Under a single controller that
exact skew cannot happen, so this inspector watches the two signals
that CAN:

1. **Cycle-latency watchdog** (intra-process): an entry enqueued but
   never synchronized/flushed past the warning age — a leaked handle
   or a deadlocked consumer. This is the signal `check()` always has.
2. **Heartbeat staleness** (cross-process): in multi-process jobs
   (runner/elastic), worker processes PUT `heartbeat/<rank>` into the
   rendezvous KV on a timer (`runner.service.heartbeat` /
   `read_heartbeats`); the driver feeds those timestamps in via
   :meth:`record_heartbeat`, and `check()` warns when a rank goes
   silent past the warning age — the true analog of the reference's
   "some ranks are absent" report, rebuilt on the rendezvous channel
   the TPU runner actually has.
"""

from __future__ import annotations

import time
from typing import Dict

from .basics import HorovodInternalError
from .logging import get_logger

logger = get_logger("stall")


class StallInspector:
    def __init__(
        self, warning_seconds: float = 60.0, shutdown_seconds: float = 0.0
    ):
        self.warning_seconds = warning_seconds
        self.shutdown_seconds = shutdown_seconds
        self._pending: Dict[str, float] = {}
        self._warned: set = set()
        self._heartbeats: Dict[int, float] = {}
        self._hb_warned: set = set()

    def record_enqueue(self, name: str) -> None:
        self._pending.setdefault(name, time.monotonic())

    def record_complete(self, name: str) -> None:
        self._pending.pop(name, None)
        self._warned.discard(name)

    def reset_heartbeats(self) -> None:
        """Forget all liveness state — call when the worker set
        changes (gang restart): departed ranks must not read as
        stalled."""
        self._heartbeats.clear()
        self._hb_warned.clear()

    def record_heartbeat(self, rank: int, ts: float = None) -> None:
        """Feed a worker heartbeat (driver side of signal #2). ``ts`` is
        a unix epoch stamp (``time.time()`` — the domain
        ``runner.rendezvous.put_heartbeat`` writes, chosen because the
        stamps cross machines); defaults to now."""
        self._heartbeats[int(rank)] = (
            time.time() if ts is None else float(ts)
        )
        self._hb_warned.discard(int(rank))

    def stale_ranks(self, now: float = None):
        """Ranks whose last heartbeat is older than warning_seconds.
        ``now`` is unix epoch (heartbeats cross machines; monotonic
        clocks don't)."""
        if not self._heartbeats:
            return []
        now = time.time() if now is None else now
        return sorted(
            r
            for r, t in self._heartbeats.items()
            if now - t > self.warning_seconds
        )

    def check(self) -> None:
        """Called once per fusion cycle (the reference checks once per
        background-loop cycle, stall_inspector.cc::CheckForStalledTensors
        [V])."""
        now = time.monotonic()
        for name, t in list(self._pending.items()):
            age = now - t
            if (
                self.shutdown_seconds > 0
                and age > self.shutdown_seconds
            ):
                raise HorovodInternalError(
                    f"collective '{name}' stalled for {age:.0f}s "
                    f"(> HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)"
                )
            if age > self.warning_seconds and name not in self._warned:
                self._warned.add(name)
                logger.warning(
                    "One or more collectives submitted but not completed "
                    "for %.0fs: %s. A consumer may be stalled.",
                    age,
                    name,
                )
        wall = time.time()  # heartbeats live in the epoch domain
        for rank in self.stale_ranks(wall):
            age = wall - self._heartbeats[rank]
            # Shutdown escalation re-checks EVERY cycle (like the
            # pending-entry path) — it must fire even after the
            # one-time warning already did.
            if (
                self.shutdown_seconds > 0
                and age > self.shutdown_seconds
            ):
                raise HorovodInternalError(
                    f"rank {rank} heartbeat silent for {age:.0f}s "
                    f"(> HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)"
                )
            if rank not in self._hb_warned:
                self._hb_warned.add(rank)
                logger.warning(
                    "Rank %d has not heartbeat for %.0fs; the worker "
                    "may be stalled or partitioned.",
                    rank,
                    age,
                )
