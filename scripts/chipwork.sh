#!/usr/bin/env bash
# chipwork.sh — the ONE parameterized unattended chip-capture runner.
#
# Replaces the 16 copy-paste chipwork_r04*/r05*.sh one-offs: every
# round shared the same skeleton (wait for earlier rounds to drain,
# probe the backend until it answers, capture each roster entry with
# skip-if-done + one health-gated retry, extract the JSON line into
# bench_results/) and differed only in the round tag, the wait regex,
# and the capture roster. Those are now parameters; the discipline
# (docs/benchmarks.md) lives in exactly one place.
#
# Usage:
#   scripts/chipwork.sh -r <round> [-w <wait-regex>] [-P] <manifest>
#
#   -r <round>       artifact suffix: bench_results/<name>_<round>.json
#   -w <wait-regex>  pgrep -f pattern to wait on before starting
#                    (earlier rounds / stray bench processes); pass ""
#                    to start immediately. Default: any chipwork/bench
#                    python process that is not this script.
#   -P               skip the initial backend probe (captures still
#                    health-gate their retry).
#   <manifest>       file of capture lines, or "-" for stdin:
#                      <name> <command...>
#                    '#' comments and blank lines ignored. Commands
#                    run from the repo root; env assignments work
#                    (lines are executed with `env`).
#
# Example (what chipwork_r04k.sh used to be):
#   scripts/chipwork.sh -r r04 - <<'EOF'
#   vit_b16_flash BENCH_INNER=1 BENCH_MODEL=vit_b16 python bench.py
#   vit_b16_dense BENCH_INNER=1 BENCH_MODEL=vit_b16 BENCH_VIT_FLASHPAD=0 python bench.py
#   EOF
#
# Discipline (unchanged from the one-offs):
#   * ONE TPU process at a time; a scripts/CHIP_HOLD file pauses
#     captures while a dev session runs the pytest suite (host load
#     confounds captures).
#   * skip-if-done: a non-empty artifact short-circuits the entry, so
#     a re-run after an outage resumes where it died.
#   * probe_backend: an untimed claim attempt (a failed claim reports
#     UNAVAILABLE on its own after ~25 min — that IS the backoff); the
#     2h timeout is only a safety net against a half-dead backend.
#   * one retry per entry, gated on backend health, so one mid-run
#     backend drop cannot burn the rest of the unattended roster.

set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

R=""
WAIT_RE='chipwork_r|python bench(_lm|_allreduce|_fusion|_int8|_seq|_overlap|_zero|_hier|_moe|_serve)?\.py'
PROBE=1
while getopts "r:w:P" opt; do
  case "$opt" in
    r) R="$OPTARG" ;;
    w) WAIT_RE="$OPTARG" ;;
    P) PROBE=0 ;;
    *) echo "usage: $0 -r <round> [-w <wait-regex>] [-P] <manifest>" >&2; exit 2 ;;
  esac
done
shift $((OPTIND - 1))
MANIFEST="${1:-}"
[ -n "$R" ] || { echo "chipwork: -r <round> is required" >&2; exit 2; }
[ -n "$MANIFEST" ] || { echo "chipwork: manifest file (or -) required" >&2; exit 2; }

echo "=== chipwork $R start $(date -u +%F' '%H:%M)" >&2

if [ -n "$WAIT_RE" ]; then
  while pgrep -f "$WAIT_RE" | grep -qv "^$$\$"; do
    echo "waiting for earlier chip work to drain..." >&2
    sleep 120
  done
fi

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}

wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}

hold_gate() {
  while [ -e scripts/CHIP_HOLD ]; do sleep 60; done
}

run_one() {  # run_one <name> <cmd...>
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  env "$@" > "bench_results/${name}_${R}.txt" \
          2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "bench_results/${name}_${R}.txt"; then
    grep -E '^\{' "bench_results/${name}_${R}.txt" > "$out"
    rm -f "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  return 1
}

cap() {  # cap <name> <cmd...>
  local name="$1"
  if [ -s "bench_results/${name}_${R}.json" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

[ "$PROBE" = 1 ] && wait_backend

failures=0
while IFS= read -r line; do
  case "$line" in ''|'#'*) continue ;; esac
  # shellcheck disable=SC2086 — word-splitting the manifest line is
  # the interface (env assignments + command)
  set -- $line
  cap "$@" || failures=$((failures + 1))
done < <(cat -- "$MANIFEST")

echo "=== chipwork $R complete $(date -u +%F' '%H:%M) (failures: $failures)" >&2
exit $((failures > 0))
