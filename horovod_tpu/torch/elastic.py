"""Elastic state for the torch shim: ``TorchState``.

Parity target: ``horovod.torch.elastic.state.TorchState`` [V]
(SURVEY.md §2.5 "Elastic worker API") — wrap a torch module +
optimizer (+ scalars like epoch/batch) so elastic training can
``commit()`` (host snapshot), ``restore()`` (roll back to the last
commit after a failure), and ``sync()`` (broadcast from the new rank 0
after a membership change). Reuses the shim's
``broadcast_parameters`` / ``broadcast_optimizer_state`` /
``broadcast_object`` for the sync leg and the base ``ObjectState``
machinery for scalar attributes; use with ``hvd.elastic.run`` exactly
like ``JaxState``.
"""

from __future__ import annotations

import copy
from typing import Any

from ..elastic.state import ObjectState, State  # noqa: F401 — re-export
from ..elastic.worker import run  # noqa: F401 — hvd.torch.elastic.run


class TorchState(ObjectState):
    """Commit/restore/sync over a torch model + optimizer
    (ref: horovod/torch/elastic/state.py TorchState [V])."""

    def __init__(self, model=None, optimizer=None, **kwargs: Any) -> None:
        self.model = model
        self.optimizer = optimizer
        self._saved_model_state: Any = None
        self._saved_optimizer_state: Any = None
        super().__init__(**kwargs)
        self.save()

    @staticmethod
    def _clone_state_dict(sd):
        import torch

        def clone(v):
            if isinstance(v, torch.Tensor):
                return v.detach().clone()
            if isinstance(v, dict):
                return {k: clone(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return type(v)(clone(x) for x in v)
            return copy.deepcopy(v)

        return clone(sd)

    def save(self) -> None:
        if self.model is not None:
            self._saved_model_state = self._clone_state_dict(
                self.model.state_dict()
            )
        if self.optimizer is not None:
            self._saved_optimizer_state = self._clone_state_dict(
                self.optimizer.state_dict()
            )
        super().save()

    def restore(self) -> None:
        # load_state_dict copies (params via copy_, optimizer via its
        # own deepcopy), so the snapshots can be passed directly
        if self.model is not None and self._saved_model_state is not None:
            self.model.load_state_dict(self._saved_model_state)
        if (
            self.optimizer is not None
            and self._saved_optimizer_state is not None
        ):
            self.optimizer.load_state_dict(self._saved_optimizer_state)
        super().restore()

    def sync(self) -> None:
        from . import broadcast_optimizer_state, broadcast_parameters

        if self.model is not None:
            broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            broadcast_optimizer_state(self.optimizer, root_rank=0)
        super().sync()  # scalar attributes via broadcast_object
        self.save()


class ElasticSampler:
    """Distributed sampler that supports mid-epoch membership changes
    (ref: horovod/torch/elastic/sampler.py ElasticSampler [V]).

    Contract (same as the reference): iterate your rank's shard;
    ``record_batch`` after each step marks those samples processed; on a
    host change call ``sampler.sync()`` — it UNIONS every rank's
    processed set (allgather, the reference's sampler state handler
    semantics) and re-shards the remainder over the new world, so no
    sample is dropped or repeated within the epoch. NOTE:
    ``TorchState.sync`` alone is NOT enough — its broadcast would
    overwrite survivors' progress with rank 0's; call the sampler's own
    ``sync()`` after it. ``state_dict``/``load_state_dict`` ride an
    elastic State object so commits capture progress; ``set_epoch``
    reshuffles and clears the processed set.

    Duck-typed to torch's Sampler protocol (``__iter__``/``__len__``) —
    usable as ``DataLoader(..., sampler=ElasticSampler(ds))`` without
    importing torch here.
    """

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0,
                 num_replicas=None, rank=None):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set = set()
        # explicit overrides pin the identity (tests / manual sharding);
        # None = re-read from the runtime on every reset (the elastic
        # membership-change behavior)
        self._fixed_replicas = num_replicas
        self._fixed_rank = rank
        self.reset()

    # -- lifecycle -----------------------------------------------------
    def reset(self) -> None:
        """Re-shard the unprocessed remainder over the CURRENT world
        (rank/size re-read — this is the membership-change hook)."""
        from ..common import basics
        import numpy as np

        self.num_replicas = (
            self._fixed_replicas
            if self._fixed_replicas is not None
            else basics.size()
        )
        self.rank = (
            self._fixed_rank if self._fixed_rank is not None else basics.rank()
        )
        n = len(self.dataset)
        remaining = np.array(
            sorted(set(range(n)) - self.processed_indices), dtype=np.int64
        )
        if self.shuffle and len(remaining):
            rng = np.random.default_rng((self.seed, self.epoch))
            remaining = remaining[rng.permutation(len(remaining))]
        # equal shards via wrap-around padding (SPMD step-count parity,
        # same discipline as data.ShardedIndexSampler)
        per = -(-len(remaining) // self.num_replicas) if len(remaining) else 0
        total = per * self.num_replicas
        if total > len(remaining) and len(remaining):
            remaining = np.resize(remaining, total)
        self.indices = remaining[self.rank :: self.num_replicas].tolist()
        self.num_samples = len(self.indices)

    def sync(self) -> None:
        """Union every rank's processed set, then re-shard the
        remainder over the CURRENT world — the membership-change hook
        (ref: the sampler state-sync handler unions processed indices
        across workers [V]; a plain broadcast would drop the progress
        of every rank but the root)."""
        from . import allgather_object

        for other in allgather_object(sorted(self.processed_indices)):
            self.processed_indices.update(int(i) for i in other)
        self.reset()

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark the samples of batch ``batch_idx`` (into this rank's
        current index list) as processed."""
        sl = self.indices[
            batch_idx * batch_size : (batch_idx + 1) * batch_size
        ]
        self.processed_indices.update(int(i) for i in sl)

    # -- elastic State integration ------------------------------------
    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "processed_indices": sorted(self.processed_indices),
        }

    def load_state_dict(self, sd: dict) -> None:
        self.epoch = int(sd["epoch"])
        self.processed_indices = set(sd["processed_indices"])
        self.reset()

    # -- sampler protocol ---------------------------------------------
    def __iter__(self):
        return iter(self.indices)

    def __len__(self) -> int:
        return self.num_samples
