"""Declarative invariant rules over :class:`~.hlo_parse.ProgramGraph`.

Each rule is a small object with a ``name`` and a ``check(subject)``
returning a list of :class:`Finding` (empty = the invariant holds).
Program rules take a ProgramGraph; :class:`CompileBudget` takes a
runtime counter mapping (engine/cache stats) — the roster runner
(``scripts/hlo_audit.py``) pairs each rule with its subject, and tests
use :func:`expect` as the one-line assertion form.

The catalog (docs/analysis.md has the prose version):

* :class:`CollectiveCount` — exactly N collectives of a kind (the
  "N buckets -> N collectives, no hidden exchange" family).
* :class:`NoInterCollectiveDefUse` — no collective's operands reach
  another's result: independence = overlappable (PR 3's contract).
* :class:`ReplicaGroupStructure` — group-limited vs world-spanning
  routing (the two-level wire's "no monolithic exchange" gates).
* :class:`WireDtype` — int8 payloads permitted on the inter-hop
  groups only, never intra (EQuARX placement, PR 10/12).
* :class:`DonationCoverage` — every declared carry is donated
  (``jax.buffer_donor`` / ``tf.aliasing_output``), so steady-state
  serving and fused dispatch never double-buffer.
* :class:`GuardOverhead` — guard on == guard off collective counts
  (+ optionally exactly one extra SCALAR all_reduce: the sharded
  agreement flag, PR 7).
* :class:`CompileBudget` — expected executable counts per cache
  (``decode_compiles == 1`` and friends).
* :class:`TransientBuffer` — a tensor shape prefix must be absent
  (kernel-path paged attention deletes the gather view) or present
  (the gather baseline — matcher falsifiability).
"""

from __future__ import annotations

import dataclasses
from typing import List, Mapping, Optional, Sequence, Tuple, Union

from .hlo_parse import COLLECTIVE_KINDS, Collective, ProgramGraph

Groups = Tuple[Tuple[int, ...], ...]


@dataclasses.dataclass
class Finding:
    """One violated invariant: which rule, what happened, where."""

    rule: str
    message: str
    snippet: str = ""
    line_no: Optional[int] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        loc = f" (line {self.line_no + 1})" if self.line_no is not None else ""
        tail = f"\n    {self.snippet}" if self.snippet else ""
        return f"[{self.rule}] {self.message}{loc}{tail}"


@dataclasses.dataclass
class Report:
    """The result of running a rule set: findings + per-rule status."""

    findings: List[Finding] = dataclasses.field(default_factory=list)
    checked: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "rules_checked": list(self.checked),
            "violations": [f.to_dict() for f in self.findings],
        }


def _norm_groups(groups) -> Groups:
    return tuple(tuple(int(r) for r in g) for g in groups)


class Rule:
    """Base: subclasses define ``check(subject) -> List[Finding]``."""

    @property
    def name(self) -> str:
        return type(self).__name__

    def check(self, subject) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def _finding(self, message: str, coll: Optional[Collective] = None) -> Finding:
        return Finding(
            rule=self.name,
            message=message,
            snippet=coll.snippet if coll is not None else "",
            line_no=coll.line_no if coll is not None else None,
        )


class CollectiveCount(Rule):
    """Exactly ``expect`` collectives of ``kind`` (int, or a
    ``(min, max)`` inclusive range)."""

    def __init__(self, kind: str, expect: Union[int, Tuple[int, int]]):
        if kind not in COLLECTIVE_KINDS:
            raise ValueError(f"unknown kind {kind!r}")
        self.kind = kind
        self.expect = expect

    @property
    def name(self) -> str:
        return f"CollectiveCount[{self.kind}=={self.expect}]"

    def check(self, graph: ProgramGraph) -> List[Finding]:
        n = graph.count(self.kind)
        if isinstance(self.expect, tuple):
            lo, hi = self.expect
            if lo <= n <= hi:
                return []
            want = f"in [{lo}, {hi}]"
        else:
            if n == int(self.expect):
                return []
            want = f"== {self.expect}"
        colls = graph.collectives(self.kind)
        return [
            self._finding(
                f"module carries {n} {self.kind} op(s), expected {want}",
                colls[0] if colls else None,
            )
        ]


class NoInterCollectiveDefUse(Rule):
    """No collective of ``kind`` may transitively depend on another's
    result — independence is what makes buckets overlappable."""

    def __init__(self, kind: Optional[str] = None):
        self.kind = kind

    @property
    def name(self) -> str:
        return f"NoInterCollectiveDefUse[{self.kind or 'any'}]"

    def check(self, graph: ProgramGraph) -> List[Finding]:
        out = []
        for dep, on in graph.dependent_pairs(self.kind):
            out.append(
                self._finding(
                    f"{dep.kind} {dep.sid} depends on {on.kind} {on.sid}: "
                    "collectives serialized (bucket independence broken)",
                    dep,
                )
            )
        return out


class ReplicaGroupStructure(Rule):
    """Routing structure of ``kind``:

    * ``groups=`` — every matching collective must carry exactly these
      replica groups.
    * ``groups_any_of=`` — every matching collective must carry ONE of
      these group sets (e.g. intra OR inter on a two-level wire).
    * ``forbid_world_spanning=True`` — no matching collective may have
      a group covering all ``world`` ranks (the "no monolithic
      exchange over DCN" gate).
    * ``require_present=True`` — at least one matching collective must
      exist (a vacuous pass is itself a violation: the program was
      expected to carry this exchange).
    """

    def __init__(
        self,
        kind: str,
        groups: Optional[Sequence[Sequence[int]]] = None,
        groups_any_of: Optional[Sequence[Sequence[Sequence[int]]]] = None,
        forbid_world_spanning: bool = False,
        world: Optional[int] = None,
        require_present: bool = False,
    ):
        if kind not in COLLECTIVE_KINDS:
            raise ValueError(f"unknown kind {kind!r}")
        self.kind = kind
        self.groups = _norm_groups(groups) if groups is not None else None
        self.groups_any_of = (
            tuple(_norm_groups(g) for g in groups_any_of)
            if groups_any_of is not None
            else None
        )
        self.forbid_world_spanning = forbid_world_spanning
        self.world = world
        self.require_present = require_present

    @property
    def name(self) -> str:
        return f"ReplicaGroupStructure[{self.kind}]"

    def check(self, graph: ProgramGraph) -> List[Finding]:
        out: List[Finding] = []
        colls = graph.collectives(self.kind)
        if self.require_present and not colls:
            out.append(
                self._finding(
                    f"expected at least one {self.kind} op, module has none"
                )
            )
        world = self.world or graph.num_partitions
        for c in colls:
            if self.groups is not None and _norm_groups(c.replica_groups) != self.groups:
                out.append(
                    self._finding(
                        f"{c.kind} {c.sid} routes over groups "
                        f"{c.replica_groups}, expected {self.groups}",
                        c,
                    )
                )
            if (
                self.groups_any_of is not None
                and _norm_groups(c.replica_groups) not in self.groups_any_of
            ):
                out.append(
                    self._finding(
                        f"{c.kind} {c.sid} routes over groups "
                        f"{c.replica_groups}, expected one of "
                        f"{list(self.groups_any_of)}",
                        c,
                    )
                )
            if self.forbid_world_spanning and c.spans(world):
                out.append(
                    self._finding(
                        f"{c.kind} {c.sid} spans the whole world "
                        f"(group of {max(c.group_sizes or (0,))} ranks, "
                        f"world {world}) — expected group-limited routing",
                        c,
                    )
                )
        return out


class WireDtype(Rule):
    """int8 wire placement: an i8-payload collective is permitted only
    when its replica groups are the INTER-hop groups; i8 on the intra
    groups (or spanning the world, when a hierarchy is declared) is the
    violation this rule exists to catch. ``int8_allowed=False`` forbids
    i8 payloads entirely (the fp32-roster programs)."""

    INT8 = ("i8", "ui8")

    def __init__(
        self,
        inter_groups: Optional[Sequence[Sequence[int]]] = None,
        intra_groups: Optional[Sequence[Sequence[int]]] = None,
        int8_allowed: bool = True,
    ):
        self.inter_groups = (
            _norm_groups(inter_groups) if inter_groups is not None else None
        )
        self.intra_groups = (
            _norm_groups(intra_groups) if intra_groups is not None else None
        )
        self.int8_allowed = int8_allowed

    def _moves_int8(self, c: Collective) -> bool:
        return any(t.dtype in self.INT8 for t in c.operand_types)

    def check(self, graph: ProgramGraph) -> List[Finding]:
        out: List[Finding] = []
        for c in graph.collectives():
            if not self._moves_int8(c):
                continue
            if not self.int8_allowed:
                out.append(
                    self._finding(
                        f"{c.kind} {c.sid} moves int8 payload on a program "
                        "whose wire contract is full-width",
                        c,
                    )
                )
                continue
            groups = _norm_groups(c.replica_groups)
            if self.intra_groups is not None and groups == self.intra_groups:
                out.append(
                    self._finding(
                        f"{c.kind} {c.sid} moves int8 over the INTRA hop "
                        f"{c.replica_groups} — int8 is licensed for the "
                        "inter (DCN) hop only",
                        c,
                    )
                )
            elif self.inter_groups is not None and groups != self.inter_groups:
                out.append(
                    self._finding(
                        f"{c.kind} {c.sid} moves int8 over groups "
                        f"{c.replica_groups}, which are not the declared "
                        f"inter-hop groups {self.inter_groups}",
                        c,
                    )
                )
        return out


class DonationCoverage(Rule):
    """Donation coverage of the entry function: the args named by
    ``arg_indices`` (or at least ``min_donated`` of all args) must be
    donated (``jax.buffer_donor``) or alias-pinned
    (``tf.aliasing_output``). The serving/fused-dispatch carry
    contract: an undonated carry double-buffers every step."""

    def __init__(
        self,
        arg_indices: Optional[Sequence[int]] = None,
        min_donated: Optional[int] = None,
        func: Optional[str] = None,
    ):
        if arg_indices is None and min_donated is None:
            raise ValueError("pass arg_indices= or min_donated=")
        self.arg_indices = tuple(arg_indices) if arg_indices is not None else None
        self.min_donated = min_donated
        self.func = func

    def check(self, graph: ProgramGraph) -> List[Finding]:
        args = graph.args(self.func)
        donated = {a.index for a in args if a.donated or a.aliased_output is not None}
        out: List[Finding] = []
        if self.arg_indices is not None:
            for idx in self.arg_indices:
                if idx not in donated:
                    ty = args[idx].type if idx < len(args) else None
                    out.append(
                        self._finding(
                            f"entry arg #{idx}"
                            + (f" ({ty})" if ty else "")
                            + " is not donated — the carry double-buffers"
                        )
                    )
        if self.min_donated is not None and len(donated) < self.min_donated:
            out.append(
                self._finding(
                    f"only {len(donated)} of {len(args)} entry args are "
                    f"donated; expected >= {self.min_donated}"
                )
            )
        return out


class GuardOverhead(Rule):
    """The PR 7 grad-guard contract, as a two-program rule: construct
    with the guard-OFF baseline graph, check the guard-ON graph. Every
    collective count must match the baseline exactly, except
    ``extra_scalar_allreduces`` additional all_reduce ops which must
    each be SCALAR (the 4-byte agreement flag) — a shaped extra
    all_reduce is a hidden full-gradient exchange."""

    def __init__(self, baseline: ProgramGraph, extra_scalar_allreduces: int = 0):
        self.baseline = baseline
        self.extra = int(extra_scalar_allreduces)

    def check(self, graph: ProgramGraph) -> List[Finding]:
        out: List[Finding] = []
        base = self.baseline.counts()
        got = graph.counts()
        for kind in COLLECTIVE_KINDS:
            want = base[kind] + (self.extra if kind == "all_reduce" else 0)
            if got[kind] != want:
                colls = graph.collectives(kind)
                out.append(
                    self._finding(
                        f"guard-on module carries {got[kind]} {kind} op(s), "
                        f"guard-off baseline implies {want}",
                        colls[0] if colls else None,
                    )
                )
        if self.extra and not out:
            # the extra all_reduces must be the scalar agreement flags:
            # identify them as the ops absent from the baseline's
            # multiset of operand shapes
            base_shapes = [
                tuple(t.shape for t in c.operand_types)
                for c in self.baseline.collectives("all_reduce")
            ]
            extras = []
            for c in graph.collectives("all_reduce"):
                shapes = tuple(t.shape for t in c.operand_types)
                if shapes in base_shapes:
                    base_shapes.remove(shapes)
                else:
                    extras.append(c)
            for c in extras:
                if not c.is_scalar():
                    out.append(
                        self._finding(
                            f"extra all_reduce {c.sid} carries a SHAPED "
                            f"operand {c.operand_types} — the agreement "
                            "flag must be scalar",
                            c,
                        )
                    )
        return out


class TransientBuffer(Rule):
    """Presence/absence of a tensor shape in the lowered module: the
    paged-attention memory-plane gate. ``forbid=True`` (the default)
    asserts NO tensor whose leading dims match ``shape_prefix`` exists
    anywhere in the program — e.g. ``(slots, max_len)`` catches the
    transient contiguous ``[slots, max_len, kvh, hd]`` gather view the
    fused kernel is supposed to delete. ``forbid=False`` is the
    falsifiability twin: the gather-path program MUST still carry it,
    proving the matcher actually detects the buffer it bans."""

    def __init__(self, shape_prefix: Sequence[int], forbid: bool = True):
        self.shape_prefix = tuple(int(d) for d in shape_prefix)
        self.forbid = bool(forbid)

    @property
    def name(self) -> str:
        dims = "x".join(str(d) for d in self.shape_prefix)
        mode = "absent" if self.forbid else "present"
        return f"TransientBuffer[{dims}* {mode}]"

    def check(self, graph: ProgramGraph) -> List[Finding]:
        needle = "tensor<" + "".join(f"{d}x" for d in self.shape_prefix)
        line_no = None
        for i, line in enumerate(graph.text.splitlines()):
            if needle in line:
                line_no = i
                break
        if self.forbid and line_no is not None:
            return [
                Finding(
                    rule=self.name,
                    message=(
                        f"module materializes a {needle}...> buffer — "
                        "the transient gather view the kernel path must "
                        "not carry"
                    ),
                    line_no=line_no,
                )
            ]
        if not self.forbid and line_no is None:
            return [
                Finding(
                    rule=self.name,
                    message=(
                        f"module carries no {needle}...> buffer — the "
                        "gather-path baseline should materialize the "
                        "view (matcher falsifiability check)"
                    ),
                )
            ]
        return []


class CompileBudget(Rule):
    """Runtime counter rule: each key of ``expected`` must equal (or,
    as ``(min, max)``, fall within) the subject mapping's value. The
    ``decode_compiles == 1`` / exact-executable-count acceptance gates,
    shared between the roster runner and tests."""

    def __init__(self, **expected):
        self.expected = expected

    def check(self, stats: Mapping[str, float]) -> List[Finding]:
        out: List[Finding] = []
        for key, want in self.expected.items():
            got = stats.get(key)
            if got is None:
                out.append(self._finding(f"counter {key!r} absent from stats"))
            elif isinstance(want, tuple):
                lo, hi = want
                if not (lo <= got <= hi):
                    out.append(
                        self._finding(
                            f"counter {key} == {got}, expected in [{lo}, {hi}]"
                        )
                    )
            elif got != want:
                out.append(
                    self._finding(f"counter {key} == {got}, expected {want}")
                )
        return out


def run_rules(pairs: Sequence[Tuple[Rule, object]]) -> Report:
    """Evaluate (rule, subject) pairs into one Report."""
    report = Report()
    for rule, subject in pairs:
        report.checked.append(rule.name)
        report.findings.extend(rule.check(subject))
    return report


def check_program(graph: ProgramGraph, rules: Sequence[Rule]) -> Report:
    """Evaluate a rule list against one program."""
    return run_rules([(r, graph) for r in rules])


def expect(graph: ProgramGraph, *rules: Rule) -> None:
    """Test-facing assertion: raise AssertionError listing every
    violated invariant (with HLO snippets)."""
    report = check_program(graph, list(rules))
    if not report.ok:
        raise AssertionError(
            "lowered-program invariant(s) violated:\n"
            + "\n".join(str(f) for f in report.findings)
        )
